/* LD_PRELOAD filesystem interposer.
 *
 * The reference intercepts filesystem ops with a FUSE passthrough
 * (hookfs); this environment has no libfuse headers, and FUSE needs a
 * privileged mount anyway. An LD_PRELOAD interposer achieves the same
 * capability — defer + fault-inject the testee's filesystem ops — with no
 * mount and no privileges: preload this library into the testee, set
 * NMZ_TPU_FS_ROOT to the watched subtree, and every hooked libc call under
 * that subtree becomes a deferred FilesystemEvent through the guest-agent
 * protocol (nmz_agent.cc). A FilesystemFaultAction makes the call fail
 * with EIO before touching the real filesystem (pre-hooks), matching the
 * reference's hook split (fs.go:77-183).
 *
 * Hooked: mkdir, rmdir, fsync, unlink, open/open64/creat (write modes
 * pre-hooked; read-only opens post-hooked).
 */
#define _GNU_SOURCE 1
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include "../agent/nmz_agent.h"

namespace {

const char* fs_root() {
  static const char* root = getenv("NMZ_TPU_FS_ROOT");
  return root;
}

bool watched(const char* path) {
  const char* root = fs_root();
  if (root == nullptr || root[0] == '\0' || path == nullptr) return false;
  size_t n = strlen(root);
  return strncmp(path, root, n) == 0 &&
         (path[n] == '\0' || path[n] == '/' || root[n - 1] == '/');
}

/* Returns 1 when the op must fail with EIO.
 *
 * The reported path is RELATIVE to NMZ_TPU_FS_ROOT (leading '/'): the
 * watched root is typically a per-run working dir, and a schedule
 * searched on one run must key the same operation in the next run --
 * absolute paths would put every run's events in disjoint replay-hint
 * buckets and make delay tables untransferable. */
int hook(const char* op, const char* path) {
  if (!watched(path)) return 0;
  const char* root = fs_root();
  size_t n = strlen(root);
  if (n > 0 && root[n - 1] == '/') n--;
  const char* rel = path + n;
  if (rel[0] == '\0') rel = "/";
  int r = nmz_agent_fs_event(op, rel);
  return r == 1 ? 1 : 0;
}

template <typename Fn>
Fn real(const char* name) {
  return reinterpret_cast<Fn>(dlsym(RTLD_NEXT, name));
}

}  // namespace

extern "C" {

int mkdir(const char* path, mode_t mode) {
  static auto fn = real<int (*)(const char*, mode_t)>("mkdir");
  if (hook("pre-mkdir", path)) {
    errno = EIO;
    return -1;
  }
  return fn(path, mode);
}

int rmdir(const char* path) {
  static auto fn = real<int (*)(const char*)>("rmdir");
  if (hook("pre-rmdir", path)) {
    errno = EIO;
    return -1;
  }
  return fn(path);
}

int unlink(const char* path) {
  static auto fn = real<int (*)(const char*)>("unlink");
  if (hook("pre-write", path)) {
    errno = EIO;
    return -1;
  }
  return fn(path);
}

int fsync(int fd) {
  static auto fn = real<int (*)(int)>("fsync");
  char linkpath[64], target[4096];
  snprintf(linkpath, sizeof linkpath, "/proc/self/fd/%d", fd);
  ssize_t n = readlink(linkpath, target, sizeof target - 1);
  if (n > 0) {
    target[n] = '\0';
    if (hook("pre-fsync", target)) {
      errno = EIO;
      return -1;
    }
  }
  return fn(fd);
}

static int open_common(const char* name, const char* path, int flags,
                       mode_t mode) {
  static auto fn = real<int (*)(const char*, int, ...)>("open");
  static auto fn64 = real<int (*)(const char*, int, ...)>("open64");
  auto call = (strcmp(name, "open64") == 0) ? fn64 : fn;
  bool writes = (flags & (O_WRONLY | O_RDWR | O_CREAT | O_TRUNC)) != 0;
  if (writes && hook("pre-write", path)) {
    errno = EIO;
    return -1;
  }
  int fd = call(path, flags, mode);
  if (!writes && fd >= 0) hook("post-read", path);
  return fd;
}

int open(const char* path, int flags, ...) {
  va_list ap;
  va_start(ap, flags);
  mode_t mode = (flags & O_CREAT) ? va_arg(ap, mode_t) : 0;
  va_end(ap);
  return open_common("open", path, flags, mode);
}

int open64(const char* path, int flags, ...) {
  va_list ap;
  va_start(ap, flags);
  mode_t mode = (flags & O_CREAT) ? va_arg(ap, mode_t) : 0;
  va_end(ap);
  return open_common("open64", path, flags, mode);
}

int creat(const char* path, mode_t mode) {
  return open_common("open", path, O_CREAT | O_WRONLY | O_TRUNC, mode);
}

}  // extern "C"
