/* LD_PRELOAD clock interposer: the testee-side half of the virtual
 * clock (doc/performance.md "Virtual clock").
 *
 * The orchestrator's VirtualTimeSource fast-forwards its own process by
 * adding a jumpable offset to CLOCK_MONOTONIC and publishing that
 * offset into a small mmap'd epoch page (namazu_tpu/vclock). This
 * library, preloaded into every experiment child, extends the same
 * clock across the process boundary:
 *
 *  - clock_gettime / gettimeofday / time read real time + the page's
 *    offset (seqlock read, no lock), so the child's clocks agree with
 *    the orchestrator's to within one quantum;
 *  - nanosleep / usleep / sleep / clock_nanosleep / sem_timedwait /
 *    sem_clockwait and the timeouts of poll / select / epoll_wait /
 *    epoll_pwait are converted from "wait this long" into "wait until
 *    virtual deadline T": the thread claims a page slot, parks its
 *    deadline there, and waits. Pure timer waits FUTEX_WAIT on the
 *    page's seqlock word — the orchestrator FUTEX_WAKEs it after
 *    every offset publish, so a jump is observed in microseconds;
 *    waits that also watch fds (poll/select/epoll with fds) fall back
 *    to short real slices (<= 2ms) re-reading the offset, so fd
 *    readiness and signals keep untouched semantics;
 *  - blocking calls whose wakeup comes from another ENTITY rather
 *    than the clock — recv / recvfrom / accept / accept4 (peer data),
 *    sem_wait (a sem_post), wait / wait3 / wait4 / waitpid (a child
 *    exit), sigsuspend / pause (a signal) — park FOREVER around one
 *    untouched real call: they count as parked for the all-parked
 *    quiescence check but never propose a jump target. Without this
 *    class, a thread blocked in recv() would sit in running state and
 *    pin the clock for the whole run.
 *
 * The slot table is the pinning rule's cross-process face: a claimed
 * slot whose deadline is 0 means "this thread is running" (CPU work,
 * real I/O, an un-hooked syscall) and vetoes every jump — time only
 * fast-forwards when all claimed slots are parked. A thread claims its
 * slot lazily on the first hooked call and frees it from the
 * thread_local destructor; threads killed without unwinding are
 * garbage-collected by the orchestrator via /proc. If the table is
 * full the thread stays invisible and falls back to real waits —
 * slower, never wrong.
 *
 * Page layout (must match namazu_tpu/vclock/__init__.py): magic
 * "NMZVCLK1", u64 seq (seqlock, odd = writer active), i64 offset_ns,
 * u64 slot_count, then slots of { u64 owner = (pid << 32) | tid,
 * i64 deadline_ns (0 = running, >= 1<<62 = parked without deadline) }.
 */
#define _GNU_SOURCE 1
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <linux/futex.h>
#include <poll.h>
#include <pthread.h>
#include <semaphore.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/mman.h>
#include <sys/resource.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

namespace {

template <typename Fn>
Fn real(const char* name) {
  return reinterpret_cast<Fn>(dlsym(RTLD_NEXT, name));
}

using clock_gettime_fn = int (*)(clockid_t, struct timespec*);
using clock_nanosleep_fn = int (*)(clockid_t, int, const struct timespec*,
                                   struct timespec*);
using nanosleep_fn = int (*)(const struct timespec*, struct timespec*);
using usleep_fn = int (*)(useconds_t);
using sleep_fn = unsigned (*)(unsigned);
using gettimeofday_fn = int (*)(struct timeval*, void*);
using time_fn = time_t (*)(time_t*);
using poll_fn = int (*)(struct pollfd*, nfds_t, int);
using select_fn = int (*)(int, fd_set*, fd_set*, fd_set*, struct timeval*);
using epoll_wait_fn = int (*)(int, struct epoll_event*, int, int);
using epoll_pwait_fn = int (*)(int, struct epoll_event*, int, int,
                               const sigset_t*);
using recv_fn = ssize_t (*)(int, void*, size_t, int);
using recvfrom_fn = ssize_t (*)(int, void*, size_t, int,
                                struct sockaddr*, socklen_t*);
using accept_fn = int (*)(int, struct sockaddr*, socklen_t*);
using accept4_fn = int (*)(int, struct sockaddr*, socklen_t*, int);
using sem_wait_fn = int (*)(sem_t*);
using sem_timedwait_fn = int (*)(sem_t*, const struct timespec*);
using sem_clockwait_fn = int (*)(sem_t*, clockid_t,
                                 const struct timespec*);
using sigsuspend_fn = int (*)(const sigset_t*);
using pause_fn = int (*)(void);
using wait_fn = pid_t (*)(int*);
using wait3_fn = pid_t (*)(int*, int, struct rusage*);
using wait4_fn = pid_t (*)(pid_t, int*, int, struct rusage*);
using waitpid_fn = pid_t (*)(pid_t, int*, int);

constexpr int64_t kNs = 1000000000LL;
constexpr int64_t kQuantumNs = 2000000LL;  // 2ms: jump-observation latency
// parked with no deadline (indefinite poll/select): counts as parked
// for the all-parked check but never proposes a jump target
constexpr int64_t kForever = int64_t{1} << 62;

struct Slot {
  uint64_t owner;
  int64_t deadline_ns;
};

struct Page {
  char magic[8];
  uint64_t seq;
  int64_t offset_ns;
  uint64_t slot_count;
  Slot slots[];
};

Page* page() {
  static Page* p = [] {
    const char* path = getenv("NMZ_VCLOCK");
    if (path == nullptr || path[0] == '\0') return (Page*)nullptr;
    int fd = open(path, O_RDWR | O_CLOEXEC);
    if (fd < 0) return (Page*)nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0 ||
        (size_t)st.st_size < sizeof(Page) + sizeof(Slot)) {
      close(fd);
      return (Page*)nullptr;
    }
    void* m = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
    close(fd);
    if (m == MAP_FAILED) return (Page*)nullptr;
    Page* pg = (Page*)m;
    if (memcmp(pg->magic, "NMZVCLK1", 8) != 0) {
      munmap(m, (size_t)st.st_size);
      return (Page*)nullptr;
    }
    return pg;
  }();
  return p;
}

int64_t offset_ns() {
  Page* pg = page();
  if (pg == nullptr) return 0;
  // seqlock read: retry while the orchestrator is mid-publish
  for (;;) {
    uint64_t s1 = __atomic_load_n(&pg->seq, __ATOMIC_ACQUIRE);
    if (s1 & 1) continue;
    int64_t off = __atomic_load_n(&pg->offset_ns, __ATOMIC_ACQUIRE);
    uint64_t s2 = __atomic_load_n(&pg->seq, __ATOMIC_ACQUIRE);
    if (s1 == s2) return off;
  }
}

int64_t real_mono_ns() {
  static auto fn = real<clock_gettime_fn>("clock_gettime");
  struct timespec ts;
  fn(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * kNs + ts.tv_nsec;
}

int64_t vnow_ns() { return real_mono_ns() + offset_ns(); }

// Slot lifetime: claimed on the thread's first hooked call, freed by
// the thread_local destructor on clean thread exit. Between hooked
// waits the slot sits in running state (deadline 0) — that IS the
// pinning rule: an interposed thread doing anything other than a
// hooked wait holds virtual time to wall rate.
struct SlotGuard {
  Slot* slot = nullptr;
  ~SlotGuard() {
    if (slot != nullptr) {
      __atomic_store_n(&slot->deadline_ns, 0, __ATOMIC_RELEASE);
      __atomic_store_n(&slot->owner, 0, __ATOMIC_RELEASE);
    }
  }
};

thread_local SlotGuard tls_slot;

Slot* my_slot() {
  uint64_t me = ((uint64_t)getpid() << 32) |
                (uint64_t)(uint32_t)syscall(SYS_gettid);
  if (tls_slot.slot != nullptr) {
    // a forked child inherits the parent's TLS pointer — writing
    // through it would corrupt the PARENT's slot; detect the owner
    // mismatch and claim fresh
    if (__atomic_load_n(&tls_slot.slot->owner, __ATOMIC_ACQUIRE) == me)
      return tls_slot.slot;
    tls_slot.slot = nullptr;
  }
  Page* pg = page();
  if (pg == nullptr) return nullptr;
  // adopt an existing slot first: exec preserves pid/tid, so the slot
  // the pre-exec image (atfork handler) claimed is still ours — a
  // second claim would leave an orphan stuck in running state
  for (uint64_t i = 0; i < pg->slot_count; i++) {
    if (__atomic_load_n(&pg->slots[i].owner, __ATOMIC_ACQUIRE) == me) {
      tls_slot.slot = &pg->slots[i];
      return tls_slot.slot;
    }
  }
  for (uint64_t i = 0; i < pg->slot_count; i++) {
    uint64_t expect = 0;
    if (__atomic_compare_exchange_n(&pg->slots[i].owner, &expect, me,
                                    false, __ATOMIC_ACQ_REL,
                                    __ATOMIC_ACQUIRE)) {
      __atomic_store_n(&pg->slots[i].deadline_ns, 0, __ATOMIC_RELEASE);
      tls_slot.slot = &pg->slots[i];
      return tls_slot.slot;
    }
  }
  return nullptr;  // table full: stay invisible, waits fall back to real
}

// RAII park: deadline published on entry, running state restored on
// every exit path (return, signal-induced early return)
struct ParkScope {
  Slot* slot;
  explicit ParkScope(int64_t deadline) : slot(my_slot()) {
    if (slot != nullptr)
      __atomic_store_n(&slot->deadline_ns, deadline, __ATOMIC_RELEASE);
  }
  ~ParkScope() {
    if (slot != nullptr)
      __atomic_store_n(&slot->deadline_ns, 0, __ATOMIC_RELEASE);
  }
  bool parked() const { return slot != nullptr; }
};

/* Visibility from the first instruction: a process must never be able
 * to RUN while invisible to the pinning rule, or the coordinator can
 * jump over work in flight the instant the visible world goes quiet
 * (e.g. over the grep in a run script's readiness loop, leaving a 60s
 * long-poll deadline as the only — and wrong — jump target). Two
 * seams close the gap:
 *  - the fork child claims a running-state slot before it can execute
 *    anything (its parent may already be parked in a hooked wait);
 *    vfork/posix_spawn skip atfork handlers, but there the PARENT
 *    stays blocked in running state until the exec, which pins;
 *  - on library load (exec'd image) the main thread claims — adopting
 *    the atfork slot when one exists, since exec preserves pid/tid. */
void atfork_child() {
  tls_slot.slot = nullptr;  // points into the PARENT's slot
  if (page() != nullptr) my_slot();
}

__attribute__((constructor)) void claim_on_load() {
  pthread_atfork(nullptr, nullptr, atfork_child);
  if (page() != nullptr) my_slot();
}

struct timespec ns_to_ts(int64_t ns) {
  if (ns < 0) ns = 0;
  struct timespec ts;
  ts.tv_sec = ns / kNs;
  ts.tv_nsec = ns % kNs;
  return ts;
}

// Largest real wait between jump-observation checks when the
// orchestrator's FUTEX_WAKE cannot reach us (foreign-arch parent that
// skipped the wake syscall); with wakes working, parked threads are
// woken the instant a jump is published and this cap is never felt.
constexpr int64_t kFutexSliceNs = 20000000LL;  // 20ms

// Park until virtual deadline `target`. The thread futex-waits on the
// page's seq word: the orchestrator FUTEX_WAKEs it after every offset
// publish, so a jump is observed in microseconds, not a polling
// quantum. Returns 0 on deadline reached, -1 with errno = EINTR when
// a signal interrupted (rem gets the remaining VIRTUAL time).
int park_until(int64_t target, struct timespec* rem) {
  Page* pg = page();
  static auto fn = real<nanosleep_fn>("nanosleep");
  for (;;) {
    int64_t remaining = target - vnow_ns();
    if (remaining <= 0) return 0;
    if (pg == nullptr) {  // unreachable when parked; belt and braces
      struct timespec q =
          ns_to_ts(remaining < kQuantumNs ? remaining : kQuantumNs);
      if (fn(&q, nullptr) != 0 && errno == EINTR) {
        if (rem != nullptr) *rem = ns_to_ts(target - vnow_ns());
        return -1;
      }
      continue;
    }
    // the futex watches the low half of the seqlock word (it moves on
    // every publish); a publish between the load and FUTEX_WAIT makes
    // the wait return EAGAIN immediately — the classic race-free loop
    uint32_t* uaddr = reinterpret_cast<uint32_t*>(&pg->seq);
    uint32_t val = __atomic_load_n(uaddr, __ATOMIC_ACQUIRE);
    remaining = target - vnow_ns();
    if (remaining <= 0) return 0;
    struct timespec ts =
        ns_to_ts(remaining < kFutexSliceNs ? remaining : kFutexSliceNs);
    long r = syscall(SYS_futex, uaddr, FUTEX_WAIT, val, &ts, nullptr, 0);
    if (r != 0 && errno == EINTR) {
      if (rem != nullptr) *rem = ns_to_ts(target - vnow_ns());
      return -1;
    }
    // ETIMEDOUT: deadline (or slice) elapsed; EAGAIN: seq moved under
    // us (a jump landed) — both re-check the deadline
  }
}

}  // namespace

extern "C" {

int clock_gettime(clockid_t clk, struct timespec* ts) {
  static auto fn = real<clock_gettime_fn>("clock_gettime");
  int r = fn(clk, ts);
  if (r != 0 || page() == nullptr || ts == nullptr) return r;
  switch (clk) {
    case CLOCK_MONOTONIC:
    case CLOCK_MONOTONIC_RAW:
    case CLOCK_MONOTONIC_COARSE:
    case CLOCK_BOOTTIME:
    case CLOCK_REALTIME:
    case CLOCK_REALTIME_COARSE: {
      my_slot();  // clock readers become visible (and pin while running)
      int64_t v = (int64_t)ts->tv_sec * kNs + ts->tv_nsec + offset_ns();
      *ts = ns_to_ts(v);
      return 0;
    }
    default:
      return 0;  // per-process/thread CPU clocks stay real
  }
}

int gettimeofday(struct timeval* tv, void* tz) {
  static auto fn = real<gettimeofday_fn>("gettimeofday");
  int r = fn(tv, tz);
  if (r != 0 || page() == nullptr || tv == nullptr) return r;
  my_slot();
  int64_t v = (int64_t)tv->tv_sec * kNs + (int64_t)tv->tv_usec * 1000 +
              offset_ns();
  if (v < 0) v = 0;
  tv->tv_sec = v / kNs;
  tv->tv_usec = (v % kNs) / 1000;
  return 0;
}

time_t time(time_t* out) {
  static auto fn = real<time_fn>("time");
  time_t t = fn(nullptr);
  if (page() != nullptr && t != (time_t)-1) t += offset_ns() / kNs;
  if (out != nullptr) *out = t;
  return t;
}

int nanosleep(const struct timespec* req, struct timespec* rem) {
  static auto fn = real<nanosleep_fn>("nanosleep");
  if (page() == nullptr || req == nullptr) return fn(req, rem);
  int64_t dur = (int64_t)req->tv_sec * kNs + req->tv_nsec;
  if (dur <= 0) return fn(req, rem);
  int64_t target = vnow_ns() + dur;
  ParkScope park(target);
  if (!park.parked()) return fn(req, rem);
  return park_until(target, rem);
}

int clock_nanosleep(clockid_t clk, int flags, const struct timespec* req,
                    struct timespec* rem) {
  static auto fn = real<clock_nanosleep_fn>("clock_nanosleep");
  if (page() == nullptr || req == nullptr ||
      (clk != CLOCK_MONOTONIC && clk != CLOCK_REALTIME))
    return fn(clk, flags, req, rem);
  int64_t target;
  if (flags & TIMER_ABSTIME) {
    // absolute deadlines arrive in the caller's (virtual) clock
    // domain; both hooked clocks share the one offset, so the
    // monotonic virtual target is reached by the same delta
    struct timespec now_v;
    clock_gettime(clk, &now_v);
    int64_t delta = (int64_t)req->tv_sec * kNs + req->tv_nsec -
                    ((int64_t)now_v.tv_sec * kNs + now_v.tv_nsec);
    if (delta <= 0) return 0;
    target = vnow_ns() + delta;
  } else {
    int64_t dur = (int64_t)req->tv_sec * kNs + req->tv_nsec;
    if (dur <= 0) return fn(clk, flags, req, rem);
    target = vnow_ns() + dur;
  }
  ParkScope park(target);
  if (!park.parked()) return fn(clk, flags, req, rem);
  struct timespec myrem;
  if (park_until(target, &myrem) != 0) {
    // clock_nanosleep reports errors as return values, not errno;
    // rem is only written for relative sleeps
    if (rem != nullptr && !(flags & TIMER_ABSTIME)) *rem = myrem;
    return EINTR;
  }
  return 0;
}

int usleep(useconds_t usec) {
  static auto fn = real<usleep_fn>("usleep");
  if (page() == nullptr || usec == 0) return fn(usec);
  int64_t target = vnow_ns() + (int64_t)usec * 1000;
  ParkScope park(target);
  if (!park.parked()) return fn(usec);
  return park_until(target, nullptr);
}

unsigned sleep(unsigned seconds) {
  static auto fn = real<sleep_fn>("sleep");
  if (page() == nullptr || seconds == 0) return fn(seconds);
  int64_t target = vnow_ns() + (int64_t)seconds * kNs;
  ParkScope park(target);
  if (!park.parked()) return fn(seconds);
  struct timespec rem;
  if (park_until(target, &rem) != 0)
    return (unsigned)(rem.tv_sec + (rem.tv_nsec > 0 ? 1 : 0));
  return 0;
}

int poll(struct pollfd* fds, nfds_t nfds, int timeout) {
  static auto fn = real<poll_fn>("poll");
  if (page() == nullptr || timeout == 0) return fn(fds, nfds, timeout);
  int64_t target =
      timeout < 0 ? kForever : vnow_ns() + (int64_t)timeout * 1000000LL;
  ParkScope park(target);
  if (!park.parked()) return fn(fds, nfds, timeout);
  if (nfds == 0 && target != kForever) {
    // pure timer (CPython's time.sleep is poll(NULL, 0, ms)): no fds
    // to watch, so futex-park instead of quantum-slicing
    int r = park_until(target, nullptr);
    return r == 0 ? 0 : -1;  // 0 = timeout; -1/EINTR passes through
  }
  for (;;) {
    int64_t remaining =
        target == kForever ? kQuantumNs : target - vnow_ns();
    if (remaining <= 0) return 0;
    int64_t q = remaining < kQuantumNs ? remaining : kQuantumNs;
    int q_ms = (int)(q / 1000000LL);
    if (q_ms <= 0) q_ms = 1;
    int r = fn(fds, nfds, q_ms);
    if (r != 0) return r;  // fd ready, or error (EINTR included)
  }
}

int select(int nfds, fd_set* rd, fd_set* wr, fd_set* ex,
           struct timeval* tv) {
  static auto fn = real<select_fn>("select");
  if (page() == nullptr ||
      (tv != nullptr && tv->tv_sec == 0 && tv->tv_usec == 0))
    return fn(nfds, rd, wr, ex, tv);
  int64_t target = tv == nullptr
                       ? kForever
                       : vnow_ns() + (int64_t)tv->tv_sec * kNs +
                             (int64_t)tv->tv_usec * 1000;
  ParkScope park(target);
  if (!park.parked()) return fn(nfds, rd, wr, ex, tv);
  if (nfds == 0 && target != kForever) {
    // pure timer (select-based sleeps pass no fds): futex-park
    if (park_until(target, nullptr) != 0) return -1;  // EINTR
    if (tv != nullptr) {
      tv->tv_sec = 0;
      tv->tv_usec = 0;
    }
    return 0;
  }
  // select clobbers its fd_sets on every call — keep the caller's
  // originals so each quantum retry watches the full set
  fd_set rd0, wr0, ex0;
  if (rd != nullptr) rd0 = *rd;
  if (wr != nullptr) wr0 = *wr;
  if (ex != nullptr) ex0 = *ex;
  for (;;) {
    int64_t remaining =
        target == kForever ? kQuantumNs : target - vnow_ns();
    if (remaining <= 0) {
      if (rd != nullptr) FD_ZERO(rd);
      if (wr != nullptr) FD_ZERO(wr);
      if (ex != nullptr) FD_ZERO(ex);
      if (tv != nullptr) {
        tv->tv_sec = 0;
        tv->tv_usec = 0;
      }
      return 0;
    }
    if (rd != nullptr) *rd = rd0;
    if (wr != nullptr) *wr = wr0;
    if (ex != nullptr) *ex = ex0;
    int64_t q = remaining < kQuantumNs ? remaining : kQuantumNs;
    struct timeval qt;
    qt.tv_sec = q / kNs;
    qt.tv_usec = (q % kNs) / 1000;
    if (qt.tv_sec == 0 && qt.tv_usec == 0) qt.tv_usec = 1000;
    int r = fn(nfds, rd, wr, ex, &qt);
    if (r != 0) return r;
  }
}

int epoll_wait(int epfd, struct epoll_event* events, int maxevents,
               int timeout) {
  static auto fn = real<epoll_wait_fn>("epoll_wait");
  if (page() == nullptr || timeout == 0)
    return fn(epfd, events, maxevents, timeout);
  int64_t target =
      timeout < 0 ? kForever : vnow_ns() + (int64_t)timeout * 1000000LL;
  ParkScope park(target);
  if (!park.parked()) return fn(epfd, events, maxevents, timeout);
  for (;;) {
    int64_t remaining =
        target == kForever ? kQuantumNs : target - vnow_ns();
    if (remaining <= 0) return 0;
    int64_t q = remaining < kQuantumNs ? remaining : kQuantumNs;
    int q_ms = (int)(q / 1000000LL);
    if (q_ms <= 0) q_ms = 1;
    int r = fn(epfd, events, maxevents, q_ms);
    if (r != 0) return r;
  }
}

/* Timed semaphore waits: CPython's timed lock acquires (Event.wait
 * with a timeout, Queue.get(timeout=...), Thread.join(timeout=...))
 * compile to sem_clockwait(CLOCK_MONOTONIC) on glibc >= 2.30 and
 * sem_timedwait(CLOCK_REALTIME) before that. Either way the caller
 * computed `abs` against OUR virtualized clock, so the kernel — which
 * compares against the real clock — would wait `offset` too long.
 * Convert to a relative virtual wait and slice it into
 * quantum-bounded real deadlines so jumps are observed. */

static int sem_park(
    sem_t* sem, clockid_t clk, const struct timespec* abs,
    int (*waiter)(sem_t*, clockid_t, const struct timespec*)) {
  static auto cg = real<clock_gettime_fn>("clock_gettime");
  struct timespec now;
  cg(clk, &now);
  int64_t real_now = (int64_t)now.tv_sec * kNs + now.tv_nsec;
  int64_t rel = (int64_t)abs->tv_sec * kNs + abs->tv_nsec -
                (real_now + offset_ns());
  if (rel <= 0) {
    // virtually expired: force the real call to decide NOW (acquire
    // if available, else ETIMEDOUT) instead of waiting out the offset
    struct timespec past =
        ns_to_ts(real_now > kNs ? real_now - kNs : 0);
    return waiter(sem, clk, &past);
  }
  int64_t target = vnow_ns() + rel;
  ParkScope park(target);
  if (!park.parked()) return waiter(sem, clk, abs);
  for (;;) {
    int64_t remaining = target - vnow_ns();
    if (remaining <= 0) {
      errno = ETIMEDOUT;
      return -1;
    }
    int64_t q = remaining < kQuantumNs ? remaining : kQuantumNs;
    cg(clk, &now);
    struct timespec slice =
        ns_to_ts((int64_t)now.tv_sec * kNs + now.tv_nsec + q);
    int r = waiter(sem, clk, &slice);
    if (r == 0 || errno != ETIMEDOUT) return r;  // acquired, or EINTR
  }
}

int sem_timedwait(sem_t* sem, const struct timespec* abs) {
  static auto fn = real<sem_timedwait_fn>("sem_timedwait");
  if (page() == nullptr) return fn(sem, abs);
  return sem_park(sem, CLOCK_REALTIME, abs,
                  [](sem_t* s, clockid_t, const struct timespec* t) {
                    static auto f = real<sem_timedwait_fn>("sem_timedwait");
                    return f(s, t);
                  });
}

int sem_clockwait(sem_t* sem, clockid_t clk, const struct timespec* abs) {
  static auto fn = real<sem_clockwait_fn>("sem_clockwait");
  if (page() == nullptr || fn == nullptr ||
      (clk != CLOCK_MONOTONIC && clk != CLOCK_REALTIME))
    return fn != nullptr ? fn(sem, clk, abs) : (errno = ENOSYS, -1);
  return sem_park(sem, clk, abs,
                  [](sem_t* s, clockid_t c, const struct timespec* t) {
                    static auto f = real<sem_clockwait_fn>("sem_clockwait");
                    return f(s, c, t);
                  });
}

/* Forever-parks: blocking calls woken by another entity (peer data, a
 * sem_post, a child exit) — never by the clock. One untouched real
 * call inside a kForever park: quiescent for the all-parked check,
 * but never a jump target. ParkScope's dtor is two relaxed stores and
 * leaves errno alone, so the hooked call's result passes through
 * bit-exactly (nonblocking sockets, WNOHANG, EOF included). */

ssize_t recv(int fd, void* buf, size_t n, int flags) {
  static auto fn = real<recv_fn>("recv");
  if (page() == nullptr) return fn(fd, buf, n, flags);
  ParkScope park(kForever);
  return fn(fd, buf, n, flags);
}

ssize_t recvfrom(int fd, void* buf, size_t n, int flags,
                 struct sockaddr* addr, socklen_t* alen) {
  static auto fn = real<recvfrom_fn>("recvfrom");
  if (page() == nullptr) return fn(fd, buf, n, flags, addr, alen);
  ParkScope park(kForever);
  return fn(fd, buf, n, flags, addr, alen);
}

int accept(int fd, struct sockaddr* addr, socklen_t* alen) {
  static auto fn = real<accept_fn>("accept");
  if (page() == nullptr) return fn(fd, addr, alen);
  ParkScope park(kForever);
  return fn(fd, addr, alen);
}

int accept4(int fd, struct sockaddr* addr, socklen_t* alen, int flags) {
  static auto fn = real<accept4_fn>("accept4");
  if (page() == nullptr) return fn(fd, addr, alen, flags);
  ParkScope park(kForever);
  return fn(fd, addr, alen, flags);
}

int sem_wait(sem_t* sem) {
  static auto fn = real<sem_wait_fn>("sem_wait");
  if (page() == nullptr) return fn(sem);
  ParkScope park(kForever);
  return fn(sem);
}

pid_t wait(int* status) {
  static auto fn = real<wait_fn>("wait");
  if (page() == nullptr) return fn(status);
  ParkScope park(kForever);
  return fn(status);
}

pid_t wait3(int* status, int options, struct rusage* ru) {
  static auto fn = real<wait3_fn>("wait3");
  if (page() == nullptr) return fn(status, options, ru);
  ParkScope park(kForever);
  return fn(status, options, ru);
}

pid_t wait4(pid_t pid, int* status, int options, struct rusage* ru) {
  static auto fn = real<wait4_fn>("wait4");
  if (page() == nullptr) return fn(pid, status, options, ru);
  ParkScope park(kForever);
  return fn(pid, status, options, ru);
}

pid_t waitpid(pid_t pid, int* status, int options) {
  static auto fn = real<waitpid_fn>("waitpid");
  if (page() == nullptr) return fn(pid, status, options);
  ParkScope park(kForever);
  return fn(pid, status, options);
}

int sigsuspend(const sigset_t* mask) {
  // dash's `wait` builtin blocks here for SIGCHLD — without this the
  // run script's shell pins the clock for the whole campaign
  static auto fn = real<sigsuspend_fn>("sigsuspend");
  if (page() == nullptr) return fn(mask);
  ParkScope park(kForever);
  return fn(mask);
}

int pause(void) {
  static auto fn = real<pause_fn>("pause");
  if (page() == nullptr) return fn();
  ParkScope park(kForever);
  return fn();
}

int epoll_pwait(int epfd, struct epoll_event* events, int maxevents,
                int timeout, const sigset_t* sigmask) {
  static auto fn = real<epoll_pwait_fn>("epoll_pwait");
  if (page() == nullptr || timeout == 0)
    return fn(epfd, events, maxevents, timeout, sigmask);
  int64_t target =
      timeout < 0 ? kForever : vnow_ns() + (int64_t)timeout * 1000000LL;
  ParkScope park(target);
  if (!park.parked())
    return fn(epfd, events, maxevents, timeout, sigmask);
  for (;;) {
    int64_t remaining =
        target == kForever ? kQuantumNs : target - vnow_ns();
    if (remaining <= 0) return 0;
    int64_t q = remaining < kQuantumNs ? remaining : kQuantumNs;
    int q_ms = (int)(q / 1000000LL);
    if (q_ms <= 0) q_ms = 1;
    int r = fn(epfd, events, maxevents, q_ms, sigmask);
    if (r != 0) return r;
  }
}

}  // extern "C"
