// Byteman helper for namazu_tpu: bind these static methods from .btm
// rules to defer JVM function calls/returns through the orchestrator.
//
// Capability parity with the reference's PBEQHelper
// (/root/reference/misc/inspector/java/base/src/net/osrg/namazu/
// PBEQHelper.java:8-65). Example rule:
//
//   RULE inspect FooServer.processRequest entry
//   CLASS com.example.FooServer
//   METHOD processRequest
//   HELPER net.namazu_tpu.EventQueueHelper
//   AT ENTRY
//   IF TRUE
//   DO eventFuncCall("processRequest")
//   ENDRULE

package net.namazu_tpu;

public class EventQueueHelper {
    public static void eventFuncCall(String funcName) {
        NmzAgent.getInstance().eventFunc(funcName, "call");
    }

    public static void eventFuncReturn(String funcName) {
        NmzAgent.getInstance().eventFunc(funcName, "return");
    }
}
