// Java guest agent for the namazu_tpu orchestrator.
//
// Capability parity with the reference's JVM inspector
// (/root/reference/misc/inspector/java/base/src/net/osrg/namazu/
// PBInspector.java:19-120): intercepted function calls/returns are sent to
// the orchestrator and the calling thread parks until the corresponding
// action frame arrives. Redesign: instead of generated protobuf stubs the
// wire format is the framework-wide ``uint32-LE length + UTF-8 JSON``
// framing of namazu_tpu/endpoint/agent.py, so this file has zero
// dependencies beyond the JDK.
//
// Environment (same contract as the C++ agent, native/agent/nmz_agent.h):
//   NMZ_TPU_AGENT_ADDR  host:port of the agent endpoint (default
//                       127.0.0.1:10081)
//   NMZ_TPU_ENTITY_ID   entity id (default "_nmz_java_agent")
//   NMZ_TPU_DISABLE     set to any value to no-op every hook

package net.namazu_tpu;

import java.io.DataInputStream;
import java.io.IOException;
import java.io.OutputStream;
import java.net.Socket;
import java.nio.charset.StandardCharsets;
import java.util.Map;
import java.util.UUID;
import java.util.concurrent.ConcurrentHashMap;
import java.util.concurrent.SynchronousQueue;

public final class NmzAgent {
    private static NmzAgent instance;

    private final String entityId;
    private Socket socket;
    private OutputStream out;
    private final Object sendLock = new Object();
    private final Map<String, SynchronousQueue<String>> waiting =
            new ConcurrentHashMap<String, SynchronousQueue<String>>();
    private final boolean disabled;

    public static synchronized NmzAgent getInstance() {
        if (instance == null) {
            instance = new NmzAgent();
        }
        return instance;
    }

    private NmzAgent() {
        this.disabled = System.getenv("NMZ_TPU_DISABLE") != null;
        String entity = System.getenv("NMZ_TPU_ENTITY_ID");
        this.entityId = entity != null ? entity : "_nmz_java_agent";
        if (disabled) {
            return;
        }
        String addr = System.getenv("NMZ_TPU_AGENT_ADDR");
        if (addr == null) {
            addr = "127.0.0.1:10081";
        }
        int colon = addr.lastIndexOf(':');
        String host = colon > 0 ? addr.substring(0, colon) : "127.0.0.1";
        int port = Integer.parseInt(addr.substring(colon + 1));
        try {
            socket = new Socket(host, port);
            socket.setTcpNoDelay(true);
            out = socket.getOutputStream();
            Thread reader = new Thread(new Runnable() {
                public void run() {
                    readLoop();
                }
            }, "nmz-agent-reader");
            reader.setDaemon(true);
            reader.start();
        } catch (IOException e) {
            throw new RuntimeException(
                    "nmz agent: cannot reach orchestrator at " + addr, e);
        }
    }

    /**
     * Send a FunctionEvent and park the calling thread until the
     * orchestrator's action releases it. funcType is "call" or "return".
     * Returns the action's class name (e.g. "EventAcceptanceAction").
     */
    public String eventFunc(String funcName, String funcType) {
        if (disabled || socket == null) {
            return "NopAction";
        }
        String uuid = UUID.randomUUID().toString();
        SynchronousQueue<String> q = new SynchronousQueue<String>();
        waiting.put(uuid, q);
        StringBuilder sb = new StringBuilder(256);
        sb.append("{\"type\":\"event\",\"class\":\"FunctionEvent\"");
        sb.append(",\"entity\":").append(quote(entityId));
        sb.append(",\"uuid\":").append(quote(uuid));
        sb.append(",\"option\":{\"func_name\":").append(quote(funcName));
        sb.append(",\"func_type\":").append(quote(funcType));
        sb.append(",\"runtime\":\"java\"");
        sb.append(",\"thread_name\":")
          .append(quote(Thread.currentThread().getName()));
        sb.append("}}");
        try {
            writeFrame(sb.toString());
            return q.take(); // park until the reader hands us the action
        } catch (IOException e) {
            waiting.remove(uuid);
            return "NopAction"; // orchestrator gone: release the thread
        } catch (InterruptedException e) {
            waiting.remove(uuid);
            Thread.currentThread().interrupt();
            return "NopAction";
        }
    }

    private void writeFrame(String json) throws IOException {
        byte[] body = json.getBytes(StandardCharsets.UTF_8);
        byte[] frame = new byte[4 + body.length];
        // uint32 little-endian length prefix
        frame[0] = (byte) (body.length & 0xFF);
        frame[1] = (byte) ((body.length >> 8) & 0xFF);
        frame[2] = (byte) ((body.length >> 16) & 0xFF);
        frame[3] = (byte) ((body.length >> 24) & 0xFF);
        System.arraycopy(body, 0, frame, 4, body.length);
        synchronized (sendLock) {
            out.write(frame); // single write: one frame per segment
            out.flush();
        }
    }

    private void readLoop() {
        try {
            DataInputStream in = new DataInputStream(socket.getInputStream());
            byte[] header = new byte[4];
            while (true) {
                in.readFully(header);
                int length = (header[0] & 0xFF)
                        | ((header[1] & 0xFF) << 8)
                        | ((header[2] & 0xFF) << 16)
                        | ((header[3] & 0xFF) << 24);
                if (length < 0 || length > 16 * 1024 * 1024) {
                    throw new IOException("bad frame length " + length);
                }
                byte[] body = new byte[length];
                in.readFully(body);
                String json = new String(body, StandardCharsets.UTF_8);
                String eventUuid = extractString(json, "event_uuid");
                if (eventUuid == null) {
                    continue; // not an event-answering action
                }
                SynchronousQueue<String> q = waiting.remove(eventUuid);
                if (q != null) {
                    String klass = extractString(json, "class");
                    q.put(klass != null ? klass : "NopAction");
                }
            }
        } catch (IOException e) {
            releaseAll();
        } catch (InterruptedException e) {
            releaseAll();
            Thread.currentThread().interrupt();
        }
    }

    private void releaseAll() {
        // connection lost: unblock every parked thread so the testee can
        // proceed (parity with the reference's fail-open behaviour)
        for (Map.Entry<String, SynchronousQueue<String>> e
                : waiting.entrySet()) {
            waiting.remove(e.getKey());
            try {
                e.getValue().put("NopAction");
            } catch (InterruptedException ie) {
                Thread.currentThread().interrupt();
                return;
            }
        }
    }

    /** Minimal JSON string-field extractor: finds "key":"value" at any
     *  nesting level. Safe here because the orchestrator emits flat,
     *  known-shape action dicts and values never embed escaped quotes
     *  except via backslash escapes, which are handled. */
    static String extractString(String json, String key) {
        String needle = "\"" + key + "\"";
        int i = json.indexOf(needle);
        if (i < 0) {
            return null;
        }
        i = json.indexOf(':', i + needle.length());
        if (i < 0) {
            return null;
        }
        i++;
        while (i < json.length()
                && Character.isWhitespace(json.charAt(i))) {
            i++;
        }
        if (i >= json.length() || json.charAt(i) != '"') {
            return null;
        }
        StringBuilder sb = new StringBuilder();
        i++;
        while (i < json.length()) {
            char c = json.charAt(i);
            if (c == '\\' && i + 1 < json.length()) {
                char n = json.charAt(i + 1);
                switch (n) {
                    case 'n': sb.append('\n'); break;
                    case 't': sb.append('\t'); break;
                    case 'r': sb.append('\r'); break;
                    case 'b': sb.append('\b'); break;
                    case 'f': sb.append('\f'); break;
                    case 'u':
                        if (i + 5 < json.length()) {
                            sb.append((char) Integer.parseInt(
                                    json.substring(i + 2, i + 6), 16));
                            i += 4;
                        }
                        break;
                    default: sb.append(n);
                }
                i += 2;
                continue;
            }
            if (c == '"') {
                return sb.toString();
            }
            sb.append(c);
            i++;
        }
        return null;
    }

    static String quote(String s) {
        StringBuilder sb = new StringBuilder(s.length() + 2);
        sb.append('"');
        for (int i = 0; i < s.length(); i++) {
            char c = s.charAt(i);
            switch (c) {
                case '"': sb.append("\\\""); break;
                case '\\': sb.append("\\\\"); break;
                case '\n': sb.append("\\n"); break;
                case '\r': sb.append("\\r"); break;
                case '\t': sb.append("\\t"); break;
                default:
                    if (c < 0x20) {
                        sb.append(String.format("\\u%04x", (int) c));
                    } else {
                        sb.append(c);
                    }
            }
        }
        sb.append('"');
        return sb.toString();
    }
}
