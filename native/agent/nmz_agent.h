/* nmz_agent: embeddable in-process inspector agent.
 *
 * Capability parity with the reference's embedded C inspector
 * (/root/reference/misc/inspector/c/embed/eq_embed.cc) and the wire role of
 * its Java PBInspector: hook functions in a native testee, ship each
 * call/return as an event to the orchestrator over the guest-agent framed
 * TCP protocol (uint32-LE length + JSON; namazu_tpu/endpoint/agent.py),
 * and park the calling thread until the policy releases it.
 *
 * Environment (reference parity: NMZ_GA_TCP_PORT / NMZ_DISABLE /
 * NMZ_ENV_PROCESS_ID):
 *   NMZ_TPU_AGENT_ADDR  host:port of the agent endpoint (default
 *                       127.0.0.1:10081)
 *   NMZ_TPU_ENTITY_ID   entity id (default "_nmz_c_agent")
 *   NMZ_TPU_DISABLE     if set (non-empty), every hook is a no-op
 *
 * All functions are thread-safe. C linkage so the library preloads into
 * anything.
 */
#ifndef NMZ_AGENT_H_
#define NMZ_AGENT_H_

#ifdef __cplusplus
extern "C" {
#endif

/* Returns 0 on success, -1 on failure (agent then disables itself). */
int nmz_agent_init(void);

/* True when the agent is connected and enabled. */
int nmz_agent_enabled(void);

/* Block until the orchestrator releases this function event.
 * Returns 0 = proceed, 1 = fault injected, -1 = error/disabled.  */
int nmz_agent_func_call(const char *func_name);
int nmz_agent_func_return(const char *func_name);

/* Generic event hook used by the fs interposer: class is the event class
 * name ("FilesystemEvent"), op/path fill its option dict. Same returns. */
int nmz_agent_fs_event(const char *op, const char *path);

void nmz_agent_shutdown(void);

#ifdef __cplusplus
}
#endif

#endif /* NMZ_AGENT_H_ */
