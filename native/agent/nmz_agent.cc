/* Embeddable guest agent — see nmz_agent.h.
 *
 * Design: one reader thread per process; hooked threads build an event
 * frame, register a waiter keyed by the event uuid, send, and park on a
 * condition variable until the reader delivers the matching action
 * (correlated by "event_uuid"). Mirrors the inspector-side transceiver
 * contract (waiter registered before the frame leaves the process).
 *
 * JSON handling is deliberately minimal: frames we *emit* are built with a
 * tiny escaper; frames we *receive* come from our own orchestrator with a
 * fixed shape, so scanning for the "event_uuid" and "class" string fields
 * is sufficient and keeps the agent dependency-free.
 */
#include "nmz_agent.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>

namespace {

struct Waiter {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  bool fault = false;
};

struct Agent {
  int fd = -1;
  bool enabled = false;
  std::string entity;
  std::mutex send_mu;
  std::mutex waiters_mu;
  std::map<std::string, Waiter*> waiters;
  std::thread reader;
};

Agent* g_agent = nullptr;
std::once_flag g_init_once;

std::string json_escape(const char* s) {
  std::string out;
  for (const char* p = s; *p; ++p) {
    unsigned char c = static_cast<unsigned char>(*p);
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  return out;
}

std::string make_uuid() {
  static std::mutex mu;
  static std::mt19937_64 rng(std::random_device{}());
  std::lock_guard<std::mutex> lk(mu);
  char buf[40];
  uint64_t a = rng(), b = rng();
  snprintf(buf, sizeof buf, "%08x-%04x-4%03x-%04x-%012llx",
           static_cast<uint32_t>(a >> 32),
           static_cast<uint32_t>(a >> 16) & 0xffff,
           static_cast<uint32_t>(a) & 0xfff,
           static_cast<uint32_t>(b >> 48) & 0xffff,
           static_cast<unsigned long long>(b & 0xffffffffffffULL));
  return buf;
}

/* Extract the value of a top-level string field: "name":"value". */
std::string scan_string_field(const std::string& json, const char* name) {
  std::string needle = std::string("\"") + name + "\":";
  size_t pos = json.find(needle);
  if (pos == std::string::npos) return "";
  pos += needle.size();
  while (pos < json.size() && (json[pos] == ' ')) ++pos;
  if (pos >= json.size() || json[pos] != '"') return "";
  ++pos;
  std::string out;
  while (pos < json.size() && json[pos] != '"') {
    if (json[pos] == '\\' && pos + 1 < json.size()) ++pos;
    out += json[pos++];
  }
  return out;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_frame(Agent* a, const std::string& payload) {
  /* single write: a split header/body send turns into 40ms of
   * Nagle + delayed-ACK latency per event */
  uint32_t le = htole32(static_cast<uint32_t>(payload.size()));
  std::string buf(reinterpret_cast<const char*>(&le), 4);
  buf += payload;
  std::lock_guard<std::mutex> lk(a->send_mu);
  return send_all(a->fd, buf.data(), buf.size());
}

void reader_loop(Agent* a) {
  for (;;) {
    uint32_t le = 0;
    if (!recv_all(a->fd, &le, 4)) break;
    uint32_t len = le32toh(le);
    if (len > (16u << 20)) break;
    std::string body(len, '\0');
    if (!recv_all(a->fd, body.data(), len)) break;
    std::string event_uuid = scan_string_field(body, "event_uuid");
    std::string cls = scan_string_field(body, "class");
    if (event_uuid.empty()) continue;
    Waiter* w = nullptr;
    {
      std::lock_guard<std::mutex> lk(a->waiters_mu);
      auto it = a->waiters.find(event_uuid);
      if (it != a->waiters.end()) {
        w = it->second;
        a->waiters.erase(it);
      }
    }
    if (w != nullptr) {
      std::lock_guard<std::mutex> lk(w->mu);
      w->fault = cls.find("Fault") != std::string::npos;
      w->done = true;
      w->cv.notify_all();
    }
  }
  /* connection gone: release every parked thread (proceed, no fault) */
  std::lock_guard<std::mutex> lk(a->waiters_mu);
  for (auto& kv : a->waiters) {
    std::lock_guard<std::mutex> wl(kv.second->mu);
    kv.second->done = true;
    kv.second->cv.notify_all();
  }
  a->waiters.clear();
  a->enabled = false;
}

int do_init() {
  const char* disable = getenv("NMZ_TPU_DISABLE");
  if (disable != nullptr && disable[0] != '\0') return -1;
  const char* addr = getenv("NMZ_TPU_AGENT_ADDR");
  std::string host = "127.0.0.1";
  std::string port = "10081";
  if (addr != nullptr && addr[0] != '\0') {
    std::string s(addr);
    size_t colon = s.rfind(':');
    if (colon == std::string::npos) return -1;
    host = s.substr(0, colon);
    port = s.substr(colon + 1);
  }
  const char* entity = getenv("NMZ_TPU_ENTITY_ID");

  struct addrinfo hints;
  memset(&hints, 0, sizeof hints);
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) return -1;
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);

  g_agent = new Agent();
  g_agent->fd = fd;
  g_agent->entity =
      (entity != nullptr && entity[0] != '\0') ? entity : "_nmz_c_agent";
  g_agent->enabled = true;
  g_agent->reader = std::thread(reader_loop, g_agent);
  g_agent->reader.detach();
  return 0;
}

/* Send one event and park until its action arrives.
 * option_json: the option dict body, already JSON (no braces). */
int emit_and_wait(const char* cls, const std::string& option_json) {
  std::call_once(g_init_once, [] { do_init(); });
  Agent* a = g_agent;
  if (a == nullptr || !a->enabled) return -1;

  std::string uuid = make_uuid();
  Waiter w;
  {
    std::lock_guard<std::mutex> lk(a->waiters_mu);
    a->waiters[uuid] = &w;
  }
  std::string frame = std::string("{\"type\":\"event\",\"class\":\"") + cls +
                      "\",\"entity\":\"" + json_escape(a->entity.c_str()) +
                      "\",\"uuid\":\"" + uuid + "\",\"option\":{" +
                      option_json + "}}";
  if (!send_frame(a, frame)) {
    std::lock_guard<std::mutex> lk(a->waiters_mu);
    a->waiters.erase(uuid);
    return -1;
  }
  std::unique_lock<std::mutex> lk(w.mu);
  w.cv.wait(lk, [&] { return w.done; });
  return w.fault ? 1 : 0;
}

int func_event(const char* func_name, const char* func_type) {
  std::string opt = std::string("\"func_name\":\"") + json_escape(func_name) +
                    "\",\"func_type\":\"" + func_type +
                    "\",\"runtime\":\"c\"";
  return emit_and_wait("FunctionEvent", opt);
}

}  // namespace

extern "C" {

int nmz_agent_init(void) {
  std::call_once(g_init_once, [] { do_init(); });
  return (g_agent != nullptr && g_agent->enabled) ? 0 : -1;
}

int nmz_agent_enabled(void) {
  return (g_agent != nullptr && g_agent->enabled) ? 1 : 0;
}

int nmz_agent_func_call(const char* func_name) {
  return func_event(func_name, "call");
}

int nmz_agent_func_return(const char* func_name) {
  return func_event(func_name, "return");
}

int nmz_agent_fs_event(const char* op, const char* path) {
  std::string opt = std::string("\"op\":\"") + json_escape(op) +
                    "\",\"path\":\"" + json_escape(path) + "\"";
  return emit_and_wait("FilesystemEvent", opt);
}

void nmz_agent_shutdown(void) {
  Agent* a = g_agent;
  if (a != nullptr && a->fd >= 0) {
    shutdown(a->fd, SHUT_RDWR);
    close(a->fd);
    a->enabled = false;
  }
}

}  // extern "C"
