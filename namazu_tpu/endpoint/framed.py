"""FramedServer: the ONE keep-alive framed-JSON serve loop.

Three servers grew the same loop independently — the ``uds://`` event
endpoint (endpoint/uds.py), the search/knowledge sidecar (sidecar.py),
and the campaign supervisor's telemetry collector
(obs/federation.TelemetryServer). PR 9 noted the consolidation and
deferred it; the causality plane forces the issue — span context must
be observed and echoed uniformly on every framed wire, and three copies
of the loop is three places to get that wrong.

The contract every framed wire now shares (one frame each way,
``uint32-LE length + UTF-8 JSON`` — endpoint/agent.py's codec, any
number of request/response pairs per connection):

* EOF or a codec/socket error drops the connection cleanly;
* a valid-JSON **non-object** frame is ANSWERED
  (``{"ok": false, ...}``) so the client's keep-alive stream stays in
  sync, never severed;
* a handler exception is answered (``{"ok": false, "error": ...}``),
  logged, and never desyncs the wire;
* **span context** (obs/context.py): a request frame carrying ``ctx``
  has its Lamport clock merged into this process's before the handler
  runs, and the response echoes a fresh ``ctx`` stamp — so causal
  order is joinable across every framed hop (knowledge push/pull,
  telemetry forward, uds event ops) without the handlers knowing.
  Context-less requests get byte-identical responses to the
  pre-context wire;
* shutdown severs live connections (a parked long-poll must error and
  reconnect, not keep talking to a dead server), and ``sever()`` alone
  simulates crash death for the chaos harness.

Binding: :meth:`bind_unix` reclaims a listener-less stale socket inode
(probe-connect first; a live listener raises — stealing a served path
would silently split an event stream across two servers; a non-socket
file is never clobbered) and unlinks the path at shutdown;
:meth:`bind_tcp` sets ``SO_REUSEADDR`` so a hard-stopped server can
rebind its port immediately.
"""

from __future__ import annotations

import os
import socket
import stat
import threading
from typing import Callable, Dict, Optional

from namazu_tpu.endpoint.agent import (FramePayloadError,
                                       read_frame_ex, write_frame)
from namazu_tpu.obs import context as _context
from namazu_tpu.obs import metrics as _metrics
from namazu_tpu.obs import spans as _spans
from namazu_tpu.signal import binary as _binary
from namazu_tpu.signal.base import SignalError
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.framed")

#: handler(req dict) -> resp dict
Handler = Callable[[dict], dict]
#: decorate(req dict, resp dict) -> None — per-wire piggybacks (the
#: uds endpoint's table_version) applied after the handler, before send
Decorator = Callable[[dict, dict], None]


def reclaim_stale_unix_socket(path: str, what: str = "server") -> None:
    """Unlink a socket inode left by a dead predecessor, IF no live
    listener answers a probe connect. A live listener raises (the path
    is being served); a non-socket path is left alone so the caller's
    bind fails loudly instead of clobbering someone's file."""
    try:
        st = os.stat(path)
    except OSError:
        return  # nothing there
    if not stat.S_ISSOCK(st.st_mode):
        return
    probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        probe.settimeout(0.2)
        try:
            probe.connect(path)
        except OSError:
            # no listener: stale — reclaim the path
            try:
                os.unlink(path)
            except OSError:
                pass
            return
    finally:
        try:
            probe.close()
        except OSError:
            pass
    raise RuntimeError(
        f"{what} path {path!r} already has a live listener "
        "(another process?); refusing to take it over")


class FramedServer:
    def __init__(self, handler: Handler, name: str = "framed",
                 decorate: Optional[Decorator] = None) -> None:
        self._handler = handler
        self._name = name
        self._decorate = decorate
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        #: AF_UNIX path when bound to one (unlinked at shutdown)
        self.path: Optional[str] = None

    # -- binding -----------------------------------------------------------

    def bind_unix(self, path: str, backlog: int = 64) -> None:
        reclaim_stale_unix_socket(path, what=self._name)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(backlog)
        self._server = srv
        self.path = path

    def bind_tcp(self, host: str, port: int, backlog: int = 8) -> int:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(backlog)
        self._server = srv
        return srv.getsockname()[1]

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.getsockname()[1]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        assert self._server is not None, "bind before start"
        if self._accept_thread is not None:
            return
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self._name}-accept",
            daemon=True)
        self._accept_thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        srv, self._server = self._server, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self.path is not None:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def sever(self) -> int:
        """Cut every live connection WITHOUT stopping the server — the
        chaos harness's in-process stand-in for kill -9: a parked
        client poll must error and reconnect, not keep talking to a
        dead process's handler thread."""
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        return len(conns)

    # -- the loop ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            srv = self._server
            if srv is None:
                return
            try:
                conn, _ = srv.accept()
            except OSError:
                return  # closed by shutdown
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name=f"{self._name}-conn",
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    req, codec, n_in = read_frame_ex(conn)
                except FramePayloadError as e:
                    # the frame's length prefix was intact, only the
                    # payload was garbled: the stream is still in sync
                    # — answer it (transient: the client's bounded
                    # retry resends a clean copy), never sever the
                    # keep-alive connection (wire.binary.garble)
                    try:
                        write_frame(conn, {"ok": False,
                                           "transient": True,
                                           "error": str(e)})
                    except OSError:
                        break
                    continue
                except (SignalError, ValueError, OSError):
                    # oversized frame or a socket error: the framing
                    # layer itself is broken — drop the connection
                    break
                if req is None:
                    break  # EOF (one-shot clients just close)
                if not isinstance(req, dict):
                    # answered, not severed: the framed stream stays in
                    # sync for the client's next request
                    try:
                        write_frame(conn, {"ok": False,
                                           "error": "frame must be a "
                                                    "JSON object"},
                                    codec=codec)
                    except OSError:
                        break
                    continue
                if req.get("op") == "codec":
                    # per-connection codec negotiation: answered by the
                    # serve loop itself so EVERY framed wire (uds
                    # endpoint, sidecar, telemetry collector) speaks it
                    # uniformly. A pre-binary server answers this op
                    # with its handler's unknown-op error — the client
                    # then stays on JSON, loss-free.
                    offered = req.get("codecs")
                    picked = (_binary.CODEC_BINARY
                              if isinstance(offered, (list, tuple))
                              and _binary.CODEC_BINARY in offered
                              else _binary.CODEC_JSON)
                    _spans.codec_negotiated(picked)
                    try:
                        write_frame(conn, {"ok": True, "codec": picked},
                                    codec=codec)
                    except OSError:
                        break
                    continue
                ctx_seen = self._observe_ctx(req)
                try:
                    resp = self._handler(req)
                except Exception as e:  # answer, never desync the wire
                    log.exception("%s op failed: %r", self._name,
                                  req.get("op"))
                    resp = {"ok": False, "error": repr(e)}
                if self._decorate is not None:
                    try:
                        self._decorate(req, resp)
                    except Exception:  # pragma: no cover - defensive
                        log.exception("%s response decorator failed",
                                      self._name)
                if ctx_seen:
                    # echo a fresh stamp so the client's clock merges
                    # ours; context-less peers get the pre-context wire
                    # byte for byte
                    resp.setdefault(_context.CTX_KEY,
                                    _context.wire_stamp())
                try:
                    # answer in the codec the request arrived in —
                    # per-frame, stateless, so mixed-codec clients on
                    # one endpoint just work
                    n_out = write_frame(conn, resp, codec=codec)
                except TypeError:
                    # a handler value the binary codec cannot carry:
                    # degrade THIS response to JSON rather than desync
                    try:
                        n_out = write_frame(conn, resp)
                    except OSError:
                        break
                except OSError:
                    break
                _spans.wire_bytes(codec, str(req.get("op") or "frame"),
                                  n_in + n_out)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _observe_ctx(req: Dict) -> bool:
        """Merge a request frame's span-context clock; True when the
        request carried one (and observability is on)."""
        ctx = req.get(_context.CTX_KEY)
        if ctx is None or not _metrics.enabled():
            return False
        _context.observe_wire(ctx)
        return True
