"""FramedServer: the ONE keep-alive framed-JSON serve loop — now an
event-driven selector core with a small worker pool.

Three servers grew the same loop independently — the ``uds://`` event
endpoint (endpoint/uds.py), the search/knowledge sidecar (sidecar.py),
and the campaign supervisor's telemetry collector
(obs/federation.TelemetryServer). PR 10 consolidated them here; the
tenancy plane (doc/tenancy.md) forces the next step: one orchestrator
serving 8+ campaigns' connections must not spend one parked thread per
idle connection. The rewrite:

* ONE selector thread owns accept + reads for every connection and
  assembles frames incrementally — an idle connection costs a registry
  entry, not a thread;
* complete frames dispatch to a small fixed **worker pool** (decode,
  handler, reply). Per-connection FIFO order is preserved: one request
  in flight per connection, later frames queue behind it;
* ops that PARK by design (the long-poll ``poll`` op) hand off from
  the worker to a short-lived thread, so parked polls occupy exactly
  one thread per in-flight poll — never a pool slot. Beyond
  ``max_parked`` simultaneous parked ops the handler runs inline in
  the worker (bounded degradation, never an error).

The contract every framed wire shares is unchanged (one frame each
way, ``uint32-LE length + UTF-8 JSON`` — endpoint/agent.py's codec,
binary high-bit negotiated per connection, any number of
request/response pairs per connection):

* EOF or a codec/socket error drops the connection cleanly;
* a valid-JSON **non-object** frame is ANSWERED
  (``{"ok": false, ...}``) so the client's keep-alive stream stays in
  sync, never severed;
* an in-sync garbled payload (``wire.binary.garble``) is answered
  ``{"ok": false, "transient": true}`` — the client's bounded retry
  resends a clean copy;
* a handler exception is answered (``{"ok": false, "error": ...}``),
  logged, and never desyncs the wire;
* **span context** (obs/context.py): a request frame carrying ``ctx``
  has its Lamport clock merged before the handler runs, and the
  response echoes a fresh ``ctx`` stamp; context-less requests get
  byte-identical responses to the pre-context wire;
* per-connection ``codec`` negotiation is answered by the serve loop
  itself, uniformly across every framed wire;
* shutdown severs live connections (a parked long-poll must error and
  reconnect, not keep talking to a dead server), and ``sever()`` alone
  simulates crash death for the chaos harness.

Binding: :meth:`bind_unix` reclaims a listener-less stale socket inode
(probe-connect first; a live listener raises — stealing a served path
would silently split an event stream across two servers; a non-socket
file is never clobbered) and unlinks the path at shutdown;
:meth:`bind_tcp` sets ``SO_REUSEADDR`` so a hard-stopped server can
rebind its port immediately.
"""

from __future__ import annotations

import json
import os
import queue
import selectors
import socket
import stat
import struct
import threading
from collections import deque
from typing import Callable, Dict, Optional

from namazu_tpu.endpoint.agent import (BINARY_FRAME_FLAG, MAX_FRAME,
                                       write_frame)
from namazu_tpu.obs import context as _context
from namazu_tpu.obs import metrics as _metrics
from namazu_tpu.obs import spans as _spans
from namazu_tpu.signal import binary as _binary
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.framed")

#: handler(req dict) -> resp dict
Handler = Callable[[dict], dict]
#: decorate(req dict, resp dict) -> None — per-wire piggybacks (the
#: uds endpoint's table_version) applied after the handler, before send
Decorator = Callable[[dict, dict], None]

#: ops that park their handler by design: the long-poll family, plus
#: the tenancy lease ops ("release" waits up to 10s for its
#: namespace's flush to drain; "lease" may replay a journal). These
#: hand off from the worker pool to a per-request thread so a parked
#: op can never starve short ops (post_batch/ack/telemetry) of a pool
#: slot — a campaign winding down several serve slots at once must not
#: convoy every other tenant's wire.
DEFAULT_BLOCKING_OPS = frozenset({"poll", "lease", "release"})


def reclaim_stale_unix_socket(path: str, what: str = "server") -> None:
    """Unlink a socket inode left by a dead predecessor, IF no live
    listener answers a probe connect. A live listener raises (the path
    is being served); a non-socket path is left alone so the caller's
    bind fails loudly instead of clobbering someone's file."""
    try:
        st = os.stat(path)
    except OSError:
        return  # nothing there
    if not stat.S_ISSOCK(st.st_mode):
        return
    probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        probe.settimeout(0.2)
        try:
            probe.connect(path)
        except OSError:
            # no listener: stale — reclaim the path
            try:
                os.unlink(path)
            except OSError:
                pass
            return
    finally:
        try:
            probe.close()
        except OSError:
            pass
    raise RuntimeError(
        f"{what} path {path!r} already has a live listener "
        "(another process?); refusing to take it over")


class _Conn:
    """Per-connection state, owned by the selector thread except where
    noted."""

    __slots__ = ("sock", "rbuf", "wlock", "plock", "busy", "pending")

    #: pipelined-requests bound: a client that floods requests without
    #: reading replies is dropped rather than buffered without limit
    MAX_PENDING = 1024

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.rbuf = bytearray()
        #: serializes response writes (workers + poll threads)
        self.wlock = threading.Lock()
        #: guards busy/pending (selector thread + workers)
        self.plock = threading.Lock()
        self.busy = False
        self.pending: deque = deque()


class FramedServer:
    def __init__(self, handler: Handler, name: str = "framed",
                 decorate: Optional[Decorator] = None,
                 workers: int = 4,
                 blocking_ops=DEFAULT_BLOCKING_OPS,
                 max_parked: int = 256) -> None:
        self._handler = handler
        self._name = name
        self._decorate = decorate
        self._workers_n = max(1, int(workers))
        self._blocking_ops = frozenset(blocking_ops or ())
        self._server: Optional[socket.socket] = None
        self._selector_thread: Optional[threading.Thread] = None
        self._worker_threads: list = []
        self._work: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # parked-op budget: a Semaphore would block; a counter + cap
        # degrades to inline execution instead
        self._parked = 0
        self._parked_cap = max(1, int(max_parked))
        self._parked_lock = threading.Lock()
        # guards the wake-pipe fds: _wake() writes under it and the
        # selector thread nulls them under it before closing, so a
        # late shutdown() can never write into a closed (or recycled)
        # descriptor
        self._wake_lock = threading.Lock()
        self._wake_r: Optional[int] = None
        self._wake_w: Optional[int] = None
        #: AF_UNIX path when bound to one (unlinked at shutdown)
        self.path: Optional[str] = None

    # -- binding -----------------------------------------------------------

    def bind_unix(self, path: str, backlog: int = 64) -> None:
        reclaim_stale_unix_socket(path, what=self._name)
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(backlog)
        self._server = srv
        self.path = path

    def bind_tcp(self, host: str, port: int, backlog: int = 8) -> int:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(backlog)
        self._server = srv
        return srv.getsockname()[1]

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.getsockname()[1]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        assert self._server is not None, "bind before start"
        if self._selector_thread is not None:
            return
        self._wake_r, self._wake_w = os.pipe()
        for i in range(self._workers_n):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"{self._name}-worker-{i}",
                                 daemon=True)
            t.start()
            self._worker_threads.append(t)
        self._selector_thread = threading.Thread(
            target=self._selector_loop, name=f"{self._name}-select",
            daemon=True)
        self._selector_thread.start()

    def _wake(self) -> None:
        with self._wake_lock:
            w = self._wake_w
            if w is not None:
                try:
                    os.write(w, b"x")
                except OSError:
                    pass

    def shutdown(self) -> None:
        self._stop.set()
        srv, self._server = self._server, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass
        self._wake()
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for conn in conns:
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.sock.close()
            except OSError:
                pass
        for _ in self._worker_threads:
            self._work.put(None)
        if self.path is not None:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def sever(self) -> int:
        """Cut every live connection WITHOUT stopping the server — the
        chaos harness's in-process stand-in for kill -9: a parked
        client poll must error and reconnect, not keep talking to a
        dead process's handler thread."""
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        return len(conns)

    # -- selector core -----------------------------------------------------

    def _selector_loop(self) -> None:
        from namazu_tpu.obs import profiling

        profiling.tag_current_thread("wire")
        sel = selectors.DefaultSelector()
        srv = self._server
        if srv is None:
            return
        sel.register(srv, selectors.EVENT_READ, "accept")
        if self._wake_r is not None:
            sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        try:
            while not self._stop.is_set():
                try:
                    events = sel.select(timeout=1.0)
                except OSError:
                    return
                for key, _ in events:
                    kind = key.data
                    if kind == "accept":
                        self._accept(sel)
                    elif kind == "wake":
                        try:
                            os.read(self._wake_r, 4096)
                        except OSError:
                            pass
                    else:
                        self._readable(sel, kind)
        finally:
            try:
                sel.close()
            except OSError:
                pass
            with self._wake_lock:
                fds = (self._wake_r, self._wake_w)
                self._wake_r = self._wake_w = None
            for fd in fds:
                if fd is not None:
                    try:
                        os.close(fd)
                    except OSError:
                        pass

    def _accept(self, sel) -> None:
        srv = self._server
        if srv is None:
            return
        try:
            sock, _ = srv.accept()
        except OSError:
            return
        conn = _Conn(sock)
        with self._conns_lock:
            self._conns.add(conn)
        try:
            sel.register(sock, selectors.EVENT_READ, conn)
        except (OSError, ValueError):
            self._close_conn(None, conn)

    def _readable(self, sel, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(1 << 16)
        except OSError:
            self._close_conn(sel, conn)
            return
        if not chunk:
            self._close_conn(sel, conn)  # EOF
            return
        conn.rbuf += chunk
        while True:
            frame = self._extract_frame(conn)
            if frame is None:
                break
            if frame == "broken":
                self._close_conn(sel, conn)
                return
            codec, body = frame
            if not self._enqueue(conn, codec, body):
                self._close_conn(sel, conn)
                return

    def _extract_frame(self, conn: _Conn):
        """One complete frame from the connection buffer:
        ``(codec, body_bytes)``, ``None`` when incomplete, or
        ``"broken"`` when the framing layer itself is bad (oversized
        length — the drop-the-connection class)."""
        buf = conn.rbuf
        if len(buf) < 4:
            return None
        (length,) = struct.unpack("<I", bytes(buf[:4]))
        codec = _binary.CODEC_JSON
        if length & BINARY_FRAME_FLAG:
            codec = _binary.CODEC_BINARY
            length &= ~BINARY_FRAME_FLAG
        if length > MAX_FRAME:
            return "broken"
        if len(buf) < 4 + length:
            return None
        body = bytes(buf[4:4 + length])
        del buf[:4 + length]
        return codec, body

    def _enqueue(self, conn: _Conn, codec: str, body: bytes) -> bool:
        """Queue one raw frame for processing, preserving per-connection
        FIFO; False = the client pipelined past the bound (drop it)."""
        with conn.plock:
            if conn.busy:
                if len(conn.pending) >= conn.MAX_PENDING:
                    return False
                conn.pending.append((codec, body))
                return True
            conn.busy = True
        self._work.put((conn, codec, body))
        return True

    def _finish_task(self, conn: _Conn) -> None:
        """A request finished: start the next queued frame, or go idle."""
        with conn.plock:
            if conn.pending:
                codec, body = conn.pending.popleft()
            else:
                conn.busy = False
                return
        self._work.put((conn, codec, body))

    def _close_conn(self, sel, conn: _Conn) -> None:
        if sel is not None:
            try:
                sel.unregister(conn.sock)
            except (KeyError, OSError, ValueError):
                pass
        with self._conns_lock:
            self._conns.discard(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- workers -----------------------------------------------------------

    def _worker_loop(self) -> None:
        # profiling plane: a worker parked on the task queue has no
        # namazu frame on its stack — pin it to the wire plane so its
        # samples classify (obs/profiling.py taxonomy)
        from namazu_tpu.obs import profiling

        profiling.tag_current_thread("wire")
        while True:
            task = self._work.get()
            if task is None:
                return
            conn, codec, body = task
            try:
                self._process(conn, codec, body)
            except Exception:  # pragma: no cover - defensive
                log.exception("%s frame processing failed", self._name)
                self._finish_task(conn)

    def _send(self, conn: _Conn, resp: dict, codec: str) -> int:
        with conn.wlock:
            return write_frame(conn.sock, resp, codec=codec)

    def _process(self, conn: _Conn, codec: str, body: bytes) -> None:
        """Decode one frame and answer it (worker thread)."""
        try:
            if codec == _binary.CODEC_BINARY:
                req = _binary.loads(body)
            else:
                req = json.loads(body)
        except ValueError as e:
            # the frame's length prefix was intact, only the payload
            # was garbled: the stream is still in sync — answer it
            # (transient: the client's bounded retry resends a clean
            # copy), never sever the keep-alive connection
            # (wire.binary.garble)
            try:
                self._send(conn, {"ok": False, "transient": True,
                                  "error": f"undecodable {codec} "
                                           f"frame: {e}"},
                           _binary.CODEC_JSON)
            except OSError:
                pass
            self._finish_task(conn)
            return
        if not isinstance(req, dict):
            # answered, not severed: the framed stream stays in sync
            # for the client's next request
            try:
                self._send(conn, {"ok": False,
                                  "error": "frame must be a JSON "
                                           "object"}, codec)
            except OSError:
                pass
            self._finish_task(conn)
            return
        if req.get("op") == "codec":
            # per-connection codec negotiation: answered by the serve
            # loop itself so EVERY framed wire (uds endpoint, sidecar,
            # telemetry collector) speaks it uniformly. A pre-binary
            # server answers this op with its handler's unknown-op
            # error — the client then stays on JSON, loss-free.
            offered = req.get("codecs")
            picked = (_binary.CODEC_BINARY
                      if isinstance(offered, (list, tuple))
                      and _binary.CODEC_BINARY in offered
                      else _binary.CODEC_JSON)
            _spans.codec_negotiated(picked)
            try:
                self._send(conn, {"ok": True, "codec": picked}, codec)
            except OSError:
                pass
            self._finish_task(conn)
            return
        if req.get("op") in self._blocking_ops:
            # long-poll class: hand off so the pool slot frees NOW —
            # one short-lived thread per in-flight parked op, bounded
            # by max_parked (beyond it, run inline: degraded latency
            # for short ops, never an error)
            with self._parked_lock:
                over = self._parked >= self._parked_cap
                if not over:
                    self._parked += 1
            if not over:
                threading.Thread(
                    target=self._answer_parked,
                    args=(conn, req, codec, len(body)),
                    name=f"{self._name}-poll", daemon=True).start()
                return
        self._answer(conn, req, codec, len(body))
        self._finish_task(conn)

    def _answer_parked(self, conn: _Conn, req: dict, codec: str,
                       n_in: int) -> None:
        try:
            self._answer(conn, req, codec, n_in)
        finally:
            with self._parked_lock:
                self._parked -= 1
            self._finish_task(conn)

    def _answer(self, conn: _Conn, req: dict, codec: str,
                n_in: int) -> None:
        ctx_seen = self._observe_ctx(req)
        try:
            resp = self._handler(req)
        except Exception as e:  # answer, never desync the wire
            log.exception("%s op failed: %r", self._name,
                          req.get("op"))
            resp = {"ok": False, "error": repr(e)}
        if self._decorate is not None:
            try:
                self._decorate(req, resp)
            except Exception:  # pragma: no cover - defensive
                log.exception("%s response decorator failed",
                              self._name)
        if ctx_seen:
            # echo a fresh stamp so the client's clock merges ours;
            # context-less peers get the pre-context wire byte for byte
            resp.setdefault(_context.CTX_KEY, _context.wire_stamp())
        try:
            # answer in the codec the request arrived in — per-frame,
            # stateless, so mixed-codec clients on one endpoint work
            n_out = self._send(conn, resp, codec)
        except TypeError:
            # a handler value the binary codec cannot carry: degrade
            # THIS response to JSON rather than desync
            try:
                n_out = self._send(conn, resp, _binary.CODEC_JSON)
            except OSError:
                return
        except OSError:
            return
        _spans.wire_bytes(codec, str(req.get("op") or "frame"),
                          n_in + n_out)

    @staticmethod
    def _observe_ctx(req: Dict) -> bool:
        """Merge a request frame's span-context clock; True when the
        request carried one (and observability is on)."""
        ctx = req.get(_context.CTX_KEY)
        if ctx is None or not _metrics.enabled():
            return False
        _context.observe_wire(ctx)
        return True
