"""Guest-agent endpoint: framed TCP for in-process agents (C/C++/Java).

Capability parity with /root/reference/nmz/endpoint/pb (pbendpoint.go:
99-160) and its length-prefixed protobuf codec (util/pb/pbutil.go:28-107).
Redesign: frames are ``uint32-LE length + UTF-8 JSON`` carrying exactly the
same wire dicts as the REST endpoint — one codec for every transport, no
generated protobuf stubs, and a guest agent implementable in ~200 lines of
dependency-free C++ (native/agent/). The reference's JVM/byteman agent
equivalent speaks this protocol from a byteman Helper the same way.

Per-connection: a reader thread decodes event frames and posts them to the
hub; actions for entities seen on a connection are written back as frames
(the agent correlates by ``event_uuid``, like every transceiver).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Dict, Optional

from namazu_tpu.endpoint.hub import Endpoint
from namazu_tpu.signal import binary as _binary
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.agent")

MAX_FRAME = 16 * 1024 * 1024

#: high bit of the length prefix marks a binary-codec frame body
#: (signal/binary.py). A pre-binary reader sees a length far past
#: MAX_FRAME and drops the connection — which is why clients never
#: send binary before the per-connection ``codec`` negotiation
#: succeeded (doc/performance.md "Binary wire + sharded edge").
BINARY_FRAME_FLAG = 0x80000000


class FramePayloadError(ValueError):
    """The frame's LENGTH prefix was intact and its body fully read,
    but the payload failed to decode (garbled binary, malformed JSON).
    The stream is still in sync — a server answers this per frame
    instead of severing the keep-alive connection."""


def write_frame(sock: socket.socket, payload: dict,
                codec: str = "json") -> int:
    """Write one frame; returns the payload byte count."""
    if codec == _binary.CODEC_BINARY:
        data = _binary.dumps(payload)
        header = struct.pack("<I", len(data) | BINARY_FRAME_FLAG)
    else:
        data = json.dumps(payload).encode()
        header = struct.pack("<I", len(data))
    sock.sendall(header + data)
    return len(data)


def write_raw_frame(sock: socket.socket, data: bytes,
                    binary: bool = False) -> None:
    """Ship pre-encoded (possibly deliberately corrupted — the
    ``wire.binary.garble`` chaos seam) frame bytes under a well-formed
    length prefix."""
    length = len(data) | (BINARY_FRAME_FLAG if binary else 0)
    sock.sendall(struct.pack("<I", length) + data)


def read_frame(sock: socket.socket) -> Optional[dict]:
    payload, _, _ = read_frame_ex(sock)
    return payload


def read_frame_ex(sock: socket.socket):
    """One frame -> ``(payload, codec, nbytes)``; ``(None, "json", 0)``
    on EOF. Raises :class:`FramePayloadError` for an in-sync garbled
    payload, :class:`SignalError` for a broken framing layer."""
    header = _read_exact(sock, 4)
    if header is None:
        return None, _binary.CODEC_JSON, 0
    (length,) = struct.unpack("<I", header)
    codec = _binary.CODEC_JSON
    if length & BINARY_FRAME_FLAG:
        codec = _binary.CODEC_BINARY
        length &= ~BINARY_FRAME_FLAG
    if length > MAX_FRAME:
        raise SignalError(f"frame too large: {length}")
    body = _read_exact(sock, length)
    if body is None:
        return None, codec, 0
    try:
        if codec == _binary.CODEC_BINARY:
            return _binary.loads(body), codec, length
        return json.loads(body), codec, length
    except ValueError as e:
        raise FramePayloadError(f"undecodable {codec} frame: {e}") \
            from None


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class AgentEndpoint(Endpoint):
    NAME = "agent"

    def __init__(self, port: int = 10081, host: str = "127.0.0.1"):
        self._host = host
        self._port = port
        self._server: Optional[socket.socket] = None
        self._conns: Dict[str, socket.socket] = {}  # entity -> connection
        self._conn_lock = threading.Lock()
        self._stop = threading.Event()

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.getsockname()[1]
        return self._port

    def start(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._host, self._port))
        srv.listen(32)
        # a thread parked in accept() would keep the listening fd alive past
        # close(); a short timeout lets the loop observe _stop and close the
        # server from its own thread
        srv.settimeout(0.2)
        self._server = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="agent-accept", daemon=True)
        self._accept_thread.start()
        log.info("agent endpoint on %s:%d", self._host, self.port)

    def shutdown(self) -> None:
        self._stop.set()
        # block until the accept loop has really closed the listening fd,
        # so a back-to-back experiment run can rebind the port
        t = getattr(self, "_accept_thread", None)
        if t is not None:
            t.join(timeout=2.0)
        with self._conn_lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()

    def _accept_loop(self) -> None:
        srv = self._server
        try:
            while not self._stop.is_set():
                try:
                    conn, addr = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                conn.settimeout(None)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                threading.Thread(
                    target=self._conn_loop, args=(conn,),
                    name=f"agent-conn-{addr[1]}", daemon=True,
                ).start()
        finally:
            try:
                srv.close()
            except OSError:
                pass

    def _conn_loop(self, conn: socket.socket) -> None:
        entities = set()
        try:
            while not self._stop.is_set():
                frame = read_frame(conn)
                if frame is None:
                    return
                try:
                    sig = signal_from_jsonable(frame)
                except (SignalError, KeyError, ValueError) as e:
                    log.warning("agent: bad frame: %s", e)
                    continue
                if not isinstance(sig, Event):
                    log.warning("agent: non-event frame %r", sig)
                    continue
                ent = sig.entity_id
                if ent not in entities:
                    entities.add(ent)
                    with self._conn_lock:
                        self._conns[ent] = conn
                self.hub.post_event(sig, self.NAME)
        finally:
            with self._conn_lock:
                for ent in entities:
                    if self._conns.get(ent) is conn:
                        del self._conns[ent]
            try:
                conn.close()
            except OSError:
                pass

    def send_action(self, action: Action) -> None:
        with self._conn_lock:
            conn = self._conns.get(action.entity_id)
        if conn is None:
            log.warning("agent: no connection for entity %s", action.entity_id)
            return
        try:
            write_frame(conn, action.to_jsonable())
        except OSError as e:
            log.warning("agent: send to %s failed: %s", action.entity_id, e)
