"""EndpointHub: merge inbound events, route outbound actions.

Parity: the endpoint mux (/root/reference/nmz/endpoint/endpoint.go:63-144) —
``registerEntityEndpointType`` + ``dispatchAction``. Transports register
themselves; the hub learns entity->transport on each inbound event and uses
that table to dispatch actions. Unroutable actions are dropped with a log
line (the reference panics; dropping is friendlier for long experiments).
"""

from __future__ import annotations

import queue
from typing import Dict, List, Optional

from namazu_tpu import obs, tenancy
from namazu_tpu.tenancy.shard import ShardedRoutes
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.control import Control
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint")


class Endpoint:
    """Interface for a transport endpoint."""

    NAME = "abstract"

    def attach(self, hub: "EndpointHub") -> None:
        self.hub = hub

    def start(self) -> None:
        pass

    def send_action(self, action: Action) -> None:
        raise NotImplementedError

    def send_actions(self, actions: List[Action]) -> None:
        """Dispatch a batch in order. Endpoints with a cheaper bulk path
        (REST: one queue-lock acquisition per entity) override this."""
        for action in actions:
            self.send_action(action)

    def shutdown(self) -> None:
        pass


class EndpointHub:
    def __init__(self, n_shards: int = ShardedRoutes.DEFAULT_SHARDS) -> None:
        self.event_queue: "queue.Queue[Event]" = queue.Queue()
        self.control_queue: "queue.Queue[Control]" = queue.Queue()
        # the zero-RTT dispatch plane's table source (policy/
        # edge_table.py TablePublisher), attached by the orchestrator
        # when its policy publishes one; None = no table plane (non-
        # table policies) — endpoints then advertise no version and
        # serve no table
        self.table_publisher = None
        # tenancy plane (doc/tenancy.md): the RunRegistry a
        # TenantOrchestrator attaches so the wire endpoints can answer
        # lease/renew/release ops; None on single-run orchestrators
        self.run_registry = None
        self._endpoints: Dict[str, Endpoint] = {}
        # routing + liveness + one-shot-unroutable-warning bookkeeping,
        # sharded by fnv64a(namespace:entity) so N tenant namespaces
        # never convoy on one lock (tenancy/shard.py). Keys are
        # composite route keys; the default namespace's keys are bare
        # entity ids (the pre-tenancy shape).
        self._routes = ShardedRoutes(n_shards)

    # -- endpoint registration ------------------------------------------

    def add_endpoint(self, ep: Endpoint) -> None:
        ep.attach(self)
        self._endpoints[ep.NAME] = ep

    def endpoint(self, name: str) -> Optional[Endpoint]:
        return self._endpoints.get(name)

    def start(self) -> None:
        for ep in self._endpoints.values():
            ep.start()

    def shutdown(self) -> None:
        for ep in self._endpoints.values():
            ep.shutdown()

    # -- inbound (transports call these) --------------------------------

    def _note_inbound(self, event: Event, endpoint_name: str) -> None:
        """Routing + liveness bookkeeping for one inbound event."""
        prev = self._routes.note_inbound(
            tenancy.signal_route_key(event), endpoint_name)
        if prev is not None:
            log.warning(
                "entity %s moved endpoint %s -> %s",
                event.entity_id, prev, endpoint_name,
            )

    def _note_inbound_batch(self, events, endpoint_name: str) -> None:
        """Batch routing/liveness bookkeeping: one lock acquisition per
        TOUCHED SHARD for the whole batch (pre-tenancy: one global
        lock)."""
        moves = self._routes.note_inbound_many(
            [tenancy.signal_route_key(ev) for ev in events],
            endpoint_name)
        for key, prev in moves:
            _, entity = tenancy.split_route_key(key)
            log.warning("entity %s moved endpoint %s -> %s",
                        entity, prev, endpoint_name)

    @staticmethod
    def _note_context(event: Event) -> None:
        """Causality plane (obs/context.py): merge an inbound event's
        logical clock into this process's (cross-process order without
        clock trust), fill the run id a remote mint couldn't know, and
        mint a context at interception for pre-context clients — one
        enabled-check + dict work per event, nothing when disabled."""
        ctx = obs.context.ensure(event)
        if ctx is None:
            return
        obs.context.observe(ctx)
        if not ctx.get("r"):
            ns = getattr(event, "_ns", "")
            run_id = (obs.recorder.recorder().pinned_run_id(ns) if ns
                      else obs.recorder.current_run_id())
            if run_id:
                ctx["r"] = run_id

    @staticmethod
    def _note_context_batch(events, extra_lc: int = 0) -> None:
        """Batch face of :meth:`_note_context`: ONE clock merge (the
        max of the inbound stamps — Lamport merge is max-monotone, so
        folding a batch through its max is exact) instead of a lock
        round per event. ``extra_lc`` folds in op-level stamps riding
        beside the events (the edge's per-chunk decision stamp)."""
        if not obs.metrics.enabled():
            return
        run_id = obs.recorder.current_run_id() or ""
        rec = obs.recorder.recorder()
        lc_of = obs.context.lc_of
        max_lc = int(extra_lc)
        for event in events:
            ctx = obs.context.ensure(event)
            if ctx is None:
                continue
            lc = lc_of(ctx)
            if lc > max_lc:
                max_lc = lc
            if not ctx.get("r"):
                ns = getattr(event, "_ns", "")
                rid = (rec.pinned_run_id(ns) or "") if ns else run_id
                if rid:
                    ctx["r"] = rid
        if max_lc > 0:
            obs.context.clock().observe(max_lc)

    def post_event(self, event: Event, endpoint_name: str) -> None:
        self._note_inbound(event, endpoint_name)
        event.mark_arrived()
        self._note_context(event)
        obs.mark(event, "intercepted")
        obs.event_intercepted(endpoint_name, event.entity_id)
        obs.record_intercepted(event, endpoint_name)
        self.event_queue.put(event)

    def post_events(self, events: List[Event], endpoint_name: str) -> None:
        """Batch ingress (the REST batch POST route): one ``_lock``
        acquisition for the whole batch's routing bookkeeping, events
        enqueued in arrival order."""
        if not events:
            return
        self._note_inbound_batch(events, endpoint_name)
        self._note_context_batch(events)
        for event in events:
            event.mark_arrived()
            obs.mark(event, "intercepted")
            obs.event_intercepted(endpoint_name, event.entity_id)
            obs.record_intercepted(event, endpoint_name)
            self.event_queue.put(event)
        obs.event_batch("ingress", len(events))

    def post_edge_backhaul(self, items, endpoint_name: str) -> None:
        """Asynchronous backhaul of edge-decided events
        (doc/performance.md "Zero-RTT dispatch"): ``items`` is a list
        of ``(event, decision)`` pairs the edge already dispatched
        against a published table. Routing/liveness bookkeeping is
        identical to :meth:`post_events` (an edge entity's backhaul
        keeps its watchdog liveness fresh), the lifecycle stamps come
        from the EDGE's clocks (same host, shared CLOCK_MONOTONIC), and
        the tagged events ride the normal event queue so the
        orchestrator's single event loop reconciles them — recorder,
        analytics, and the collected trace see exactly what a central
        run records, modulo the ``decision_source="edge"`` tag."""
        if not items:
            return
        self._note_inbound_batch([ev for ev, _ in items], endpoint_name)
        # the edge's per-chunk decision stamp (added at backhaul
        # serialization) merges too — the reconcile point is causally
        # after the decision, whatever the wall clocks say
        extra_lc = 0
        for _, decision in items:
            lc = decision.get("lc")
            if isinstance(lc, int) and lc > extra_lc:
                extra_lc = lc
        self._note_context_batch([ev for ev, _ in items],
                                 extra_lc=extra_lc)
        per_entity: Dict[str, int] = {}
        put = self.event_queue.put
        for event, decision in items:
            event.mark_arrived(now=decision.get("arrived_wall"))
            per_entity[event.entity_id] = \
                per_entity.get(event.entity_id, 0) + 1
            # the tag the orchestrator's event loop partitions on: an
            # edge-decided event must never reach the policy (it was
            # already decided AND dispatched at the edge). The full
            # recorder write (obs.record_edge) happens there too, in
            # ONE pass per event — not stage-by-stage here.
            event._edge_decision = decision
            event._edge_endpoint = endpoint_name
            put(event)
        for entity, n in per_entity.items():
            obs.event_intercepted(endpoint_name, entity, n)
            obs.edge_decision(entity, n)
        obs.event_batch("backhaul", len(items))

    # -- zero-RTT table plane (doc/performance.md) ----------------------

    def _ns_table_publisher(self, ns: str):
        """The table publisher serving namespace ``ns``: a leased run's
        OWN policy publisher when it has one (doc/tenancy.md
        "Per-namespace tables" — one tenant's edges must never decide
        against the process-default policy's table), else the
        process-default publisher for the default namespace, else
        None."""
        if ns and self.run_registry is not None:
            run = self.run_registry.namespace(ns)
            if run is not None:
                return getattr(run.policy, "table_publisher", None)
            return None  # unknown/expired tenant: no table, no version
        return self.table_publisher

    def table_version(self, ns: str = "") -> Optional[int]:
        """The published table's current version for namespace ``ns``
        ("" = the process default), None when that namespace has no
        table plane at all."""
        pub = self._ns_table_publisher(ns)
        return None if pub is None else pub.version

    def table_doc(self, ns: str = ""):
        """``(version, doc_or_None)`` of the table published for
        namespace ``ns``; (0, None) without a table plane."""
        pub = self._ns_table_publisher(ns)
        return (0, None) if pub is None else pub.current()

    def post_control(self, control: Control) -> None:
        self.control_queue.put(control)

    # -- outbound (orchestrator calls this) -----------------------------

    def send_action(self, action: Action) -> None:
        name, first_drop = self._routes.resolve(
            tenancy.signal_route_key(action))
        if name is None:
            self._drop_unroutable(action, first_drop)
            return
        self._endpoints[name].send_action(action)

    def _drop_unroutable(self, action: Action, first_drop: bool) -> None:
        obs.action_unroutable(action.entity_id)
        if first_drop:
            log.warning(
                "no endpoint for entity %s; dropping %r (repeats "
                "counted in %s, logged at DEBUG)",
                action.entity_id, action, "nmz_actions_unroutable_total")
        else:
            log.debug("no endpoint for entity %s; dropping %r",
                      action.entity_id, action)

    def send_actions(self, actions: List[Action]) -> None:
        """Batch dispatch (the orchestrator's action loop drains its
        merged queue greedily): routes for the whole batch are resolved
        under ONE ``_lock`` acquisition, then each endpoint receives its
        sub-batch in order via its own bulk path. Size-1 batches take
        this path too so the dispatch-occupancy histogram sees them —
        "batches are always full" must be falsifiable from the metric."""
        if not actions:
            return
        routed: Dict[str, List[Action]] = {}
        drops = []
        resolved = self._routes.resolve_many(
            [tenancy.signal_route_key(a) for a in actions])
        for action, (name, first) in zip(actions, resolved):
            if name is None:
                drops.append((action, first))
            else:
                routed.setdefault(name, []).append(action)
        for action, first_drop in drops:
            self._drop_unroutable(action, first_drop)
        n_routed = 0
        for name, batch in routed.items():
            self._endpoints[name].send_actions(batch)
            n_routed += len(batch)
        if n_routed:
            # dropped actions were not dispatched; they must not inflate
            # the occupancy histogram
            obs.event_batch("dispatch", n_routed)

    # -- liveness (the orchestrator's watchdog reads these) -------------

    def last_seen(self) -> Dict[str, float]:
        """Snapshot of route key -> monotonic last-inbound-event time
        (default-namespace keys are bare entity ids)."""
        return self._routes.last_seen()

    def routes(self) -> Dict[str, str]:
        """Snapshot of the route-key -> endpoint routing table (the
        event journal persists it so recovery can restore dispatch
        routes)."""
        return self._routes.routes()

    def forget_namespace(self, ns: str) -> int:
        """Drop one namespace's routing/liveness state (a released or
        reclaimed tenant; doc/tenancy.md)."""
        return self._routes.forget_namespace(ns)

    def stalled_entities(self, timeout_s: float,
                         now: Optional[float] = None) -> Dict[str, float]:
        """Route keys silent for more than ``timeout_s``, with their
        silence duration."""
        return self._routes.stalled(timeout_s, now=now)
