"""EndpointHub: merge inbound events, route outbound actions.

Parity: the endpoint mux (/root/reference/nmz/endpoint/endpoint.go:63-144) —
``registerEntityEndpointType`` + ``dispatchAction``. Transports register
themselves; the hub learns entity->transport on each inbound event and uses
that table to dispatch actions. Unroutable actions are dropped with a log
line (the reference panics; dropping is friendlier for long experiments).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional

from namazu_tpu import obs
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.control import Control
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint")


class Endpoint:
    """Interface for a transport endpoint."""

    NAME = "abstract"

    def attach(self, hub: "EndpointHub") -> None:
        self.hub = hub

    def start(self) -> None:
        pass

    def send_action(self, action: Action) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class EndpointHub:
    def __init__(self) -> None:
        self.event_queue: "queue.Queue[Event]" = queue.Queue()
        self.control_queue: "queue.Queue[Control]" = queue.Queue()
        self._endpoints: Dict[str, Endpoint] = {}
        self._entity_route: Dict[str, str] = {}
        self._lock = threading.Lock()

    # -- endpoint registration ------------------------------------------

    def add_endpoint(self, ep: Endpoint) -> None:
        ep.attach(self)
        self._endpoints[ep.NAME] = ep

    def endpoint(self, name: str) -> Optional[Endpoint]:
        return self._endpoints.get(name)

    def start(self) -> None:
        for ep in self._endpoints.values():
            ep.start()

    def shutdown(self) -> None:
        for ep in self._endpoints.values():
            ep.shutdown()

    # -- inbound (transports call these) --------------------------------

    def post_event(self, event: Event, endpoint_name: str) -> None:
        with self._lock:
            prev = self._entity_route.get(event.entity_id)
            if prev is not None and prev != endpoint_name:
                log.warning(
                    "entity %s moved endpoint %s -> %s",
                    event.entity_id, prev, endpoint_name,
                )
            self._entity_route[event.entity_id] = endpoint_name
        event.mark_arrived()
        obs.mark(event, "intercepted")
        obs.event_intercepted(endpoint_name, event.entity_id)
        obs.record_intercepted(event, endpoint_name)
        self.event_queue.put(event)

    def post_control(self, control: Control) -> None:
        self.control_queue.put(control)

    # -- outbound (orchestrator calls this) -----------------------------

    def send_action(self, action: Action) -> None:
        with self._lock:
            name = self._entity_route.get(action.entity_id)
        if name is None:
            log.warning("no endpoint for entity %s; dropping %r", action.entity_id, action)
            return
        self._endpoints[name].send_action(action)
