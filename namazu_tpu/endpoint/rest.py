"""REST endpoint: the HTTP wire for out-of-process inspectors.

Capability parity with /root/reference/nmz/endpoint/rest
(restendpoint.go:71-223, queue/restqueue.go:20-135), API root ``/api/v3``
(util/rest/restutil.go:16):

* ``POST /api/v3/events/{entity}/{uuid}``   — submit an event (non-blocking)
* ``GET /api/v3/actions/{entity}``          — long-poll the next action;
  idempotent (RFC 7231): repeated GETs return the same head until deleted;
  a newer concurrent poll supersedes an older one (the older returns 204)
* ``DELETE /api/v3/actions/{entity}/{uuid}``— acknowledge/remove an action
* ``POST /api/v3/control?op=enableOrchestration|disableOrchestration``

Operator surface at the server root (not under the API root — that is
the inspector wire): ``GET /metrics`` + ``/metrics.json`` (PR 1),
``GET /healthz`` (liveness + active run id), ``GET /traces`` (recorded
run summaries), ``GET /traces/<run_id>`` (Chrome-trace JSON;
``?format=ndjson`` for the diffable line format), and
``GET /analytics`` (cross-run experiment statistics, ``?format=json``
default or ``ndjson``) — doc/observability.md.

Implementation: stdlib ThreadingHTTPServer — one thread per in-flight
request, which long-polling requires anyway; no third-party HTTP stack.
"""

from __future__ import annotations

import json
import re
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse, parse_qs

from namazu_tpu import obs
from namazu_tpu.endpoint.hub import Endpoint
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.control import Control, ControlOp
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.rest")

API_ROOT = "/api/v3"

_EVENTS_RE = re.compile(rf"^{API_ROOT}/events/([^/]+)/([^/]+)$")
_ACTIONS_RE = re.compile(rf"^{API_ROOT}/actions/([^/]+)(?:/([^/]+))?$")
_CONTROL_RE = re.compile(rf"^{API_ROOT}/control$")
_TRACES_RE = re.compile(r"^/traces(?:/([^/]+))?$")


class ActionQueue:
    """Per-entity deletable action queue with blocking peek.

    Parity: /root/reference/nmz/endpoint/rest/queue/restqueue.go:20-135 —
    ``peek`` blocks until non-empty; a newer concurrent peek supersedes the
    older one; ``delete`` acknowledges by uuid.
    """

    def __init__(self) -> None:
        self._items: List[Action] = []
        self._cond = threading.Condition()
        self._peek_gen = 0

    def put(self, action: Action) -> None:
        with self._cond:
            self._items.append(action)
            self._cond.notify_all()

    def peek(self, timeout: float = 30.0) -> Optional[Action]:
        """Return (without removing) the head action, blocking up to
        ``timeout``. Returns None on timeout or when superseded by a newer
        peek."""
        with self._cond:
            self._peek_gen += 1
            my_gen = self._peek_gen
            self._cond.notify_all()  # wake any older poller so it can yield
            end = threading.TIMEOUT_MAX if timeout is None else None
            import time as _time

            deadline = None if end else _time.monotonic() + timeout
            while True:
                if self._items:
                    return self._items[0]
                if my_gen != self._peek_gen:
                    return None  # superseded
                remaining = None if deadline is None else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)

    def delete(self, uuid: str) -> Optional[Action]:
        """Remove and return the action with ``uuid``, or None."""
        with self._cond:
            for i, a in enumerate(self._items):
                if a.uuid == uuid:
                    del self._items[i]
                    self._cond.notify_all()
                    return a
            return None

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)


class RestEndpoint(Endpoint):
    NAME = "rest"

    def __init__(self, port: int = 10080, host: str = "127.0.0.1",
                 poll_timeout: float = 30.0):
        self._host = host
        self._port = port
        self.poll_timeout = poll_timeout
        self._queues: Dict[str, ActionQueue] = {}
        self._queues_lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_mono = time.monotonic()  # /healthz uptime anchor
        # event-POST dedup ring: the transceiver retries a POST whose
        # 200 was lost in flight (doc/robustness.md), so an uuid seen
        # twice means the first attempt already reached the hub — ack
        # without re-posting, or one network blip doubles an event in
        # the trace. Bounded: uuids are unique per event, so a small
        # recent window is enough to cover the retry horizon.
        self._seen_event_uuids: "OrderedDict[str, None]" = OrderedDict()
        self._seen_lock = threading.Lock()

    _SEEN_EVENT_CAP = 4096

    def note_event_uuid(self, uuid: str) -> bool:
        """Record an inbound event uuid; True if it was already seen
        (i.e. this POST is a retry duplicate)."""
        with self._seen_lock:
            if uuid in self._seen_event_uuids:
                return True
            self._seen_event_uuids[uuid] = None
            while len(self._seen_event_uuids) > self._SEEN_EVENT_CAP:
                self._seen_event_uuids.popitem(last=False)
            return False

    # -- lifecycle -------------------------------------------------------

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.server_address[1]
        return self._port

    def start(self) -> None:
        endpoint = self
        self._started_mono = time.monotonic()

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route to our logger
                log.debug("http: " + fmt, *args)

            def _reply(self, code: int, body: Optional[dict] = None) -> None:
                data = json.dumps(body).encode() if body is not None else b""
                self._reply_raw(code, data, "application/json")

            def _reply_raw(self, code: int, data: bytes,
                           content_type: str) -> None:
                obs.rest_request(self.command, code)
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                if data:
                    self.wfile.write(data)

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(length) if length else b""

            def do_POST(self) -> None:
                url = urlparse(self.path)
                m = _EVENTS_RE.match(url.path)
                if m:
                    return self._post_event(m.group(1), m.group(2))
                if _CONTROL_RE.match(url.path):
                    return self._post_control(parse_qs(url.query))
                self._reply(404, {"error": f"no route {url.path}"})

            def _post_event(self, entity: str, uuid: str) -> None:
                try:
                    sig = signal_from_jsonable(json.loads(self._read_body()))
                except (SignalError, ValueError) as e:
                    return self._reply(400, {"error": str(e)})
                if not isinstance(sig, Event):
                    return self._reply(400, {"error": "signal is not an event"})
                if sig.entity_id != entity or sig.uuid != uuid:
                    return self._reply(
                        400,
                        {"error": "url entity/uuid do not match event body"},
                    )
                if endpoint.note_event_uuid(sig.uuid):
                    # retry of a POST whose 200 was lost: the event is
                    # already in the hub — idempotent ack
                    return self._reply(200, {"duplicate": True})
                endpoint.hub.post_event(sig, endpoint.NAME)
                self._reply(200, {})

            def _post_control(self, query: Dict[str, list]) -> None:
                ops = query.get("op") or []
                try:
                    op = ControlOp(ops[0] if ops else "")
                except ValueError:
                    return self._reply(
                        400, {"error": f"bad op {ops!r}; known: "
                              f"{[o.value for o in ControlOp]}"}
                    )
                endpoint.hub.post_control(Control(op))
                self._reply(200, {})

            def do_GET(self) -> None:
                url = urlparse(self.path)
                if url.path == "/metrics":
                    # Prometheus text exposition of the process registry
                    return self._reply_raw(
                        200, obs.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                if url.path == "/metrics.json":
                    return self._reply(200, obs.registry_jsonable())
                if url.path == "/healthz":
                    return self._reply(200, {
                        "status": "ok",
                        "run_id": obs.current_run_id(),
                        "uptime_s": round(
                            time.monotonic() - endpoint._started_mono, 3),
                        "endpoint": endpoint.NAME,
                    })
                if url.path == "/analytics":
                    return self._get_analytics(parse_qs(url.query))
                m = _TRACES_RE.match(url.path)
                if m:
                    return self._get_traces(m.group(1), parse_qs(url.query))
                m = _ACTIONS_RE.match(url.path)
                if not (m and m.group(2) is None):
                    return self._reply(404, {"error": f"no route {url.path}"})
                entity = m.group(1)
                action = endpoint._queue_for(entity).peek(endpoint.poll_timeout)
                if action is None:
                    return self._reply(204)
                self._reply(200, action.to_jsonable())

            def _get_analytics(self, query) -> None:
                """Experiment-analytics surface (obs/analytics.py): the
                registered storage's cross-run statistics joined with
                this process's recorded runs — the same payload
                ``nmz-tpu tools report`` renders."""
                fmt = (query.get("format") or ["json"])[0]
                if fmt not in ("json", "ndjson"):
                    return self._reply(
                        400, {"error": f"unknown format {fmt!r}; known: "
                              "json, ndjson"})
                # top/window mirror the CLI's --top/--window so a remote
                # `tools report --url` request is not silently computed
                # with different parameters than a local one
                params = {}
                for name, default in (
                        ("top", obs.analytics.DEFAULT_TOP),
                        ("window", obs.analytics.DEFAULT_WINDOW)):
                    raw = (query.get(name) or [None])[0]
                    try:
                        params[name] = default if raw is None \
                            else max(1, int(raw))
                    except ValueError:
                        return self._reply(
                            400, {"error": f"bad {name}={raw!r} "
                                  "(want a positive integer)"})
                try:
                    payload = obs.analytics_payload(**params)
                except Exception as e:  # never let a stats bug kill ops
                    log.exception("analytics payload failed")
                    return self._reply(
                        500, {"error": f"analytics failed: {e}"})
                if fmt == "ndjson":
                    return self._reply_raw(
                        200, obs.report.render_ndjson(payload).encode(),
                        "application/x-ndjson")
                self._reply(200, payload)

            def _get_traces(self, run_id, query) -> None:
                """Flight-recorder surface: run list, or one run as
                Chrome-trace JSON / NDJSON (obs/export.py)."""
                if run_id is None:
                    return self._reply(200, {"runs": obs.trace_summaries()})
                run = obs.trace_run(run_id)
                if run is None:
                    return self._reply(
                        404, {"error": f"no recorded run {run_id}"})
                fmt = (query.get("format") or ["chrome"])[0]
                if fmt == "ndjson":
                    return self._reply_raw(
                        200, obs.export.to_ndjson(run).encode(),
                        "application/x-ndjson")
                if fmt != "chrome":
                    return self._reply(
                        400, {"error": f"unknown format {fmt!r}; known: "
                              "chrome, ndjson"})
                self._reply(200, obs.export.chrome_trace(run))

            def do_DELETE(self) -> None:
                url = urlparse(self.path)
                m = _ACTIONS_RE.match(url.path)
                if not (m and m.group(2)):
                    return self._reply(404, {"error": f"no route {url.path}"})
                entity, uuid = m.group(1), m.group(2)
                action = endpoint._queue_for(entity).delete(uuid)
                if action is not None:
                    obs.mark(action, "acked")
                    obs.record_acked(action)
                    obs.rest_ack(entity, obs.latency(action, "dispatched"))
                    self._reply(200, {})
                else:
                    self._reply(404, {"error": f"no action {uuid} for {entity}"})

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="rest-endpoint", daemon=True
        )
        self._thread.start()
        log.info("REST endpoint on %s:%d%s", self._host, self.port, API_ROOT)

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # -- action dispatch -------------------------------------------------

    def _queue_for(self, entity: str) -> ActionQueue:
        with self._queues_lock:
            q = self._queues.get(entity)
            if q is None:
                q = self._queues[entity] = ActionQueue()
            return q

    def send_action(self, action: Action) -> None:
        self._queue_for(action.entity_id).put(action)
