"""REST endpoint: the HTTP wire for out-of-process inspectors.

Capability parity with /root/reference/nmz/endpoint/rest
(restendpoint.go:71-223, queue/restqueue.go:20-135), API root ``/api/v3``
(util/rest/restutil.go:16):

* ``POST /api/v3/events/{entity}/{uuid}``   — submit an event (non-blocking)
* ``GET /api/v3/actions/{entity}``          — long-poll the next action;
  idempotent (RFC 7231): repeated GETs return the same head until deleted;
  a newer concurrent poll supersedes an older one (the older returns 204)
* ``DELETE /api/v3/actions/{entity}/{uuid}``— acknowledge/remove an action
* ``POST /api/v3/control?op=enableOrchestration|disableOrchestration``

Batch fast path (doc/performance.md) — the per-event routes above stay
wire-compatible for old inspectors; new transceivers amortize the
per-request overhead across whole batches:

* ``POST /api/v3/events/{entity}/batch``    — submit a JSON array of
  events in one request; each uuid rides the same dedupe ring as the
  per-event route, so a retried batch whose 200 was lost replays
  idempotently (``{"accepted": N, "duplicates": M}``)
* ``GET /api/v3/actions/{entity}?batch=N``  — long-poll up to N queued
  actions in one response (``{"actions": [...]}``; 204 when none)
* ``DELETE /api/v3/actions/{entity}``       — multi-uuid acknowledge,
  body ``{"uuids": [...]}``; unknown uuids are reported, not an error
  (``{"deleted": [...], "missing": [...]}``)

Operator surface at the server root (not under the API root — that is
the inspector wire): ``GET /metrics`` + ``/metrics.json`` (PR 1),
``GET /healthz`` (liveness + active run id), ``GET /traces`` (recorded
run summaries), ``GET /traces/<run_id>`` (Chrome-trace JSON;
``?format=ndjson`` for the diffable line format), and
``GET /analytics`` (cross-run experiment statistics, ``?format=json``
default or ``ndjson``) — doc/observability.md.

Bounded ingress (doc/robustness.md "Chaos plane"): with
``ingress_cap`` > 0, event POSTs arriving while more than that many
events sit undrained in the hub queue are refused with **429 +
Retry-After** (``nmz_ingress_rejections_total``) instead of growing
the queue without limit; the transceiver's bounded retry honors the
header. The ``endpoint.*`` chaos fault points (injected refusals,
long-poll stalls) are seamed through the same handlers.

Implementation: stdlib ThreadingHTTPServer — one thread per in-flight
request, which long-polling requires anyway; no third-party HTTP stack.
"""

from __future__ import annotations

import itertools
import json
import re
import socket as _socket
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse, parse_qs

from namazu_tpu import chaos, obs, tenancy
from namazu_tpu.endpoint.hub import Endpoint
from namazu_tpu.signal import binary as _binary
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.control import Control, ControlOp
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.rest")

API_ROOT = "/api/v3"

#: the version piggyback header on batch POST / batch poll responses —
#: how an edge notices a table rollover within one batch
#: (doc/performance.md "Zero-RTT dispatch"); re-exported here so wire
#: code has one import site, defined next to the publisher it serves
from namazu_tpu.policy.edge_table import (  # noqa: F401  (re-export)
    TABLE_VERSION_HEADER,
)

_EVENTS_RE = re.compile(rf"^{API_ROOT}/events/([^/]+)/([^/]+)$")
_EVENTS_BATCH_RE = re.compile(rf"^{API_ROOT}/events/([^/]+)/batch$")
_EVENTS_BACKHAUL_RE = re.compile(rf"^{API_ROOT}/events/([^/]+)/backhaul$")
_ACTIONS_RE = re.compile(rf"^{API_ROOT}/actions/([^/]+)(?:/([^/]+))?$")
_CONTROL_RE = re.compile(rf"^{API_ROOT}/control$")
_POLICY_TABLE_RE = re.compile(rf"^{API_ROOT}/policy/table$")
_TELEMETRY_RE = re.compile(rf"^{API_ROOT}/telemetry$")
_TENANCY_RE = re.compile(rf"^{API_ROOT}/tenancy$")
_TRACES_RE = re.compile(r"^/traces(?:/([^/]+))?$")
_CAUSALITY_RE = re.compile(r"^/causality/([^/]+)(?:/([^/]+))?$")
# triage surface (namazu_tpu/triage): dossier list / one dossier by
# failure signature
_TRIAGE_RE = re.compile(r"^/triage(?:/([^/]+))?$")


class ActionQueue:
    """Per-entity deletable action queue with blocking peek.

    Parity: /root/reference/nmz/endpoint/rest/queue/restqueue.go:20-135 —
    ``peek`` blocks until non-empty; a newer concurrent peek supersedes the
    older one; ``delete`` acknowledges by uuid.

    Storage is an insertion-ordered uuid->action dict (dicts preserve
    insertion order), so ``delete`` is O(1) instead of the old linear
    scan — at batch depths a DELETE ack of the queue tail no longer costs
    a walk over every action still in flight.
    """

    def __init__(self) -> None:
        self._items: "Dict[str, Action]" = {}
        self._cond = threading.Condition()
        self._peek_gen = 0

    def put(self, action: Action) -> None:
        with self._cond:
            self._items[action.uuid] = action
            self._cond.notify_all()

    def put_many(self, actions: List[Action]) -> None:
        """Enqueue a whole batch under one lock acquisition + one wakeup
        (the hub's batch fan-through calls this per entity)."""
        if not actions:
            return
        with self._cond:
            for action in actions:
                self._items[action.uuid] = action
            self._cond.notify_all()

    def _wait_nonempty(self, timeout: Optional[float]) -> Optional[int]:
        """Block until non-empty; returns this poller's generation, or
        None on timeout or supersession. Caller holds the lock."""
        self._peek_gen += 1
        my_gen = self._peek_gen
        self._cond.notify_all()  # wake any older poller so it can yield
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._items:
                return my_gen
            if my_gen != self._peek_gen:
                return None  # superseded
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return None
            self._cond.wait(remaining)

    def peek(self, timeout: float = 30.0) -> Optional[Action]:
        """Return (without removing) the head action, blocking up to
        ``timeout``. Returns None on timeout or when superseded by a newer
        peek."""
        with self._cond:
            if self._wait_nonempty(timeout) is None:
                return None
            return next(iter(self._items.values()))

    def peek_batch(self, max_n: int, timeout: float = 30.0,
                   linger: float = 0.0) -> List[Action]:
        """Return (without removing) up to ``max_n`` head actions,
        blocking like :meth:`peek` until at least one is present. The
        batch GET route's body: whatever is queued NOW ships in one
        response instead of one long-poll round trip per action.

        ``linger`` > 0 trades that many seconds of delivery latency for
        occupancy: after the first action lands, keep collecting until
        the batch is full or the linger expires — at production rates a
        few ms of linger turns per-action round trips into full
        batches."""
        max_n = max(1, max_n)
        with self._cond:
            my_gen = self._wait_nonempty(timeout)
            if my_gen is None:
                return []
            if linger > 0 and len(self._items) < max_n:
                deadline = time.monotonic() + linger
                while len(self._items) < max_n:
                    if self._peek_gen != my_gen:
                        # a newer poll arrived mid-linger: yield to it
                        # (like peek does), or both pollers would be
                        # handed — and dispatch — the same actions
                        return []
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            return list(itertools.islice(self._items.values(), max_n))

    def supersede(self) -> None:
        """Unpark every waiting peek NOW (they return empty): the
        simulated-crash path — a kill -9'd process has no parked
        handler threads, so ``sever()`` must not leave pollers parked
        on a dead endpoint's queues for a full poll window. Found as
        the root of the documented crash-restart flake: a transceiver
        whose transparent reconnect raced into the dying listener's
        last milliseconds parked 30s against a zombie handler."""
        with self._cond:
            self._peek_gen += 1
            self._cond.notify_all()

    def delete(self, uuid: str) -> Optional[Action]:
        """Remove and return the action with ``uuid``, or None."""
        with self._cond:
            action = self._items.pop(uuid, None)
            if action is not None:
                self._cond.notify_all()
            return action

    def delete_many(self, uuids: List[str]):
        """Remove a batch of uuids under one lock acquisition; returns
        ``(deleted_actions, missing_uuids)`` — a partial ack (some uuids
        already acked or never queued) is data, not an error."""
        deleted: List[Action] = []
        missing: List[str] = []
        with self._cond:
            for uuid in uuids:
                action = self._items.pop(uuid, None)
                if action is None:
                    missing.append(uuid)
                else:
                    deleted.append(action)
            if deleted:
                self._cond.notify_all()
        return deleted, missing

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)


class QueuedEndpoint(Endpoint):
    """Shared machinery for endpoints built around per-entity
    :class:`ActionQueue` instances and an inbound-uuid dedupe ring —
    the REST wire and the ``uds://`` framed wire (endpoint/uds.py)
    carry the same batch/ack/backhaul semantics over different
    transports, so the queue fan-through, the idempotency ring, and
    the edge-backhaul ingestion live here once."""

    _SEEN_EVENT_CAP = 4096
    #: backhaul uuids get their OWN (larger) ring: the zero-RTT path
    #: runs ~50x the central wire's rate, and sharing one ring would
    #: let a few tens of milliseconds of backhaul evict a central
    #: retry's uuid before its >=0.5s backoff replays it — doubling the
    #: event the ring exists to dedupe. The two populations never
    #: overlap (an event is either edge-decided or centrally posted),
    #: so splitting them loses nothing.
    _SEEN_BACKHAUL_CAP = 65536

    def __init__(self) -> None:
        self._queues: Dict[str, ActionQueue] = {}
        self._queues_lock = threading.Lock()
        # event-uuid dedup ring: the transceiver retries a POST whose
        # ack was lost in flight (doc/robustness.md), so an uuid seen
        # twice means the first attempt already reached the hub — ack
        # without re-posting, or one network blip doubles an event in
        # the trace. Bounded: uuids are unique per event, so a small
        # recent window is enough to cover the retry horizon.
        self._seen_event_uuids: "OrderedDict[str, None]" = OrderedDict()
        self._seen_backhaul_uuids: "OrderedDict[str, None]" = \
            OrderedDict()
        self._seen_lock = threading.Lock()

    def note_event_uuid(self, uuid: str) -> bool:
        """Record an inbound event uuid; True if it was already seen
        (i.e. this POST is a retry duplicate)."""
        with self._seen_lock:
            if uuid in self._seen_event_uuids:
                return True
            self._seen_event_uuids[uuid] = None
            while len(self._seen_event_uuids) > self._SEEN_EVENT_CAP:
                self._seen_event_uuids.popitem(last=False)
            return False

    def note_backhaul_uuid(self, uuid: str) -> bool:
        """The backhaul face of the ring (separate population + cap —
        see _SEEN_BACKHAUL_CAP)."""
        with self._seen_lock:
            if uuid in self._seen_backhaul_uuids:
                return True
            self._seen_backhaul_uuids[uuid] = None
            while len(self._seen_backhaul_uuids) \
                    > self._SEEN_BACKHAUL_CAP:
                self._seen_backhaul_uuids.popitem(last=False)
            return False

    # -- action dispatch -------------------------------------------------

    def _queue_for(self, entity: str, ns: str = "") -> ActionQueue:
        """The action queue of (run namespace, entity). The default
        namespace's key is the bare entity id, so pre-tenancy clients
        poll the exact queues they always did (doc/tenancy.md)."""
        key = tenancy.route_key(ns, entity)
        with self._queues_lock:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = ActionQueue()
            return q

    def send_action(self, action: Action) -> None:
        self._queue_for(action.entity_id,
                        tenancy.ns_of(action)).put(action)

    def send_actions(self, actions: List[Action]) -> None:
        """Batch fan-through: group by (namespace, entity) (order
        preserved within each), resolve every queue under ONE
        ``_queues_lock`` acquisition, then one ``put_many`` (one queue
        lock + one wakeup) per entity — instead of lock/unlock churn
        per action."""
        if len(actions) == 1:
            return self.send_action(actions[0])
        by_key: Dict[str, List[Action]] = {}
        for action in actions:
            by_key.setdefault(tenancy.signal_route_key(action),
                              []).append(action)
        with self._queues_lock:
            queues = {}
            for key in by_key:
                q = self._queues.get(key)
                if q is None:
                    q = self._queues[key] = ActionQueue()
                queues[key] = q
        for key, batch in by_key.items():
            queues[key].put_many(batch)

    def forget_namespace(self, ns: str) -> int:
        """Drop one namespace's action queues (a released/reclaimed
        tenant): a re-lease of the same run name must never poll a dead
        incarnation's undelivered actions, and a long-lived host must
        not leak one queue per entity per lease. Parked pollers on the
        dropped queues are superseded (they return empty and the client
        re-polls into nothing)."""
        if not ns:
            return 0
        prefix = ns + tenancy.ROUTE_SEP
        with self._queues_lock:
            dead = [k for k in self._queues if k.startswith(prefix)]
            queues = [self._queues.pop(k) for k in dead]
        for q in queues:
            q.supersede()
        return len(dead)

    def ack_action(self, entity: str, action: Action) -> None:
        """Observability for one acknowledged (delivered) action."""
        obs.mark(action, "acked")
        obs.record_acked(action)
        obs.rest_ack(entity, obs.latency(action, "dispatched"))
        # every central lifecycle stamp is in hand at the ack: publish
        # the per-segment latency decomposition (queue/decision/
        # parking/dispatch/wire) into nmz_event_stage_seconds — the
        # causality plane's live histogram face (obs/causality.py)
        obs.causality.observe_stage_segments(action)

    # -- zero-RTT edge backhaul (doc/performance.md) ---------------------

    def ingest_backhaul(self, doc, entity: str, ns: str = ""):
        """Decode + dedupe one backhaul request body
        (``{"items": [{"event": ..., "decision": ...}, ...]}``) and
        reconcile the fresh items into the hub. Returns
        ``(accepted, duplicates)``; raises ValueError on a malformed
        body — like the batch POST route, validation is atomic (the
        client retries the whole chunk, the dedupe ring absorbs the
        replay of already-accepted uuids)."""
        items = doc.get("items") if isinstance(doc, dict) else None
        if not isinstance(items, list) or not items:
            raise ValueError(
                "backhaul body must be {\"items\": [{\"event\": ..., "
                "\"decision\": ...}, ...]}")
        pairs = []
        for i, item in enumerate(items):
            if not isinstance(item, dict):
                raise ValueError(f"backhaul item {i} is not an object")
            try:
                sig = signal_from_jsonable(item.get("event"))
            except (SignalError, ValueError, TypeError) as e:
                raise ValueError(f"backhaul item {i}: {e}") from e
            if not isinstance(sig, Event):
                raise ValueError(f"backhaul item {i} is not an event")
            if sig.entity_id != entity:
                raise ValueError(
                    f"backhaul item {i} entity {sig.entity_id!r} does "
                    f"not match url entity {entity!r}")
            decision = item.get("decision")
            if not isinstance(decision, dict) \
                    or "table_version" not in decision:
                raise ValueError(
                    f"backhaul item {i} carries no decision/"
                    "table_version")
            pairs.append((sig, decision))
        fresh = [(ev, d) for ev, d in pairs
                 if not self.note_backhaul_uuid(ev.uuid)]
        if ns:
            for ev, _ in fresh:
                tenancy.set_ns(ev, ns)
        if fresh:
            self.hub.post_edge_backhaul(fresh, self.NAME)
        return len(fresh), len(pairs) - len(fresh)


class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with (a) connection tracking, so a simulated
    crash (`Orchestrator.abandon`, the chaos harness's in-process
    kill -9) can sever open connections the way real process death
    would, and (b) a BOUNDED handler pool (doc/tenancy.md): connections
    are served by at most ``max_threads`` lazily-spawned workers, with
    overflow connections queued — 8 campaigns' clients hitting one
    orchestrator grow a queue, not an unbounded thread count (the
    stdlib mixin spawned one thread per connection, forever)."""

    #: an idle pool worker exits after this long (a short burst's
    #: threads drain back instead of lingering for the process life)
    IDLE_EXIT_S = 30.0

    def __init__(self, *args, max_threads: int = 64, **kw):
        super().__init__(*args, **kw)
        self._open_requests: set = set()
        self._open_lock = threading.Lock()
        self._max_threads = max(1, int(max_threads))
        # condition-based hand-off (NOT a bare Queue): the spawn
        # decision and the idle-waiter accounting happen under ONE
        # lock, so the two lost-wakeup races a stale idle count allows
        # (enqueue beside a worker mid-dequeue, enqueue beside a
        # worker mid-retire) are closed by construction — the put-side
        # invariant is pending <= idle_waiters + spawned workers
        self._conn_cond = threading.Condition()
        self._conn_pending: deque = deque()
        self._idle_waiters = 0
        self._threads_alive = 0
        self._pool_stopped = False

    def process_request(self, request, client_address):
        with self._open_lock:
            self._open_requests.add(request)
        with self._conn_cond:
            if self._pool_stopped:
                pending = 0
            else:
                self._conn_pending.append((request, client_address))
                pending = len(self._conn_pending)
            # soft cap: whenever queued connections outnumber waiting
            # workers, spawn — beyond max_threads the pool grows like
            # the old thread-per-connection server did (long-lived
            # keep-alive connections, long-polls included, each hold a
            # worker; starving them in the queue would strand
            # entities). The cap's win is burst absorption: short
            # connections reuse pooled workers instead of costing a
            # thread each, and the overflow gauge (nmz_rest_conn_
            # threads vs max) shows sustained pressure.
            spawn = pending > self._idle_waiters
            if spawn:
                self._threads_alive += 1
            alive = self._threads_alive
            self._conn_cond.notify()
        if not pending:
            self.shutdown_request(request)  # stopping: refuse politely
            return
        if spawn:
            threading.Thread(target=self._conn_worker,
                             name="rest-conn", daemon=True).start()
        obs.rest_conn_pool(alive, pending - 1)

    def _next_conn(self):
        """One connection to serve, or None to retire (idle past
        IDLE_EXIT_S, or the pool stopped). All accounting under the
        condition lock."""
        with self._conn_cond:
            deadline = time.monotonic() + self.IDLE_EXIT_S
            while True:
                if self._conn_pending:
                    return self._conn_pending.popleft()
                remaining = deadline - time.monotonic()
                if self._pool_stopped or remaining <= 0:
                    self._threads_alive -= 1
                    return None
                self._idle_waiters += 1
                try:
                    self._conn_cond.wait(remaining)
                finally:
                    self._idle_waiters -= 1

    def _conn_worker(self):
        while True:
            item = self._next_conn()
            if item is None:
                return
            request, client_address = item
            try:
                self.finish_request(request, client_address)
            except Exception:
                self.handle_error(request, client_address)
            finally:
                self.shutdown_request(request)

    def stop_pool(self) -> None:
        """Retire every pool worker and close queued-but-unserved
        connections (shutdown/sever path)."""
        with self._conn_cond:
            self._pool_stopped = True
            drained = list(self._conn_pending)
            self._conn_pending.clear()
            self._conn_cond.notify_all()
        for request, _ in drained:
            self.shutdown_request(request)

    def shutdown_request(self, request):
        with self._open_lock:
            self._open_requests.discard(request)
        super().shutdown_request(request)

    def sever_connections(self) -> int:
        with self._open_lock:
            socks = list(self._open_requests)
        for sock in socks:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
        return len(socks)


class RestEndpoint(QueuedEndpoint):
    NAME = "rest"

    def __init__(self, port: int = 10080, host: str = "127.0.0.1",
                 poll_timeout: float = 30.0, ingress_cap: int = 0,
                 retry_after_s: float = 1.0,
                 advertise_codec: bool = True,
                 max_threads: int = 64):
        super().__init__()
        self._host = host
        self._port = port
        self.poll_timeout = poll_timeout
        # the binary-codec negotiation piggyback (doc/performance.md
        # "Binary wire + sharded edge"): advertise X-Nmz-Codec-Accept
        # on every API reply so auto-codec clients upgrade; False
        # simulates a pre-binary server (interop tests)
        self.advertise_codec = bool(advertise_codec)
        # bounded ingress (doc/robustness.md): when more than this many
        # events sit undrained in the hub's queue, new POSTs are refused
        # with 429 + Retry-After instead of growing the queue without
        # limit — the transceiver's bounded retry honors the header.
        # 0 = unbounded (the pre-backpressure behavior).
        self.ingress_cap = max(0, int(ingress_cap))
        self.retry_after_s = max(0.0, float(retry_after_s))
        # bounded connection-handler pool (doc/tenancy.md): beyond this
        # many concurrent connections, new ones queue for a handler
        self.max_threads = max(1, int(max_threads))
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_mono = time.monotonic()  # /healthz uptime anchor

    # -- lifecycle -------------------------------------------------------

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.server_address[1]
        return self._port

    def start(self) -> None:
        endpoint = self
        self._started_mono = time.monotonic()

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # one response = ONE tcp segment: fully buffer the write
            # side (handle_one_request flushes per response) and disable
            # Nagle — header and body written as separate unbuffered
            # segments interlock with the peer's delayed ACK and cost
            # tens of ms per small request/response round trip
            wbufsize = -1
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # route to our logger
                log.debug("http: " + fmt, *args)

            def _entity_ok(self, entity: str) -> bool:
                """False AFTER replying 400 for an entity id that
                would alias a composite route key (tenancy plane:
                '\x1f' is the namespace separator)."""
                if tenancy.ROUTE_SEP in entity:
                    self._reply(400, {"error": "entity id must not "
                                      "contain \x1f"})
                    return False
                return True

            def _req_ns(self):
                """The request's run namespace (the X-Nmz-Run header,
                tenancy plane): '' = the process-default namespace
                (every pre-tenancy client). Returns None AFTER replying
                400 when the header value is malformed."""
                raw = self.headers.get(tenancy.RUN_HEADER)
                if raw is None:
                    return ""
                try:
                    return tenancy.validate_ns(raw.strip())
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return None

            def _req_codec(self) -> str:
                """The request's negotiated codec (the X-Nmz-Codec
                header names the body's codec AND asks for the reply
                in kind; absent = the JSON default wire)."""
                raw = self.headers.get(_binary.CODEC_HEADER)
                if raw is None:
                    return _binary.CODEC_JSON
                return raw.strip()

            def _decode_body(self, raw: bytes):
                """Body -> value tree by the request's codec. Raises
                ValueError; a garbled BINARY payload is tagged so the
                client retries in place instead of downgrading (the
                codec is fine, the bytes were damaged in flight)."""
                if self._req_codec() == _binary.CODEC_BINARY:
                    obs.wire_bytes(_binary.CODEC_BINARY, "ingress",
                                   len(raw))
                    return _binary.loads(raw)
                obs.wire_bytes(_binary.CODEC_JSON, "ingress", len(raw))
                return json.loads(raw)

            def _reply(self, code: int, body: Optional[dict] = None,
                       headers: Optional[Dict[str, str]] = None,
                       codec: Optional[str] = None) -> None:
                """``codec`` (or the request's) picks the body
                serialization; anything binary-incapable degrades to
                JSON per response (the X-Nmz-Codec reply header names
                what was actually used)."""
                codec = self._req_codec() if codec is None else codec
                if body is None:
                    return self._reply_raw(code, b"", "application/json",
                                           headers=headers)
                if codec == _binary.CODEC_BINARY:
                    try:
                        data = _binary.dumps(body)
                    except TypeError:
                        codec = _binary.CODEC_JSON
                    else:
                        headers = dict(headers or {})
                        headers[_binary.CODEC_HEADER] = \
                            _binary.CODEC_BINARY
                        return self._reply_raw(
                            code, data, _binary.CONTENT_TYPE_BINARY,
                            headers=headers)
                self._reply_raw(code, json.dumps(body).encode(),
                                "application/json", headers=headers)

            def _reply_raw(self, code: int, data: bytes,
                           content_type: str,
                           headers: Optional[Dict[str, str]] = None
                           ) -> None:
                obs.rest_request(self.command, code)
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                if endpoint.advertise_codec \
                        and self.path.startswith(API_ROOT):
                    # the negotiation piggyback: every API reply tells
                    # the client this server accepts the binary codec
                    self.send_header(_binary.CODEC_ACCEPT_HEADER,
                                     _binary.CODEC_BINARY)
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                if data:
                    self.wfile.write(data)

            def _reply_badbody(self, e: Exception) -> None:
                """400 for an undecodable body. A garbled BINARY
                payload is tagged retry-in-place: the codec agreement
                is intact, the bytes were damaged in flight — the
                client must NOT downgrade to JSON over it
                (wire.binary.garble chaos contract)."""
                headers = {}
                if self._req_codec() == _binary.CODEC_BINARY:
                    headers["X-Nmz-Codec-Error"] = "garbled"
                self._reply(400, {"error": str(e)}, headers=headers,
                            codec=_binary.CODEC_JSON)

            def _reject_ingress(self, reason: str, status: int = 429,
                                retry_after: Optional[float] = None
                                ) -> None:
                """Refuse an event POST (backpressure or chaos): the
                429/503 + Retry-After contract the transceiver's
                bounded retry honors (doc/robustness.md)."""
                if retry_after is None:
                    retry_after = endpoint.retry_after_s
                obs.ingress_rejected(endpoint.NAME, reason)
                self._reply(
                    status,
                    {"error": f"ingress refused ({reason}); retry after "
                              f"{retry_after:g}s"},
                    headers={"Retry-After": f"{retry_after:g}"})

            def _ingress_refused(self) -> bool:
                """Consult the chaos seam, then the bounded-ingress cap;
                True = a refusal was already sent."""
                fault = chaos.decide("endpoint.ingress.refuse")
                if fault is not None:
                    self._reject_ingress(
                        "chaos", status=int(fault.get("status", 429)),
                        retry_after=float(fault.get("retry_after", 0.05)))
                    return True
                cap = endpoint.ingress_cap
                if cap > 0 and endpoint.hub.event_queue.qsize() >= cap:
                    self._reject_ingress("backpressure")
                    return True
                return False

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(length) if length else b""

            def _tv_headers(self, ns: str = "") -> Dict[str, str]:
                """The table-version piggyback (zero-RTT dispatch):
                present on batch POST / batch poll / backhaul replies
                whenever the request's namespace has a table plane —
                the one signal an edge needs to notice a rollover
                within one batch. Namespaced requests see THEIR
                tenant's version (doc/tenancy.md "Per-namespace
                tables"), never the process default's."""
                version = endpoint.hub.table_version(ns)
                if version is None:
                    return {}
                return {TABLE_VERSION_HEADER: str(version)}

            def do_POST(self) -> None:
                url = urlparse(self.path)
                m = _EVENTS_BATCH_RE.match(url.path)
                if m:
                    return self._post_event_batch(m.group(1))
                m = _EVENTS_BACKHAUL_RE.match(url.path)
                if m:
                    return self._post_event_backhaul(m.group(1))
                m = _EVENTS_RE.match(url.path)
                if m:
                    return self._post_event(m.group(1), m.group(2))
                if _TELEMETRY_RE.match(url.path):
                    return self._post_telemetry()
                if _TENANCY_RE.match(url.path):
                    return self._post_tenancy()
                if _CONTROL_RE.match(url.path):
                    return self._post_control(parse_qs(url.query))
                self._reply(404, {"error": f"no route {url.path}"})

            def _post_telemetry(self) -> None:
                """Fleet telemetry push wire (doc/observability.md
                "Fleet telemetry"): one delta-snapshot doc into this
                process's aggregator. Not gated by the event-ingress
                cap — telemetry about an overloaded fleet is exactly
                what must still get through; the doc's seq watermark
                makes a retried push whose 200 was lost idempotent."""
                try:
                    raw = self._read_body()  # always drain (keep-alive)
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                try:
                    doc = json.loads(raw)
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                try:
                    ack = obs.note_telemetry_push(doc)
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                self._reply(200, ack)

            def _post_tenancy(self) -> None:
                """The slot-leasing wire (doc/tenancy.md): one JSON op
                body (lease/renew/release/runs) against this host's
                RunRegistry. 404 on single-run orchestrators — the
                plane simply isn't there."""
                try:
                    raw = self._read_body()  # always drain (keep-alive)
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                registry = endpoint.hub.run_registry
                if registry is None:
                    return self._reply(
                        404, {"error": "this orchestrator hosts no "
                              "tenancy plane"})
                try:
                    doc = json.loads(raw)
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                if not isinstance(doc, dict):
                    return self._reply(
                        400, {"error": "tenancy body must be a JSON "
                              "object"})
                from namazu_tpu.policy.base import PolicyError
                from namazu_tpu.tenancy.registry import (TenancyError,
                                                         handle_tenancy_op)
                try:
                    resp = handle_tenancy_op(doc, registry)
                except (TenancyError, PolicyError, ValueError) as e:
                    return self._reply(400, {"error": str(e)})
                if resp is None:
                    return self._reply(
                        400, {"error": f"unknown tenancy op "
                              f"{doc.get('op')!r}"})
                self._reply(200, resp)

            def _post_event(self, entity: str, uuid: str) -> None:
                # the body must be READ even when refusing — an unread
                # body desyncs the keep-alive connection (the next
                # request line would parse mid-JSON) — but shed load
                # before the JSON parse, which is the expensive part
                try:
                    raw = self._read_body()
                except ValueError as e:  # malformed Content-Length
                    return self._reply(400, {"error": str(e)})
                if self._ingress_refused():
                    return
                try:
                    sig = signal_from_jsonable(self._decode_body(raw))
                except SignalError as e:
                    return self._reply(400, {"error": str(e)})
                except ValueError as e:
                    return self._reply_badbody(e)
                if not isinstance(sig, Event):
                    return self._reply(400, {"error": "signal is not an event"})
                if sig.entity_id != entity or sig.uuid != uuid:
                    return self._reply(
                        400,
                        {"error": "url entity/uuid do not match event body"},
                    )
                ns = self._req_ns()
                if ns is None or not self._entity_ok(entity):
                    return
                if endpoint.note_event_uuid(sig.uuid):
                    # retry of a POST whose 200 was lost: the event is
                    # already in the hub — idempotent ack
                    return self._reply(200, {"duplicate": True})
                tenancy.set_ns(sig, ns)
                endpoint.hub.post_event(sig, endpoint.NAME)
                self._reply(200, {})

            def _post_event_batch(self, entity: str) -> None:
                """One POST carrying a whole JSON array of events. The
                batch is validated atomically (any malformed item 400s
                the whole request — the client retries the batch, and
                the dedupe ring makes the replay of already-accepted
                uuids idempotent), then fanned into the hub in ONE
                call."""
                try:
                    raw = self._read_body()  # always drain (keep-alive)
                except ValueError as e:  # malformed Content-Length
                    return self._reply(400, {"error": str(e)})
                if self._ingress_refused():
                    return
                try:
                    body = self._decode_body(raw)
                except ValueError as e:
                    return self._reply_badbody(e)
                if isinstance(body, dict):
                    body = body.get("events")
                if not isinstance(body, list) or not body:
                    return self._reply(
                        400, {"error": "batch body must be a non-empty "
                              "JSON array of events (or {\"events\": "
                              "[...]})"})
                events = []
                for i, item in enumerate(body):
                    try:
                        sig = signal_from_jsonable(item)
                    except (SignalError, ValueError, TypeError) as e:
                        return self._reply(
                            400, {"error": f"batch item {i}: {e}"})
                    if not isinstance(sig, Event):
                        return self._reply(
                            400, {"error": f"batch item {i} is not an "
                                  "event"})
                    if sig.entity_id != entity:
                        return self._reply(
                            400, {"error": f"batch item {i} entity "
                                  f"{sig.entity_id!r} does not match url "
                                  f"entity {entity!r}"})
                    events.append(sig)
                ns = self._req_ns()
                if ns is None or not self._entity_ok(entity):
                    return
                fresh = [ev for ev in events
                         if not endpoint.note_event_uuid(ev.uuid)]
                if ns:
                    for ev in fresh:
                        tenancy.set_ns(ev, ns)
                if fresh:
                    endpoint.hub.post_events(fresh, endpoint.NAME)
                self._reply(200, {"accepted": len(fresh),
                                  "duplicates": len(events) - len(fresh)},
                            headers=self._tv_headers(ns))

            def _post_event_backhaul(self, entity: str) -> None:
                """Asynchronous backhaul of edge-decided events
                (doc/performance.md "Zero-RTT dispatch"): the edge
                already dispatched these against a published table;
                this request reconciles their trace records + decision
                detail into the orchestrator. The reply always carries
                the server's current ``table_version`` so a stale edge
                learns of a rollover from its own backhaul."""
                try:
                    raw = self._read_body()  # always drain (keep-alive)
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                if self._ingress_refused():
                    return
                try:
                    doc = self._decode_body(raw)
                except ValueError as e:
                    return self._reply_badbody(e)
                ns = self._req_ns()
                if ns is None or not self._entity_ok(entity):
                    return
                try:
                    accepted, duplicates = endpoint.ingest_backhaul(
                        doc, entity, ns=ns)
                except ValueError as e:
                    return self._reply(400, {"error": str(e)})
                self._reply(200, {
                    "accepted": accepted, "duplicates": duplicates,
                    "table_version": endpoint.hub.table_version(ns) or 0,
                }, headers=self._tv_headers(ns))

            def _post_control(self, query: Dict[str, list]) -> None:
                ops = query.get("op") or []
                try:
                    op = ControlOp(ops[0] if ops else "")
                except ValueError:
                    return self._reply(
                        400, {"error": f"bad op {ops!r}; known: "
                              f"{[o.value for o in ControlOp]}"}
                    )
                # tenancy plane: an X-Nmz-Run header scopes the op to
                # that namespace's publisher (one tenant's disable must
                # never suspend a sibling's table); absent = the
                # process-default policy, pre-tenancy behavior
                ns = self._req_ns()
                if ns is None:
                    return
                ctrl = Control(op)
                tenancy.set_ns(ctrl, ns)
                endpoint.hub.post_control(ctrl)
                self._reply(200, {})

            def do_GET(self) -> None:
                url = urlparse(self.path)
                if url.path == "/metrics":
                    # Prometheus text exposition of the process registry
                    return self._reply_raw(
                        200, obs.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                if url.path == "/metrics.json":
                    return self._reply(200, obs.registry_jsonable())
                if url.path == "/healthz":
                    return self._reply(200, {
                        "status": "ok",
                        "run_id": obs.current_run_id(),
                        "uptime_s": round(
                            time.monotonic() - endpoint._started_mono, 3),
                        "endpoint": endpoint.NAME,
                    })
                if url.path == "/analytics":
                    return self._get_analytics(parse_qs(url.query))
                if url.path == "/progress":
                    return self._get_progress()
                if url.path == "/fleet":
                    return self._get_fleet(parse_qs(url.query))
                if url.path == "/profile":
                    return self._get_profile(parse_qs(url.query))
                if _POLICY_TABLE_RE.match(url.path):
                    return self._get_policy_table()
                m = _TRACES_RE.match(url.path)
                if m:
                    return self._get_traces(m.group(1), parse_qs(url.query))
                m = _CAUSALITY_RE.match(url.path)
                if m:
                    return self._get_causality(m.group(1), m.group(2),
                                               parse_qs(url.query))
                m = _TRIAGE_RE.match(url.path)
                if m:
                    return self._get_triage(m.group(1))
                m = _ACTIONS_RE.match(url.path)
                if not (m and m.group(2) is None):
                    return self._reply(404, {"error": f"no route {url.path}"})
                entity = m.group(1)
                query = parse_qs(url.query)
                ns = self._req_ns()
                if ns is None or not self._entity_ok(entity):
                    return
                # chaos seam: stall a long-poll (the inspector's receive
                # loop must ride it out, not die)
                fault = chaos.decide("endpoint.poll.stall")
                if fault is not None:
                    time.sleep(float(fault.get("delay_s", 0.2)))
                raw_batch = (query.get("batch") or [None])[0]
                if raw_batch is None:
                    # per-event wire (pre-batch inspectors): one head
                    # action as the whole body
                    action = endpoint._queue_for(entity, ns).peek(
                        endpoint.poll_timeout)
                    if action is None:
                        return self._reply(204)
                    return self._reply(200, action.to_jsonable())
                try:
                    max_n = int(raw_batch)
                    if max_n <= 0:
                        raise ValueError
                except ValueError:
                    return self._reply(
                        400, {"error": f"bad batch={raw_batch!r} "
                              "(want a positive integer)"})
                raw_linger = (query.get("linger_ms") or ["0"])[0]
                try:
                    # capped: a client must not park this handler
                    # thread for longer than a poll window
                    linger = min(max(0.0, float(raw_linger)),
                                 1000.0) / 1000.0
                except ValueError:
                    return self._reply(
                        400, {"error": f"bad linger_ms={raw_linger!r} "
                              "(want a number)"})
                actions = endpoint._queue_for(entity, ns).peek_batch(
                    max_n, endpoint.poll_timeout, linger=linger)
                if not actions:
                    return self._reply(204, headers=self._tv_headers(ns))
                obs.event_batch("actions_poll", len(actions))
                self._reply(200, {"actions": [a.to_jsonable()
                                              for a in actions]},
                            headers=self._tv_headers(ns))

            def _get_policy_table(self) -> None:
                """The published hash->delay table (zero-RTT dispatch):
                200 + the versioned doc when one is publishable, 204
                (with the version header) when the current version has
                no table — non-table policies, cold start, fault-
                bearing installs, disabled orchestration. An X-Nmz-Run
                header scopes the read to that tenant's OWN publisher
                (doc/tenancy.md "Per-namespace tables"); an unknown or
                expired tenant gets a bare 204 — no version, no
                table."""
                ns = self._req_ns()
                if ns is None:
                    return
                version, doc = endpoint.hub.table_doc(ns)
                headers = self._tv_headers(ns)
                if doc is None:
                    return self._reply(204, headers=headers)
                self._reply(200, doc, headers=headers)

            def _get_analytics(self, query) -> None:
                """Experiment-analytics surface (obs/analytics.py): the
                registered storage's cross-run statistics joined with
                this process's recorded runs — the same payload
                ``nmz-tpu tools report`` renders."""
                fmt = (query.get("format") or ["json"])[0]
                if fmt not in ("json", "ndjson"):
                    return self._reply(
                        400, {"error": f"unknown format {fmt!r}; known: "
                              "json, ndjson"})
                # top/window mirror the CLI's --top/--window so a remote
                # `tools report --url` request is not silently computed
                # with different parameters than a local one
                params = {}
                for name, default in (
                        ("top", obs.analytics.DEFAULT_TOP),
                        ("window", obs.analytics.DEFAULT_WINDOW)):
                    raw = (query.get(name) or [None])[0]
                    try:
                        params[name] = default if raw is None \
                            else max(1, int(raw))
                    except ValueError:
                        return self._reply(
                            400, {"error": f"bad {name}={raw!r} "
                                  "(want a positive integer)"})
                try:
                    payload = obs.analytics_payload(**params)
                except Exception as e:  # never let a stats bug kill ops
                    log.exception("analytics payload failed")
                    return self._reply(
                        500, {"error": f"analytics failed: {e}"})
                if fmt == "ndjson":
                    return self._reply_raw(
                        200, obs.report.render_ndjson(payload).encode(),
                        "application/x-ndjson")
                self._reply(200, payload)

            def _get_progress(self) -> None:
                """Campaign-progress surface (obs/stats.py via
                obs/analytics.progress_stats): the registered storage's
                sequential repro-rate statistics, band verdict, and ETA
                forecasts — always 200, zeros before the first run."""
                try:
                    payload = obs.progress_payload()
                except Exception as e:  # never let a stats bug kill ops
                    log.exception("progress payload failed")
                    return self._reply(
                        500, {"error": f"progress failed: {e}"})
                self._reply(200, payload)

            def _get_fleet(self, query) -> None:
                """Fleet status surface (obs/federation.py): every
                producer process that pushed telemetry here, merged
                under (job, instance) with staleness marking, plus the
                SLO objective table. ``?format=prom`` renders the whole
                fleet as ONE Prometheus exposition so a single scrape
                covers every process."""
                fmt = (query.get("format") or ["json"])[0]
                if fmt not in ("json", "prom"):
                    return self._reply(
                        400, {"error": f"unknown format {fmt!r}; known: "
                              "json, prom"})
                try:
                    if fmt == "prom":
                        return self._reply_raw(
                            200, obs.fleet_prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    payload = obs.fleet_payload()
                except Exception as e:  # never let a stats bug kill ops
                    log.exception("fleet payload failed")
                    return self._reply(
                        500, {"error": f"fleet failed: {e}"})
                self._reply(200, payload)

            def _get_profile(self, query) -> None:
                """Profiling surface (obs/profiling.py): this process's
                sampling profile — speedscope JSON by default (open the
                body in speedscope.app), ``?format=collapsed`` for
                folded flamegraph text, ``?format=json`` for the raw
                ``nmz-profile-v1`` payload profdiff consumes. 404 when
                the profiler is off (``profile_enabled = false`` /
                ``NMZ_PROFILE=0`` / obs disabled)."""
                fmt = (query.get("format") or ["speedscope"])[0]
                if fmt not in ("speedscope", "collapsed", "json"):
                    return self._reply(
                        400, {"error": f"unknown format {fmt!r}; known: "
                              "speedscope, collapsed, json"})
                try:
                    if not obs.profiling.enabled():
                        return self._reply(
                            404, {"error": "profiler disabled in this "
                                  "process (profile_enabled=false, "
                                  "NMZ_PROFILE=0, or obs off)"})
                    if fmt == "collapsed":
                        return self._reply_raw(
                            200, obs.profile_collapsed().encode(),
                            "text/plain; charset=utf-8")
                    if fmt == "json":
                        return self._reply(200, obs.profile_payload())
                    return self._reply(200, obs.profile_speedscope())
                except Exception as e:  # never let a profile bug kill ops
                    log.exception("profile payload failed")
                    return self._reply(
                        500, {"error": f"profile failed: {e}"})

            def _get_causality(self, run_a, run_b, query) -> None:
                """Causality surface (obs/causality.py): one run's
                happens-before graph + critical-path attribution, or —
                with two run ids — the ordering-relation divergence
                explanation ``nmz-tpu tools why`` renders."""
                raw_top = (query.get("top") or [None])[0]
                try:
                    top = 20 if raw_top is None else max(1, int(raw_top))
                except ValueError:
                    return self._reply(
                        400, {"error": f"bad top={raw_top!r} "
                              "(want a positive integer)"})
                try:
                    if run_b is None:
                        payload = obs.causality_run_payload(run_a)
                    else:
                        payload = obs.causality_why_payload(
                            run_a, run_b, top=top)
                except Exception as e:  # analysis bugs must not kill ops
                    log.exception("causality payload failed")
                    return self._reply(
                        500, {"error": f"causality failed: {e}"})
                if payload is None:
                    return self._reply(
                        404, {"error": "no recorded run "
                              f"{run_a if run_b is None else (run_a, run_b)!r}"})
                self._reply(200, payload)

            def _get_triage(self, signature) -> None:
                """Triage surface (namazu_tpu/triage): the dossier
                summaries this process holds, or one full dossier by
                failure signature — what ``nmz-tpu tools minimize
                --url`` reads."""
                try:
                    from namazu_tpu.triage import store as triage_store

                    if signature is None:
                        return self._reply(
                            200,
                            {"dossiers": triage_store.summaries()})
                    dossier = triage_store.dossier_for(signature)
                except Exception as e:  # stats bugs must not kill ops
                    log.exception("triage payload failed")
                    return self._reply(
                        500, {"error": f"triage failed: {e}"})
                if dossier is None:
                    return self._reply(
                        404, {"error": "no triage dossier for "
                              f"signature {signature!r} (minimize a "
                              "failing run first, or pull it from the "
                              "knowledge pool: tools minimize "
                              "--knowledge)"})
                self._reply(200, {"dossier": dossier})

            def _get_traces(self, run_id, query) -> None:
                """Flight-recorder surface: run list, or one run as
                Chrome-trace JSON / NDJSON (obs/export.py)."""
                if run_id is None:
                    return self._reply(200, {"runs": obs.trace_summaries()})
                run = obs.trace_run(run_id)
                if run is None:
                    return self._reply(
                        404, {"error": f"no recorded run {run_id}"})
                fmt = (query.get("format") or ["chrome"])[0]
                if fmt == "ndjson":
                    return self._reply_raw(
                        200, obs.export.to_ndjson(run).encode(),
                        "application/x-ndjson")
                if fmt != "chrome":
                    return self._reply(
                        400, {"error": f"unknown format {fmt!r}; known: "
                              "chrome, ndjson"})
                self._reply(200, obs.export.chrome_trace(run))

            def do_DELETE(self) -> None:
                url = urlparse(self.path)
                m = _ACTIONS_RE.match(url.path)
                if not m:
                    return self._reply(404, {"error": f"no route {url.path}"})
                entity, uuid = m.group(1), m.group(2)
                ns = self._req_ns()
                if ns is None or not self._entity_ok(entity):
                    return
                if uuid is None:
                    return self._delete_batch(entity, ns)
                action = endpoint._queue_for(entity, ns).delete(uuid)
                if action is not None:
                    self._ack(entity, action)
                    self._reply(200, {})
                else:
                    self._reply(404, {"error": f"no action {uuid} for {entity}"})

            def _ack(self, entity: str, action: Action) -> None:
                endpoint.ack_action(entity, action)

            def _delete_batch(self, entity: str, ns: str = "") -> None:
                """Multi-uuid acknowledge: ``{"uuids": [...]}`` in the
                body, one queue-lock acquisition for the whole batch.
                Unknown uuids come back in ``missing`` with a 200 — a
                replayed ack (the 200 was lost in flight) is a normal
                retry, not a client error."""
                try:
                    body = self._decode_body(self._read_body())
                except ValueError as e:
                    return self._reply_badbody(e)
                uuids = body.get("uuids") if isinstance(body, dict) else None
                if (not isinstance(uuids, list) or not uuids
                        or not all(isinstance(u, str) for u in uuids)):
                    return self._reply(
                        400, {"error": "body must be {\"uuids\": "
                              "[\"...\", ...]}"})
                deleted, missing = \
                    endpoint._queue_for(entity, ns).delete_many(uuids)
                for action in deleted:
                    self._ack(entity, action)
                self._reply(200, {"deleted": [a.uuid for a in deleted],
                                  "missing": missing})

        self._server = _TrackingHTTPServer((self._host, self._port), Handler,
                                           max_threads=self.max_threads)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="rest-endpoint", daemon=True
        )
        self._thread.start()
        log.info("REST endpoint on %s:%d%s", self._host, self.port, API_ROOT)

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server.stop_pool()
            self._server = None

    def sever(self) -> int:
        """Simulated process death (see :class:`_TrackingHTTPServer`):
        close the LISTENER first (a dead process accepts nothing — a
        client whose transparent reconnect races into the last
        milliseconds must get a refusal, not a fresh socket into the
        corpse), then cut every open connection, then supersede parked
        pollers so their handlers answer into the severed sockets and
        die NOW instead of parking a zombie poll for a full window
        against queues nobody will ever fill. Returns how many
        connections were cut."""
        srv = self._server
        if srv is None:
            return 0
        try:
            srv.shutdown()
            srv.server_close()
        except OSError:  # pragma: no cover - defensive
            pass
        n = srv.sever_connections()
        srv.stop_pool()
        with self._queues_lock:
            queues = list(self._queues.values())
        for q in queues:
            q.supersede()
        return n
