"""UDS endpoint: framed JSON over AF_UNIX for same-host inspectors.

The ``uds://`` wire (doc/performance.md "Zero-RTT dispatch"): same
batch/ack/backhaul semantics as the REST endpoint, but spoken as
length-prefixed JSON frames (``uint32-LE length + UTF-8 JSON`` — the
codec the guest-agent endpoint and the sidecar already use,
endpoint/agent.py) over a Unix domain socket. No HTTP parse, no
request-line/header overhead, no TCP handshake — for a same-host
inspector the per-request cost is one frame each way on a persistent
connection.

Ops (one request frame -> one response frame, any number per
connection; every response carries ``table_version`` when the hub has
a table plane, the piggyback an edge needs to notice a rollover):

* ``{"op": "post_batch", "entity": e, "events": [...]}``
  -> ``{"ok": true, "accepted": N, "duplicates": M}``
  (validated atomically like the REST batch route; uuids ride the
  shared dedupe ring, so a replayed batch acks idempotently)
* ``{"op": "poll", "entity": e, "batch": N, "linger_ms": L,
  "timeout_s": T}`` -> ``{"ok": true, "actions": [...]}``
  (long-poll; empty ``actions`` = timeout, not an error)
* ``{"op": "ack", "entity": e, "uuids": [...]}``
  -> ``{"ok": true, "deleted": [...], "missing": [...]}``
* ``{"op": "backhaul", "entity": e, "items": [...]}``
  -> ``{"ok": true, "accepted": N, "duplicates": M}``
* ``{"op": "table"}`` -> ``{"ok": true, "version": V,
  "table": doc_or_null}``
* observability ops (obs/federation.py): ``{"op": "telemetry",
  "doc": ...}`` pushes one fleet-telemetry delta snapshot,
  ``{"op": "fleet"[, "format": "prom"]}`` serves the merged fleet
  view, ``{"op": "metrics"}`` dumps this process's local registry —
  the uds face of ``POST /api/v3/telemetry`` / ``GET /fleet`` /
  ``GET /metrics.json``.

Connection model mirrors the REST transceiver's: the client holds one
connection for the outbound ops and one owned by its receive thread
(a parked ``poll`` must never block a ``post_batch``). Each server
connection gets its own handler thread — long-polling requires one
anyway.
"""

from __future__ import annotations

from typing import List, Optional

import threading

from namazu_tpu import obs, tenancy
from namazu_tpu.endpoint.framed import FramedServer
from namazu_tpu.endpoint.rest import QueuedEndpoint
from namazu_tpu.endpoint.shm import (DEFAULT_CAPACITY, ShmIngressThread,
                                     ShmRing)
from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.uds")


class UdsEndpoint(QueuedEndpoint):
    NAME = "uds"

    def __init__(self, path: str, poll_timeout: float = 30.0,
                 ingress_cap: int = 0, retry_after_s: float = 1.0):
        super().__init__()
        self.path = path
        self.poll_timeout = poll_timeout
        # bounded ingress, same contract as the REST endpoint
        # (doc/robustness.md): over-cap post/backhaul ops are refused
        # with a retry_after hint instead of growing the hub queue
        # unboundedly. 0 = unbounded.
        self.ingress_cap = max(0, int(ingress_cap))
        self.retry_after_s = max(0.0, float(retry_after_s))
        # the shared keep-alive serve loop (endpoint/framed.py): frame
        # hygiene, error answering, span-context merge/echo, severable
        # connections — one implementation across the framed wires
        self._server: Optional[FramedServer] = None
        # shared-memory ingress rings handed out by the shm_open op
        # (endpoint/shm.py): one drain thread per ring
        self._shm_threads: List[ShmIngressThread] = []
        self._shm_lock = threading.Lock()
        self._shm_seq = 0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._server is not None:
            return
        srv = FramedServer(self._handle, name="uds-endpoint",
                           decorate=self._decorate)
        srv.bind_unix(self.path)
        srv.start()
        self._server = srv
        log.info("UDS endpoint on %s", self.path)

    def _decorate(self, req: dict, resp: dict) -> None:
        """The zero-RTT version piggyback: every response carries
        ``table_version`` when the request's namespace has a table
        plane — how an edge notices a rollover within one batch
        (doc/performance.md). Namespaced ops (the framed ``run``
        field) see THEIR tenant's version, never the process
        default's."""
        if getattr(self, "hub", None) is None:
            return
        ns = req.get(tenancy.RUN_FIELD) or ""
        version = self.hub.table_version(ns if isinstance(ns, str)
                                         else "")
        if version is not None:
            resp.setdefault("table_version", version)

    def shutdown(self) -> None:
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()
        with self._shm_lock:
            threads, self._shm_threads = self._shm_threads, []
        for t in threads:
            t.shutdown()

    def sever(self) -> int:
        """Cut every live connection (simulated crash, like
        RestEndpoint.sever) and supersede parked pollers — a parked
        client poll must error and reconnect, not keep talking to a
        dead orchestrator's handler thread."""
        srv = self._server
        n = srv.sever() if srv is not None else 0
        with self._queues_lock:
            queues = list(self._queues.values())
        for q in queues:
            q.supersede()
        return n

    # -- ops --------------------------------------------------------------

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "post_batch":
            return self._op_post_batch(req)
        if op == "poll":
            return self._op_poll(req)
        if op == "ack":
            return self._op_ack(req)
        if op == "backhaul":
            return self._op_backhaul(req)
        if op == "table":
            return self._op_table(req)
        if op == "shm_open":
            return self._op_shm_open(req)
        if op == "control":
            return self._op_control(req)
        if op in ("lease", "renew", "release", "reclaim", "runs"):
            return self._op_tenancy(req)
        # observability ops (telemetry push / fleet view / local
        # metrics dump — obs/federation.py): the uds wire serves the
        # same fleet surface as the REST routes, so a same-host fleet
        # is fully inspectable without a TCP port
        from namazu_tpu.obs import federation

        resp = federation.handle_obs_op(req)
        if resp is not None:
            return resp
        return {"ok": False, "error": f"unknown op {op!r}"}

    @staticmethod
    def _entity_error(entity: str):
        """Reject entity ids that would alias a composite route key
        (tenancy/shard.py): '\x1f' inside an entity id would misparse
        as a namespace separator in journals and watchdog sweeps."""
        if tenancy.ROUTE_SEP in entity:
            return {"ok": False,
                    "error": "entity id must not contain \x1f"}
        return None

    @staticmethod
    def _req_ns(req: dict):
        """``(namespace, None)`` or ``(None, error resp)`` for one op's
        ``run`` field (tenancy plane; absent = the process-default
        namespace, every pre-tenancy client)."""
        raw = req.get(tenancy.RUN_FIELD)
        if raw is None:
            return "", None
        try:
            return tenancy.validate_ns(raw), None
        except ValueError as e:
            return None, {"ok": False, "error": str(e)}

    def _op_control(self, req: dict) -> dict:
        """The framed face of ``POST /api/v3/control``: enable/disable
        orchestration, scoped by the op's ``run`` field exactly like
        the REST route's X-Nmz-Run header (a namespaced op suspends/
        resumes that tenant's publisher only; absent = the
        process-default policy, pre-tenancy behavior)."""
        from namazu_tpu.signal.control import Control, ControlOp

        hub = getattr(self, "hub", None)
        if hub is None:
            return {"ok": False, "error": "endpoint not attached to an "
                                          "orchestrator hub"}
        ns, err = self._req_ns(req)
        if err is not None:
            return err
        try:
            ctrl = Control(ControlOp(str(req.get("control_op") or "")))
        except ValueError:
            return {"ok": False,
                    "error": f"bad control op "
                             f"{req.get('control_op')!r}; known: "
                             f"{[o.value for o in ControlOp]}"}
        tenancy.set_ns(ctrl, ns)
        hub.post_control(ctrl)
        return {"ok": True}

    def _op_tenancy(self, req: dict) -> dict:
        """The framed face of the slot-leasing wire (doc/tenancy.md) —
        same op grammar as ``POST /api/v3/tenancy``."""
        registry = getattr(self.hub, "run_registry", None) \
            if getattr(self, "hub", None) is not None else None
        if registry is None:
            return {"ok": False,
                    "error": "this orchestrator hosts no tenancy plane"}
        from namazu_tpu.policy.base import PolicyError
        from namazu_tpu.tenancy.registry import (TenancyError,
                                                 handle_tenancy_op)
        try:
            resp = handle_tenancy_op(req, registry)
        except (TenancyError, PolicyError, ValueError) as e:
            return {"ok": False, "error": str(e)}
        if resp is None:  # pragma: no cover - dispatcher filtered ops
            return {"ok": False,
                    "error": f"unknown tenancy op {req.get('op')!r}"}
        return resp

    def _ingress_refusal(self) -> Optional[dict]:
        """The uds face of the bounded-ingress plane: consult the chaos
        seam, then the cap; a refusal doc mirrors the REST 429 +
        Retry-After contract (``transient`` tells the transceiver's
        bounded retry to honor ``retry_after`` instead of treating it
        as a hard error)."""
        from namazu_tpu import chaos

        fault = chaos.decide("endpoint.ingress.refuse")
        if fault is not None:
            retry_after = float(fault.get("retry_after", 0.05))
            obs.ingress_rejected(self.NAME, "chaos")
            return {"ok": False, "transient": True,
                    "retry_after": retry_after,
                    "error": f"ingress refused (chaos); retry after "
                             f"{retry_after:g}s"}
        cap = self.ingress_cap
        if cap > 0 and self.hub.event_queue.qsize() >= cap:
            obs.ingress_rejected(self.NAME, "backpressure")
            return {"ok": False, "transient": True,
                    "retry_after": self.retry_after_s,
                    "error": f"ingress refused (backpressure); retry "
                             f"after {self.retry_after_s:g}s"}
        return None

    def _decode_batch(self, entity: str, body):
        """``(events, None)`` or ``(None, error string)`` for one
        post_batch body — shared by the op wire and the shm ingress."""
        events: List[Event] = []
        for i, item in enumerate(body):
            try:
                sig = signal_from_jsonable(item)
            except (SignalError, ValueError, TypeError) as e:
                return None, f"batch item {i}: {e}"
            if not isinstance(sig, Event):
                return None, f"batch item {i} is not an event"
            if sig.entity_id != entity:
                return None, (f"batch item {i} entity "
                              f"{sig.entity_id!r} does not match "
                              f"{entity!r}")
            events.append(sig)
        return events, None

    def _op_post_batch(self, req: dict) -> dict:
        entity = str(req.get("entity") or "")
        body = req.get("events")
        if not entity or not isinstance(body, list) or not body:
            return {"ok": False,
                    "error": "post_batch needs entity + a non-empty "
                             "events array"}
        bad_entity = self._entity_error(entity)
        if bad_entity is not None:
            return bad_entity
        ns, bad = self._req_ns(req)
        if bad is not None:
            return bad
        refusal = self._ingress_refusal()
        if refusal is not None:
            return refusal
        events, err = self._decode_batch(entity, body)
        if err is not None:
            return {"ok": False, "error": err}
        fresh = [ev for ev in events if not self.note_event_uuid(ev.uuid)]
        if ns:
            for ev in fresh:
                tenancy.set_ns(ev, ns)
        if fresh:
            self.hub.post_events(fresh, self.NAME)
        return {"ok": True, "accepted": len(fresh),
                "duplicates": len(events) - len(fresh)}

    # -- shared-memory ingress (endpoint/shm.py) --------------------------

    def _op_shm_open(self, req: dict) -> dict:
        """Create one ingress ring + drain thread for this client.
        The ring carries post_batch frames only (the acked ops stay on
        this connection); its CAPACITY is the backpressure — a full
        ring makes the client fall back to the acked op wire, where
        the bounded-ingress 429 contract applies as usual."""
        entity = str(req.get("entity") or "")
        try:
            capacity = int(req.get("capacity") or DEFAULT_CAPACITY)
        except (TypeError, ValueError):
            return {"ok": False, "error": "bad shm capacity"}
        capacity = min(max(capacity, 1 << 16), 1 << 26)
        with self._shm_lock:
            self._shm_seq += 1
            path = f"{self.path}.shm{self._shm_seq}"
        try:
            ring = ShmRing(path, capacity, create=True)
        except OSError as e:
            return {"ok": False, "error": f"shm ring: {e}"}
        thread = ShmIngressThread(
            ring, self._shm_handle,
            name=f"shm-ingress-{entity or self._shm_seq}")
        with self._shm_lock:
            self._shm_threads.append(thread)
        log.info("shm ingress ring %s (%d bytes) for %s", path,
                 capacity, entity or "<any>")
        return {"ok": True, "path": path, "capacity": capacity}

    def _shm_handle(self, doc) -> None:
        """One decoded ring frame -> the hub, through the same dedupe
        ring as the op wire. Malformed frames cost themselves (logged),
        never the ring."""
        if not isinstance(doc, dict) or doc.get("op") != "post_batch":
            log.warning("shm frame is not a post_batch op: %r",
                        type(doc))
            return
        entity = str(doc.get("entity") or "")
        body = doc.get("events")
        if not entity or not isinstance(body, list) or not body:
            log.warning("malformed shm post_batch frame dropped")
            return
        events, err = self._decode_batch(entity, body)
        if err is not None:
            log.warning("shm post_batch frame dropped: %s", err)
            return
        ns, bad = self._req_ns(doc)
        if bad is not None:
            log.warning("shm post_batch frame dropped: %s", bad["error"])
            return
        fresh = [ev for ev in events
                 if not self.note_event_uuid(ev.uuid)]
        if ns:
            for ev in fresh:
                tenancy.set_ns(ev, ns)
        if fresh:
            self.hub.post_events(fresh, self.NAME)

    def _op_poll(self, req: dict) -> dict:
        entity = str(req.get("entity") or "")
        if not entity:
            return {"ok": False, "error": "poll needs entity"}
        bad_entity = self._entity_error(entity)
        if bad_entity is not None:
            return bad_entity
        try:
            batch = max(1, int(req.get("batch", 1)))
            linger = min(max(0.0, float(req.get("linger_ms", 0))),
                         1000.0) / 1000.0
            timeout = min(max(0.0, float(req.get("timeout_s",
                                                 self.poll_timeout))),
                          self.poll_timeout)
        except (TypeError, ValueError) as e:
            return {"ok": False, "error": f"bad poll params: {e}"}
        ns, bad = self._req_ns(req)
        if bad is not None:
            return bad
        actions = self._queue_for(entity, ns).peek_batch(
            batch, timeout, linger=linger)
        if actions:
            obs.event_batch("actions_poll", len(actions))
        return {"ok": True,
                "actions": [a.to_jsonable() for a in actions]}

    def _op_ack(self, req: dict) -> dict:
        entity = str(req.get("entity") or "")
        bad_entity = self._entity_error(entity)
        if bad_entity is not None:
            return bad_entity
        uuids = req.get("uuids")
        if (not entity or not isinstance(uuids, list) or not uuids
                or not all(isinstance(u, str) for u in uuids)):
            return {"ok": False,
                    "error": "ack needs entity + a uuids array"}
        ns, bad = self._req_ns(req)
        if bad is not None:
            return bad
        deleted, missing = self._queue_for(entity, ns).delete_many(uuids)
        for action in deleted:
            self.ack_action(entity, action)
        return {"ok": True, "deleted": [a.uuid for a in deleted],
                "missing": missing}

    def _op_backhaul(self, req: dict) -> dict:
        entity = str(req.get("entity") or "")
        if not entity:
            return {"ok": False, "error": "backhaul needs entity"}
        bad_entity = self._entity_error(entity)
        if bad_entity is not None:
            return bad_entity
        ns, bad = self._req_ns(req)
        if bad is not None:
            return bad
        refusal = self._ingress_refusal()
        if refusal is not None:
            return refusal
        try:
            accepted, duplicates = self.ingest_backhaul(req, entity,
                                                        ns=ns)
        except ValueError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True, "accepted": accepted,
                "duplicates": duplicates}

    def _op_table(self, req: dict) -> dict:
        """The published table, scoped by the op's ``run`` field to
        that tenant's OWN publisher (doc/tenancy.md "Per-namespace
        tables"); absent = the process default, pre-tenancy
        behavior."""
        ns, bad = self._req_ns(req)
        if bad is not None:
            return bad
        version, doc = self.hub.table_doc(ns)
        return {"ok": True, "version": version, "table": doc}
