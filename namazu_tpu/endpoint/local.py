"""Local (in-process) endpoint.

Parity: /root/reference/nmz/endpoint/local/localendpoint.go — the
pure-channel bridge used by autopilot mode and every in-process test.
Inspector-side local transceivers register an action sink per entity;
events are posted straight into the hub.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict

from namazu_tpu.endpoint.hub import Endpoint
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.local")

ActionSink = Callable[[Action], None]


class LocalEndpoint(Endpoint):
    NAME = "local"

    def __init__(self) -> None:
        self._sinks: Dict[str, ActionSink] = {}
        self._lock = threading.Lock()

    # inspector side ----------------------------------------------------

    def connect(self, entity_id: str, sink: ActionSink) -> None:
        with self._lock:
            self._sinks[entity_id] = sink

    def disconnect(self, entity_id: str) -> None:
        with self._lock:
            self._sinks.pop(entity_id, None)

    def post_event(self, event: Event) -> None:
        self.hub.post_event(event, self.NAME)

    # orchestrator side -------------------------------------------------

    def send_action(self, action: Action) -> None:
        with self._lock:
            sink = self._sinks.get(action.entity_id)
        if sink is None:
            log.warning("local: no sink for entity %s", action.entity_id)
            return
        sink(action)
