"""Shared-memory ring: the same-host data plane beside ``uds://``.

The million-events/s serving plane's third wire (doc/performance.md
"Binary wire + sharded edge"). A same-host inspector that already
speaks ``uds://`` can ask the endpoint for a **shared-memory ring**
(the ``shm_open`` op) and push its event bursts through it: one
binary-codec frame memcpy'd into a mmap'd tmpfs file, no syscall, no
socket, no wakeup on the posting path. The ring carries the HIGH-RATE
direction only (event batches); polls, acks, table fetches, and
backhaul stay on the uds control connection — they need per-request
acknowledgement semantics the one-way ring deliberately does not have.

Durability/exactly-once: a frame written to the ring is in the server
process's address space — the only loss mode is server death before
the drain, exactly the crash window the transceiver's unacked-replay
ring already covers (the receive loop's reconnect replays deferred
events over the uds op wire, and the endpoint's dedupe ring absorbs
any double). A FULL ring falls back to the acked uds op, loss-free.
The ``wire.shm.drop`` chaos seam drops a burst pre-write (the
accounted-loss case the invariant harness ledgers).

Layout of a ring file (little-endian, offsets monotonic u64, index =
offset % capacity)::

    0..3    magic  b"NMZR"
    4..7    capacity u32
    8..15   head   u64  (read offset  — only the reader writes it)
    16..23  tail   u64  (write offset — only the writer writes it)
    24..    data[capacity]

Frames inside the ring reuse the framed-wire convention: ``u32 length``
with the high bit marking a binary-codec body (endpoint/agent.py).
SPSC by construction: one writer process, one reader thread. The
head/tail stores are 8-byte aligned single-word writes — published
AFTER their data on the strongly-ordered platforms this same-host
transport targets; this is a loopback data plane, not a portable IPC
library.
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
import time
from typing import Optional, Tuple

from namazu_tpu.utils.log import get_logger

log = get_logger("endpoint.shm")

MAGIC = b"NMZR"
HDR = 24
_BINARY_FLAG = 0x80000000
_pack_u64 = struct.Struct("<Q").pack_into
_unpack_u64 = struct.Struct("<Q").unpack_from
_pack_u32 = struct.Struct("<I").pack_into
_unpack_u32 = struct.Struct("<I").unpack_from

DEFAULT_CAPACITY = 1 << 20


class ShmRing:
    """One SPSC byte ring over a mmap'd file (tmpfs path — the caller
    picks something under /dev/shm or next to its uds socket)."""

    def __init__(self, path: str, capacity: int = DEFAULT_CAPACITY,
                 create: bool = False):
        self.path = path
        if create:
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC,
                         0o600)
            try:
                os.ftruncate(fd, HDR + capacity)
                self._mm = mmap.mmap(fd, HDR + capacity)
            finally:
                os.close(fd)
            self._mm[0:4] = MAGIC
            _pack_u32(self._mm, 4, capacity)
            _pack_u64(self._mm, 8, 0)
            _pack_u64(self._mm, 16, 0)
            self.capacity = capacity
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            if bytes(self._mm[0:4]) != MAGIC:
                self._mm.close()
                raise ValueError(f"{path}: not a shm ring")
            (self.capacity,) = _unpack_u32(self._mm, 4)
            if HDR + self.capacity != size:
                self._mm.close()
                raise ValueError(f"{path}: truncated ring")
        self._view = memoryview(self._mm)

    # -- offsets -----------------------------------------------------------

    @property
    def head(self) -> int:
        return _unpack_u64(self._mm, 8)[0]

    @property
    def tail(self) -> int:
        return _unpack_u64(self._mm, 16)[0]

    def pending(self) -> int:
        return self.tail - self.head

    # -- writer side -------------------------------------------------------

    def _copy_in(self, off: int, data) -> None:
        cap = self.capacity
        idx = off % cap
        first = min(len(data), cap - idx)
        base = HDR + idx
        self._view[base:base + first] = data[:first]
        if first < len(data):
            self._view[HDR:HDR + len(data) - first] = data[first:]

    def try_write_frame(self, payload: bytes,
                        binary: bool = True) -> bool:
        """One frame into the ring; False when it does not fit (the
        caller falls back to the acked op wire). Non-blocking by
        design — the zero-RTT path never waits on a slow reader."""
        need = 4 + len(payload)
        if need > self.capacity:
            return False
        tail = self.tail
        if tail - self.head + need > self.capacity:
            return False
        header = bytearray(4)
        _pack_u32(header, 0,
                  len(payload) | (_BINARY_FLAG if binary else 0))
        self._copy_in(tail, header)
        self._copy_in(tail + 4, payload)
        # publish AFTER the data: the reader only advances on tail
        _pack_u64(self._mm, 16, tail + need)
        return True

    # -- reader side -------------------------------------------------------

    def _copy_out(self, off: int, n: int) -> bytes:
        cap = self.capacity
        idx = off % cap
        first = min(n, cap - idx)
        base = HDR + idx
        out = bytes(self._view[base:base + first])
        if first < n:
            out += bytes(self._view[HDR:HDR + n - first])
        return out

    def try_read_frame(self) -> Optional[Tuple[bytes, bool]]:
        """One ``(payload, is_binary)`` off the ring, or None when
        empty. Raises ValueError on a corrupt length (the reader drops
        the ring — framing inside shared memory cannot resync)."""
        head = self.head
        if self.tail - head < 4:
            return None
        (length,) = _unpack_u32(self._copy_out(head, 4), 0)
        binary = bool(length & _BINARY_FLAG)
        length &= ~_BINARY_FLAG
        if length > self.capacity - 4:
            raise ValueError(f"corrupt shm frame length {length}")
        if self.tail - head < 4 + length:
            return None  # frame still being written
        payload = self._copy_out(head + 4, length)
        _pack_u64(self._mm, 8, head + 4 + length)
        return payload, binary

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        try:
            self._view.release()
        except (BufferError, AttributeError):  # pragma: no cover
            pass
        try:
            self._mm.close()
        except (BufferError, ValueError):  # pragma: no cover
            pass

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ShmIngressThread:
    """The endpoint-side drain of one client's ring: decode each frame
    and hand the doc to ``handle`` (the uds endpoint routes it through
    the SAME post_batch handler the op wire uses — dedupe ring, hub
    fan-in, bounded ingress all included). Adaptive poll: spin briefly
    at high rate, back off to a millisecond sleep when idle."""

    def __init__(self, ring: ShmRing, handle, name: str = "shm-ingress"):
        self.ring = ring
        self._handle = handle
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        import json as _json

        from namazu_tpu.signal import binary as _binary

        idle_spins = 0
        while not self._stop.is_set():
            try:
                frame = self.ring.try_read_frame()
            except ValueError as e:
                log.warning("shm ring corrupt (%s); abandoning it", e)
                return
            if frame is None:
                idle_spins += 1
                if idle_spins > 64:
                    time.sleep(0.001)
                continue
            idle_spins = 0
            payload, is_binary = frame
            try:
                doc = (_binary.loads(payload) if is_binary
                       else _json.loads(payload))
            except ValueError as e:
                # one garbled frame costs itself, never the ring: the
                # length prefix still delimited it correctly
                log.warning("undecodable shm frame dropped: %s", e)
                continue
            try:
                self._handle(doc)
            except Exception:
                log.exception("shm ingress handler failed")

    def shutdown(self, drain_s: float = 1.0) -> None:
        """Stop after draining what is already in the ring (bounded):
        frames the client wrote before its shutdown must reach the
        hub."""
        deadline = time.monotonic() + drain_s
        while self.ring.pending() > 0 and time.monotonic() < deadline:
            time.sleep(0.002)
        self._stop.set()
        self._thread.join(timeout=2.0)
        self.ring.close()
        self.ring.unlink()
