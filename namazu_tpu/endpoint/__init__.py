"""Orchestrator-side endpoints: where inspector events arrive and actions
are dispatched back.

Capability parity with /root/reference/nmz/endpoint (endpoint.go:63-144):
a hub merges event streams from all transports (local in-process, REST
HTTP, framed-TCP guest agent) into one queue, remembers which transport
each entity spoke on, and routes actions back over the right one.
"""

from namazu_tpu.endpoint.hub import EndpointHub, Endpoint
from namazu_tpu.endpoint.local import LocalEndpoint

__all__ = ["EndpointHub", "Endpoint", "LocalEndpoint"]
