"""Tenancy plane: one orchestrator serving N concurrent experiments.

The reference Namazu (and our reproduction through PR 12) runs one
orchestrator per experiment: ``nmz-tpu campaign`` forks a full ``run``
process per slot, so aggregate throughput across experiments is a
process-count problem. This package is the consolidation move serving
stacks make when they go from one-model-per-process to a multi-tenant
scheduler (doc/tenancy.md):

* **Namespaced runs** — a :class:`~namazu_tpu.tenancy.registry.RunRegistry`
  hosts N concurrent run namespaces inside one orchestrator process.
  Each namespace owns its own policy instance (its own ScheduledQueue),
  flight-recorder run, crash-recovery journal, and collected trace.
  Every wire op carries a ``run`` namespace — the ``X-Nmz-Run`` header
  on the REST wire, a ``run`` field on framed/shm ops. An absent
  namespace is the **process-default namespace**: every pre-tenancy
  client lands there with byte-identical replies.
* **Entity-sharded hub** — the EndpointHub's single routing lock is
  split into per-shard locks keyed by ``fnv64a(namespace:entity) % N``
  (:mod:`namazu_tpu.tenancy.shard`), so namespaces never contend on
  one lock.
* **Slot leasing** — tenants acquire namespaces through
  ``lease``/``renew``/``release`` ops with TTL expiry
  (:mod:`namazu_tpu.tenancy.registry`): a crashed tenant's lease
  expires, its namespace is reclaimed with parked events left in its
  journal, and a re-lease over the same journal dir recovers them
  exactly-once — sibling namespaces dispatch undisturbed throughout.

The host side lives in :class:`~namazu_tpu.tenancy.host.TenantOrchestrator`;
the client side (the campaign supervisor's ``--serve`` mode, bench
``--runs``) in :class:`~namazu_tpu.tenancy.client.TenancyClient`.
"""

from __future__ import annotations

from typing import Tuple

#: the process-default namespace: pre-tenancy clients (no run header/
#: field) land here and observe the exact pre-tenancy behavior
DEFAULT_NS = ""

#: the REST wire's namespace piggyback (established X-Nmz-* style)
RUN_HEADER = "X-Nmz-Run"

#: the framed/shm wire's namespace field
RUN_FIELD = "run"

#: separator inside composite routing keys. Unit separator: never part
#: of an entity id or a run namespace (validate_ns refuses it), so
#: ``split_route_key`` is unambiguous.
ROUTE_SEP = "\x1f"


def ns_of(sig) -> str:
    """The namespace a signal is tagged with ('' = default). Tags are
    plain attributes set at the ingress edge (endpoint handlers) and
    propagated event -> action at ``Action.for_event``."""
    return getattr(sig, "_ns", DEFAULT_NS)


def set_ns(sig, ns: str) -> None:
    """Tag a signal with its namespace (no-op for the default one, so
    default-namespace signals stay attribute-identical to pre-tenancy
    ones)."""
    if ns:
        sig._ns = ns


def route_key(ns: str, entity: str) -> str:
    """The hub/queue key for (namespace, entity). The default
    namespace's key IS the bare entity id — pre-tenancy state (journaled
    route tables, tests pinning key shapes) reads unchanged."""
    return entity if not ns else ns + ROUTE_SEP + entity


def split_route_key(key: str) -> Tuple[str, str]:
    """Inverse of :func:`route_key`: ``(namespace, entity)``."""
    if ROUTE_SEP in key:
        ns, _, entity = key.partition(ROUTE_SEP)
        return ns, entity
    return DEFAULT_NS, key


def signal_route_key(sig) -> str:
    """The routing key of one tagged signal."""
    return route_key(ns_of(sig), sig.entity_id)


def validate_ns(ns: str) -> str:
    """Check a wire-supplied namespace; returns it. Raises ValueError
    on names that would alias the default namespace or break the
    composite-key encoding."""
    if not isinstance(ns, str) or not ns:
        raise ValueError("run namespace must be a non-empty string")
    if ROUTE_SEP in ns:
        raise ValueError("run namespace must not contain \\x1f")
    if len(ns) > 128:
        raise ValueError("run namespace too long (>128 chars)")
    return ns


from namazu_tpu.tenancy.shard import fnv64a, shard_index  # noqa: E402,F401
