"""TenancyClient: the tenant side of the lease wire.

One small client for both transports a serving orchestrator exposes:

* ``http(s)://host:port`` — ``POST /api/v3/tenancy`` with a JSON op
  body (the REST face; endpoint/rest.py);
* ``uds:///path/to.sock`` (or a bare socket path) — the same op dicts
  as framed JSON over the uds endpoint (endpoint/uds.py).

Used by the campaign supervisor's ``--serve`` mode and by
``bench.py --runs``; errors surface as :class:`TenancyWireError` so a
supervisor can classify them as infra failures.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Any, Dict, Optional
from urllib.parse import urlparse

from namazu_tpu.endpoint.agent import read_frame, write_frame
from namazu_tpu.utils.log import get_logger

log = get_logger("tenancy.client")


class TenancyWireError(Exception):
    """A tenancy op failed on the wire. ``retry_after`` carries the
    server-requested backoff (seconds) when the refusal named one —
    the placement plane's 429 admission refusals do — and ``status``
    the refusal's HTTP-style status code; both None otherwise, so the
    bounded-retry path can honor a Retry-After without string
    parsing."""

    def __init__(self, message: str, retry_after=None,
                 status=None) -> None:
        super().__init__(message)
        self.retry_after = retry_after
        self.status = status


class TenancyClient:
    def __init__(self, url: str, timeout: float = 10.0) -> None:
        self.url = url
        self.timeout = timeout
        parsed = urlparse(url)
        self._tcp_addr = None
        if parsed.scheme in ("http", "https"):
            self._uds_path = None
            self._base = url.rstrip("/")
        elif parsed.scheme == "uds":
            # uds://tmp/x.sock parses as netloc="tmp" path="/x.sock";
            # rejoin them so relative forms resolve to the SAME path
            # the transceivers use (url[len("uds://"):])
            self._uds_path = parsed.netloc + parsed.path
        elif parsed.scheme == "tcp":
            # tcp://host:port — the same framed-JSON grammar as uds,
            # over TCP (endpoint/framed.py bind_tcp): how the placement
            # service serves pool ops across hosts without HTTP
            self._uds_path = None
            self._tcp_addr = (parsed.hostname or "127.0.0.1",
                              int(parsed.port or 0))
        elif not parsed.scheme:
            self._uds_path = url
        else:
            raise TenancyWireError(
                f"unsupported tenancy url {url!r} (want http(s)://, "
                "uds:// or tcp://)")
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    # -- transport --------------------------------------------------------

    def _op(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        if self._uds_path is not None or self._tcp_addr is not None:
            return self._op_framed(doc)
        return self._op_http(doc)

    def _op_http(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self._base + "/api/v3/tenancy",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                body = json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read() or b"{}").get("error", "")
            except ValueError:
                detail = ""
            retry_after = None
            try:
                raw = e.headers.get("Retry-After") if e.headers else None
                if raw is not None:
                    retry_after = float(raw)
            except (TypeError, ValueError):
                pass
            raise TenancyWireError(
                f"tenancy op {doc.get('op')!r} failed: HTTP {e.code} "
                f"{detail}".strip(), retry_after=retry_after,
                status=e.code) from None
        except (OSError, ValueError) as e:
            raise TenancyWireError(
                f"tenancy op {doc.get('op')!r} failed: {e}") from e
        return body

    def _connect(self) -> socket.socket:
        if self._tcp_addr is not None:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            target: Any = self._tcp_addr
        else:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            target = self._uds_path
        sock.settimeout(self.timeout)
        try:
            sock.connect(target)
        except OSError as e:
            sock.close()
            raise TenancyWireError(
                f"tenancy socket {target}: {e}") from e
        return sock

    def _op_framed(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            for attempt in (0, 1):
                sock = self._sock
                if sock is None:
                    sock = self._sock = self._connect()
                try:
                    write_frame(sock, doc)
                    resp = read_frame(sock)
                except (OSError, ValueError) as e:
                    self._drop_sock()
                    if attempt == 0:
                        continue  # one transparent reconnect
                    raise TenancyWireError(
                        f"tenancy op {doc.get('op')!r} failed: {e}") \
                        from e
                if resp is None:
                    self._drop_sock()
                    if attempt == 0:
                        continue
                    raise TenancyWireError(
                        f"tenancy op {doc.get('op')!r}: connection "
                        "closed")
                if not isinstance(resp, dict):
                    raise TenancyWireError(
                        f"tenancy op {doc.get('op')!r}: non-object "
                        "reply")
                if not resp.get("ok", True):
                    retry_after = resp.get("retry_after")
                    try:
                        retry_after = (float(retry_after)
                                       if retry_after is not None
                                       else None)
                    except (TypeError, ValueError):
                        retry_after = None
                    raise TenancyWireError(
                        f"tenancy op {doc.get('op')!r} failed: "
                        f"{resp.get('error')}",
                        retry_after=retry_after,
                        status=resp.get("status"))
                return resp
        raise TenancyWireError("unreachable")  # pragma: no cover

    def _drop_sock(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            self._drop_sock()

    # -- ops --------------------------------------------------------------

    def lease(self, run: str, ttl_s: Optional[float] = None,
              policy: str = "random",
              policy_param: Optional[dict] = None,
              journal_dir: str = "",
              collect_trace: bool = True) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"op": "lease", "run": run,
                               "policy": policy,
                               "collect_trace": collect_trace}
        if ttl_s is not None:
            doc["ttl_s"] = ttl_s
        if policy_param:
            doc["policy_param"] = policy_param
        if journal_dir:
            doc["journal_dir"] = journal_dir
        return self._op(doc)

    def renew(self, lease_id: str,
              ttl_s: Optional[float] = None) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"op": "renew", "lease_id": lease_id}
        if ttl_s is not None:
            doc["ttl_s"] = ttl_s
        return self._op(doc)

    def release(self, lease_id: str,
                want_trace: bool = True) -> Dict[str, Any]:
        return self._op({"op": "release", "lease_id": lease_id,
                         "trace": want_trace})

    def reclaim(self, lease_id: str) -> Dict[str, Any]:
        """Park-preserving detach: the namespace's parked events stay
        journaled (exactly like a lease expiry) for an exactly-once
        re-lease — the placement plane's graceful-drain primitive."""
        return self._op({"op": "reclaim", "lease_id": lease_id})

    def runs(self) -> Dict[str, Any]:
        return self._op({"op": "runs"})

    def op(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Send one raw op dict (the pool-level grammar the placement
        service adds — ``pool_status``/``drain``/``hosts`` — rides the
        same transport as the tenancy ops)."""
        return self._op(dict(doc))
