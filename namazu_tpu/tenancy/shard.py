"""Entity sharding for the tenancy plane: FNV-1a keys + a sharded
route table.

The pre-tenancy EndpointHub serialized ALL routing/liveness bookkeeping
through one lock — fine for one run, a convoy for eight campaigns whose
inbound bursts all touch it. :class:`ShardedRoutes` splits that state
into ``n_shards`` independently-locked shards keyed by
``fnv64a(namespace + ':' + entity) % n_shards``, so two namespaces (or
two disjoint entity sets) practically never contend on one lock, while
per-key operations stay exactly as cheap as before.

FNV-1a (64-bit) is the hash: stable across processes and Python builds
(``hash()`` is salted per process — a journal written by one process
must shard identically in its successor), one multiply + xor per byte,
and well-mixed in the low bits the modulo keeps.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from namazu_tpu import tenancy
from namazu_tpu.policy.replayable import fnv64a as _fnv64a_bytes
from namazu_tpu.utils import timesource


def fnv64a(text: str) -> int:
    """64-bit FNV-1a of a string's UTF-8 bytes (the str face of the
    replayable-policy helper — ONE implementation of a hash whose
    cross-process stability is load-bearing)."""
    return _fnv64a_bytes(text.encode("utf-8"))


def shard_index(ns: str, entity: str, n_shards: int) -> int:
    """The shard owning (namespace, entity)."""
    return fnv64a(ns + ":" + entity) % n_shards


class _Shard:
    __slots__ = ("lock", "route", "last_seen", "warned")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        #: route key -> endpoint name
        self.route: Dict[str, str] = {}
        #: route key -> monotonic last-inbound time
        self.last_seen: Dict[str, float] = {}
        #: route keys already warned unroutable
        self.warned: set = set()


class ShardedRoutes:
    """The hub's routing/liveness table, sharded by (ns, entity).

    Keys are composite route keys (:func:`namazu_tpu.tenancy.route_key`);
    the default namespace's keys are bare entity ids, so everything a
    pre-tenancy consumer reads (journaled route snapshots, watchdog
    sweeps) keeps its shape.
    """

    DEFAULT_SHARDS = 16

    def __init__(self, n_shards: int = DEFAULT_SHARDS) -> None:
        self.n_shards = max(1, int(n_shards))
        self._shards: List[_Shard] = [_Shard()
                                      for _ in range(self.n_shards)]

    def _shard(self, key: str) -> _Shard:
        ns, entity = tenancy.split_route_key(key)
        return self._shards[shard_index(ns, entity, self.n_shards)]

    # -- inbound bookkeeping --------------------------------------------

    def note_inbound(self, key: str, endpoint_name: str,
                     now: Optional[float] = None) -> Optional[str]:
        """Record one inbound event's route + liveness; returns the
        PREVIOUS endpoint name when the entity moved (the caller logs
        it — log I/O never runs under a shard lock). Liveness stamps
        read the process TimeSource: under a virtual clock the
        watchdog's ``stalled`` sweep compares against the SAME jumped
        clock, so a fast-forward cannot declare a healthy (parked)
        entity silent (doc/performance.md "Virtual clock")."""
        now = timesource.get().now() if now is None else now
        shard = self._shard(key)
        with shard.lock:
            prev = shard.route.get(key)
            shard.route[key] = endpoint_name
            shard.last_seen[key] = now
            shard.warned.discard(key)
        return prev if (prev is not None and prev != endpoint_name) \
            else None

    def note_inbound_many(self, keys, endpoint_name: str
                          ) -> List[Tuple[str, str]]:
        """Batch face: keys grouped by shard, ONE lock acquisition per
        touched shard. Returns the ``(key, previous_endpoint)`` moves."""
        now = timesource.get().now()
        by_shard: Dict[int, List[str]] = {}
        for key in keys:
            ns, entity = tenancy.split_route_key(key)
            by_shard.setdefault(
                shard_index(ns, entity, self.n_shards), []).append(key)
        moves: List[Tuple[str, str]] = []
        for idx, shard_keys in by_shard.items():
            shard = self._shards[idx]
            with shard.lock:
                for key in shard_keys:
                    prev = shard.route.get(key)
                    if prev is not None and prev != endpoint_name:
                        moves.append((key, prev))
                    shard.route[key] = endpoint_name
                    shard.last_seen[key] = now
                    shard.warned.discard(key)
        return moves

    # -- outbound resolution --------------------------------------------

    def resolve(self, key: str) -> Tuple[Optional[str], bool]:
        """``(endpoint_name_or_None, first_drop)`` for one action; the
        first unroutable hit per key arms its one-shot warning."""
        shard = self._shard(key)
        with shard.lock:
            name = shard.route.get(key)
            first_drop = False
            if name is None and key not in shard.warned:
                shard.warned.add(key)
                first_drop = True
        return name, first_drop

    def resolve_many(self, keys) -> List[Tuple[Optional[str], bool]]:
        """Batch resolve, one lock acquisition per touched shard;
        results align with ``keys``."""
        idxs = []
        by_shard: Dict[int, List[int]] = {}
        for i, key in enumerate(keys):
            ns, entity = tenancy.split_route_key(key)
            idx = shard_index(ns, entity, self.n_shards)
            idxs.append(idx)
            by_shard.setdefault(idx, []).append(i)
        out: List[Tuple[Optional[str], bool]] = [None] * len(keys)  # type: ignore[list-item]
        for idx, positions in by_shard.items():
            shard = self._shards[idx]
            with shard.lock:
                for i in positions:
                    key = keys[i]
                    name = shard.route.get(key)
                    first_drop = False
                    if name is None and key not in shard.warned:
                        shard.warned.add(key)
                        first_drop = True
                    out[i] = (name, first_drop)
        return out

    # -- snapshots -------------------------------------------------------

    def routes(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for shard in self._shards:
            with shard.lock:
                out.update(shard.route)
        return out

    def last_seen(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for shard in self._shards:
            with shard.lock:
                out.update(shard.last_seen)
        return out

    def stalled(self, timeout_s: float,
                now: Optional[float] = None) -> Dict[str, float]:
        now = timesource.get().now() if now is None else now
        out: Dict[str, float] = {}
        for shard in self._shards:
            with shard.lock:
                for key, t in shard.last_seen.items():
                    if now - t > timeout_s:
                        out[key] = now - t
        return out

    def forget_namespace(self, ns: str) -> int:
        """Drop every key of one namespace (a released/reclaimed run's
        routes must not shadow a later lease of the same name across a
        different endpoint); returns how many were dropped."""
        prefix = ns + tenancy.ROUTE_SEP
        dropped = 0
        for shard in self._shards:
            with shard.lock:
                dead = [k for k in shard.route if k.startswith(prefix)]
                for k in dead:
                    shard.route.pop(k, None)
                    shard.last_seen.pop(k, None)
                    shard.warned.discard(k)
                dropped += len(dead)
        return dropped
