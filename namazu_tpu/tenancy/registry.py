"""Run namespaces + slot leases: the tenancy plane's bookkeeping.

A :class:`RunNamespace` is everything one experiment owns inside a
shared orchestrator: its own policy instance (and therefore its own
ScheduledQueue of parked events), its own flight-recorder run, its own
crash-recovery journal, and its own collected trace. The
:class:`RunRegistry` hands namespaces out as TTL **leases**
(``lease`` / ``renew`` / ``release`` — the wire ops the REST
``/api/v3/tenancy`` route and the framed endpoints expose):

* a **released** lease flushes its namespace — parked events dispatch,
  the journal is removed, and the response carries the run's collected
  trace (the tenant records it into its own storage);
* an **expired** lease (the tenant crashed, stopped renewing) is
  **reclaimed**: parked events are dropped *without dispatch* — they
  stay in the namespace's journal, exactly as a SIGKILL would leave
  them — and a later lease naming the same journal dir recovers them
  exactly-once, while sibling namespaces dispatch undisturbed
  throughout. The ``tenancy.lease.expire`` chaos seam forces this path
  deterministically (doc/robustness.md).

Lease TTLs are renewed by live tenants (the campaign supervisor's
``--serve`` loop renews at TTL/3); the registry's sweep runs on the
host's reaper thread.
"""

from __future__ import annotations

import threading
import uuid as _uuid
from typing import Any, Dict, List, Optional

from namazu_tpu import chaos, tenancy
from namazu_tpu.utils import timesource
from namazu_tpu.obs import recorder as _recorder
from namazu_tpu.obs import spans as _spans
from namazu_tpu.policy.base import ExplorePolicy, create_policy
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.trace import SingleTrace

log = get_logger("tenancy")

#: default lease TTL (seconds) when the tenant names none
DEFAULT_TTL_S = 30.0
#: TTL bounds: a sub-100ms TTL is a typo'd footgun, an hours-long one
#: defeats crash reclamation
MIN_TTL_S = 0.2
MAX_TTL_S = 3600.0


class TenancyError(Exception):
    pass


class RunNamespace:
    """One tenant's state inside a shared orchestrator."""

    def __init__(self, name: str, policy: ExplorePolicy,
                 run_id: str, journal=None,
                 collect_trace: bool = True,
                 storage_dir: str = "") -> None:
        self.name = name
        self.policy = policy
        self.run_id = run_id
        self.journal = journal
        self.collect_trace = collect_trace
        self.storage_dir = storage_dir
        self.trace = SingleTrace()
        self.created_mono = timesource.get().now()
        #: events ingested for this namespace (the /fleet RUN row)
        self.events_ingested = 0
        #: per-namespace orchestration switch (a namespaced control op
        #: flips THIS, never the host's process-default flag): False
        #: routes the namespace's events to the passthrough policy
        self.enabled = True
        #: set once the namespace's policy flush has fully drained
        #: through the action loop (release waits on it)
        self.flushed = threading.Event()
        #: set when the namespace is detached (release or reclaim);
        #: the event loop drops late events for detached namespaces
        self.detached = False

    def parked_depth(self) -> int:
        q = getattr(self.policy, "_queue", None)
        try:
            return len(q) if q is not None else 0
        except Exception:  # pragma: no cover - defensive
            return 0


class Lease:
    __slots__ = ("lease_id", "ns", "ttl_s", "expires_at", "renewals",
                 "journal_dir")

    def __init__(self, ns: RunNamespace, ttl_s: float,
                 journal_dir: str = "") -> None:
        self.lease_id = _uuid.uuid4().hex
        self.ns = ns
        self.ttl_s = ttl_s
        # TTLs read the process TimeSource, same as the delay queue: a
        # virtual-clock fast-forward advances a live tenant's renewals
        # and its lease's expiry through the SAME clock, so a jump
        # cannot expire a lease whose tenant is healthy
        # (doc/performance.md "Virtual clock")
        self.expires_at = timesource.get().now() + ttl_s
        self.renewals = 0
        self.journal_dir = journal_dir


def _clamp_ttl(raw, default: float = DEFAULT_TTL_S) -> float:
    try:
        ttl = float(raw) if raw is not None else default
    except (TypeError, ValueError):
        raise TenancyError(f"bad ttl_s {raw!r}") from None
    return min(max(ttl, MIN_TTL_S), MAX_TTL_S)


def handle_tenancy_op(req: Dict[str, Any],
                      registry: "RunRegistry") -> Optional[Dict[str, Any]]:
    """Answer one wire-form tenancy op (``lease``/``renew``/``release``/
    ``runs``); ``None`` = not a tenancy op (the caller keeps
    dispatching). Shared by the REST ``POST /api/v3/tenancy`` route and
    the framed uds wire, so both faces speak one grammar. Raises
    :class:`TenancyError` (and the policy registry's errors) for the
    caller to turn into a 400 / ``ok: false``."""
    op = req.get("op")
    if op == "lease":
        doc = registry.lease(
            run=req.get("run") or "",
            ttl_s=req.get("ttl_s"),
            policy=str(req.get("policy") or "random"),
            policy_param=(req.get("policy_param")
                          if isinstance(req.get("policy_param"), dict)
                          else None),
            journal_dir=str(req.get("journal_dir") or ""),
            collect_trace=bool(req.get("collect_trace", True)),
            storage_dir=str(req.get("storage_dir") or ""))
        return dict(doc, ok=True)
    if op == "renew":
        return dict(registry.renew(str(req.get("lease_id") or ""),
                                   ttl_s=req.get("ttl_s")), ok=True)
    if op == "release":
        return dict(registry.release(
            str(req.get("lease_id") or ""),
            want_trace=bool(req.get("trace", True))), ok=True)
    if op == "reclaim":
        return dict(registry.reclaim(str(req.get("lease_id") or "")),
                    ok=True)
    if op == "runs":
        return {"ok": True, "runs": registry.payload()}
    return None


class RunRegistry:
    """The lease table of one :class:`TenantOrchestrator`."""

    def __init__(self, host) -> None:
        self._host = host
        self._lock = threading.Lock()
        self._leases: Dict[str, Lease] = {}
        self._by_ns: Dict[str, Lease] = {}

    # -- ops (the wire handlers call these) ------------------------------

    def lease(self, run: str, ttl_s=None, policy: str = "random",
              policy_param: Optional[dict] = None,
              journal_dir: str = "", collect_trace: bool = True,
              storage_dir: str = "") -> Dict[str, Any]:
        """Create + attach one namespace; returns the lease doc. The
        namespace name is the tenant's stable identity: re-leasing a
        name whose previous lease expired (with the same journal dir)
        recovers its journaled parked events exactly-once."""
        run = tenancy.validate_ns(run)
        ttl = _clamp_ttl(ttl_s)
        pol = create_policy(policy or "random")
        cfg = {"explore_policy": policy or "random"}
        if policy_param:
            cfg["explore_policy_param"] = dict(policy_param)
        pol.load_config(Config(cfg))
        journal = None
        if journal_dir:
            from namazu_tpu.chaos.journal import EventJournal

            journal = EventJournal(journal_dir)
        with self._lock:
            if run in self._by_ns:
                raise TenancyError(f"run {run!r} is already leased")
            run_id = _recorder.recorder().begin_pinned(
                run, run_id=f"{run}-{_uuid.uuid4().hex[:8]}")
            ns = RunNamespace(run, pol, run_id, journal=journal,
                              collect_trace=collect_trace,
                              storage_dir=storage_dir)
            lease = Lease(ns, ttl, journal_dir=journal_dir)
            self._leases[lease.lease_id] = lease
            self._by_ns[run] = lease
        recovered = self._host.attach_namespace(ns)
        _spans.tenancy_runs(self.active_count())
        log.info("leased run %s (ttl %.1fs, policy %s%s)", run, ttl,
                 pol.name,
                 f", recovered {recovered}" if recovered else "")
        return {"lease_id": lease.lease_id, "run": run,
                "run_id": run_id, "ttl_s": ttl,
                "recovered": recovered}

    def renew(self, lease_id: str, ttl_s=None) -> Dict[str, Any]:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise TenancyError(f"unknown lease {lease_id!r} "
                                   "(expired and reclaimed?)")
            lease.ttl_s = _clamp_ttl(ttl_s, default=lease.ttl_s)
            lease.expires_at = timesource.get().now() + lease.ttl_s
            lease.renewals += 1
            return {"lease_id": lease_id, "run": lease.ns.name,
                    "ttl_s": lease.ttl_s,
                    "renewals": lease.renewals}

    def release(self, lease_id: str,
                want_trace: bool = True) -> Dict[str, Any]:
        """Graceful end-of-run: flush the namespace (parked events
        dispatch), return the run summary + collected trace, remove the
        journal (the run completed — nothing left to recover)."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                raise TenancyError(f"unknown lease {lease_id!r} "
                                   "(expired and reclaimed?)")
            self._by_ns.pop(lease.ns.name, None)
        ns = lease.ns
        self._host.release_namespace(ns)
        _spans.tenancy_runs(self.active_count())
        doc = {"run": ns.name, "run_id": ns.run_id,
               "events": ns.events_ingested,
               "dispatched": len(ns.trace)}
        if want_trace and ns.collect_trace:
            doc["trace"] = ns.trace.to_jsonable()
        log.info("released run %s (%d event(s), %d action(s) traced)",
                 ns.name, ns.events_ingested, len(ns.trace))
        return doc

    def reclaim(self, lease_id: str) -> Dict[str, Any]:
        """Operator-requested reclaim (the placement plane's graceful
        drain): detach the namespace WITHOUT dispatching its parked
        events — they stay in the journal, exactly as a lease expiry
        would leave them — so a re-lease of the same run name (on this
        host or a replacement) recovers them exactly-once."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                raise TenancyError(f"unknown lease {lease_id!r} "
                                   "(expired and reclaimed?)")
            self._by_ns.pop(lease.ns.name, None)
        ns = lease.ns
        parked = ns.parked_depth()
        self._host.reclaim_namespace(ns)
        _spans.tenancy_reclaim(ns.name)
        _spans.tenancy_runs(self.active_count())
        log.info("reclaimed run %s on request (%d parked event(s) left "
                 "%s)", ns.name, parked,
                 f"journaled in {lease.journal_dir}" if lease.journal_dir
                 else "undispatched (no journal)")
        return {"run": ns.name, "run_id": ns.run_id,
                "events": ns.events_ingested, "parked": parked,
                "journal_dir": lease.journal_dir}

    def payload(self) -> List[Dict[str, Any]]:
        """Active leases, for the ``runs`` status op and /fleet."""
        now = timesource.get().now()
        with self._lock:
            return [{
                "run": lease.ns.name,
                "run_id": lease.ns.run_id,
                "lease_id": lease.lease_id,
                "ttl_s": lease.ttl_s,
                "expires_in_s": round(lease.expires_at - now, 3),
                "renewals": lease.renewals,
                "events": lease.ns.events_ingested,
                "parked": lease.ns.parked_depth(),
            } for lease in self._leases.values()]

    # -- host-side --------------------------------------------------------

    def namespace(self, run: str) -> Optional[RunNamespace]:
        with self._lock:
            lease = self._by_ns.get(run)
            return None if lease is None else lease.ns

    def active_count(self) -> int:
        with self._lock:
            return len(self._leases)

    def sweep(self, now: Optional[float] = None) -> int:
        """Expire overdue leases (reclaiming their namespaces); returns
        how many were reclaimed. The ``tenancy.lease.expire`` chaos
        seam force-expires one live lease per fire — the deterministic
        stand-in for a tenant that stopped renewing."""
        now = timesource.get().now() if now is None else now
        due: List[Lease] = []
        with self._lock:
            for lease in list(self._leases.values()):
                expired = lease.expires_at <= now
                if not expired \
                        and chaos.decide("tenancy.lease.expire") is not None:
                    expired = True
                if expired:
                    del self._leases[lease.lease_id]
                    self._by_ns.pop(lease.ns.name, None)
                    due.append(lease)
        for lease in due:
            ns = lease.ns
            parked = ns.parked_depth()
            self._host.reclaim_namespace(ns)
            _spans.tenancy_reclaim(ns.name)
            log.warning(
                "lease on run %s expired (tenant dead?); namespace "
                "reclaimed with %d parked event(s) left %s", ns.name,
                parked,
                f"journaled in {lease.journal_dir}" if lease.journal_dir
                else "undispatched (no journal)")
        if due:
            _spans.tenancy_runs(self.active_count())
        return len(due)
