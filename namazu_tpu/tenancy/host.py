"""TenantOrchestrator: ONE orchestrator process serving N experiments.

Extends the single-run :class:`~namazu_tpu.orchestrator.core.Orchestrator`
with the tenancy plane (doc/tenancy.md): a :class:`RunRegistry` of
leased run namespaces, per-namespace policy/journal/trace/flight-
recorder isolation, and a reaper that reclaims crashed tenants'
namespaces on lease expiry.

The default namespace stays EXACTLY the base orchestrator: untagged
events ride the inherited code paths (same policy, same journal, same
collected trace), so a TenantOrchestrator hosting zero leases is
behaviorally identical to an Orchestrator — the loss-free-compatibility
half of the tenancy contract. Namespaced events partition out of the
same drained batch and feed their namespace's own policy; their actions
carry the namespace back through dispatch, trace collection, release
journaling, and the endpoint action queues.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from namazu_tpu import obs, tenancy
from namazu_tpu.endpoint.hub import EndpointHub
from namazu_tpu.obs import recorder as _recorder
from namazu_tpu.orchestrator.core import (_FWD_DONE, FlushMarker,
                                           Orchestrator)
from namazu_tpu.policy.base import POLICY_DONE, ExplorePolicy
from namazu_tpu.tenancy.registry import RunNamespace, RunRegistry
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import get_logger

log = get_logger("tenancy.host")


class TenantOrchestrator(Orchestrator):
    def __init__(self, config: Config, policy: ExplorePolicy,
                 collect_trace: bool = False,
                 hub: Optional[EndpointHub] = None):
        super().__init__(config, policy, collect_trace=collect_trace,
                         hub=hub)
        self.registry = RunRegistry(self)
        # the wire endpoints answer lease/renew/release ops through
        # this attachment (endpoint/rest.py, endpoint/uds.py)
        self.hub.run_registry = self.registry
        #: live namespaces by name — the loops' resolution table
        #: (distinct from the registry's lease table: a namespace stays
        #: here through its release flush, after its lease is gone)
        self._namespaces: Dict[str, RunNamespace] = {}
        self._ns_lock = threading.Lock()
        self._reap_interval_s = float(
            config.get("tenancy_reap_interval_s", 0.25) or 0.25)
        self._reaper_stop = threading.Event()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        super().start()
        t = threading.Thread(target=self._reaper_loop,
                             name="orc-tenancy-reaper", daemon=True)
        t.start()
        self._threads["tenancy-reaper"] = t

    def shutdown(self):
        # flush every still-leased namespace FIRST, while the action
        # loop is alive to drain it (their tenants get no release doc —
        # a shutdown host is equivalent to every lease ending at once)
        if self._started and not self._shut_down:
            for row in self.registry.payload():
                try:
                    self.registry.release(row["lease_id"],
                                          want_trace=False)
                except Exception:
                    log.exception("releasing run %s at shutdown failed",
                                  row["run"])
        self._reaper_stop.set()
        trace = super().shutdown()
        t = self._threads.get("tenancy-reaper")
        if t is not None:
            t.join(timeout=5)
        return trace

    def abandon(self) -> None:
        self._reaper_stop.set()
        # a simulated SIGKILL takes every namespace's parked queue with
        # it, exactly like the default policy's (journals survive for
        # the re-lease recovery)
        with self._ns_lock:
            namespaces = list(self._namespaces.values())
        for ns in namespaces:
            ns.detached = True
            self._close_ns_policy(ns)
            if ns.journal is not None:
                ns.journal.close()
        super().abandon()

    def _reaper_loop(self) -> None:
        while not self._reaper_stop.wait(self._reap_interval_s):
            try:
                self.registry.sweep()
            except Exception:  # pragma: no cover - defensive
                log.exception("tenancy lease sweep failed")
            with self._ns_lock:
                namespaces = list(self._namespaces.values())
            for ns in namespaces:
                if not ns.detached:
                    obs.tenancy_parked(ns.name, ns.parked_depth())

    # -- namespace attach/detach (the registry calls these) --------------

    def attach_namespace(self, ns: RunNamespace) -> int:
        """Start a namespace's policy + forward loop and recover its
        journal; returns how many journaled events were recovered."""
        with self._ns_lock:
            self._namespaces[ns.name] = ns
            # the action loop exits after one _FWD_DONE per policy ever
            # forwarded; grows monotonically so early releases (their
            # _FWD_DONE arriving mid-run) can never trip the exit
            self._n_policies += 1
        ns.policy.start()
        t = threading.Thread(target=self._ns_forward_loop, args=(ns,),
                             name=f"orc-fwd-ns-{ns.name}", daemon=True)
        t.start()
        self._threads[f"fwd-ns-{ns.name}"] = t
        return self._recover_ns_journal(ns)

    def _recover_ns_journal(self, ns: RunNamespace) -> int:
        """Re-lease recovery (doc/tenancy.md): parked events a reclaimed
        predecessor journaled but never released replay into THIS
        namespace — dedupe rings seeded first so an inspector-side
        replay acks idempotent, exactly like single-run crash
        recovery."""
        if ns.journal is None:
            return 0
        recovered = ns.journal.unreleased()
        if not recovered:
            return 0
        for name in ("rest", "uds"):
            ep = self.hub.endpoint(name)
            if ep is not None and hasattr(ep, "note_event_uuid"):
                for event, _ in recovered:
                    ep.note_event_uuid(event.uuid)
        for event, endpoint_name in recovered:
            tenancy.set_ns(event, ns.name)
            self.hub.post_event(event, endpoint_name or "local")
        obs.journal_recovered(len(recovered))
        log.warning("run %s: recovered %d parked event(s) from its "
                    "journal; resuming the tenant's run", ns.name,
                    len(recovered))
        return len(recovered)

    def _ns_forward_loop(self, ns: RunNamespace) -> None:
        marker = FlushMarker()
        ns._flush_marker = marker
        put = self._merged_actions.put
        while True:
            item = ns.policy.action_out.get()
            if item is POLICY_DONE:
                # marker BEFORE the done sentinel: it fires once every
                # action above has been dispatched + release-journaled
                put(marker)
                put(_FWD_DONE)
                return
            # defensive namespace tag: policies mint actions through
            # Action.for_event (which inherits the event's tag), but a
            # plugin emitting raw actions must still route/trace under
            # its tenant
            if isinstance(item, list):
                for action in item:
                    tenancy.set_ns(action, ns.name)
            else:
                tenancy.set_ns(item, ns.name)
            put(item)

    def _close_ns_policy(self, ns: RunNamespace) -> None:
        """Close a namespace's delay queue WITHOUT releasing (the
        reclaim path): parked items die here — only the journal
        resurrects them — then the policy flushes empty so its
        POLICY_DONE keeps the action loop's accounting exact."""
        q = getattr(ns.policy, "_queue", None)
        if q is not None:
            try:
                q.close()
                q.drain_remaining()
            except Exception:  # pragma: no cover - best effort
                log.exception("closing run %s's delay queue failed",
                              ns.name)
        try:
            ns.policy.shutdown()
        except Exception:  # pragma: no cover - best effort
            log.exception("shutting down run %s's policy failed",
                          ns.name)

    def release_namespace(self, ns: RunNamespace) -> None:
        """Graceful detach: flush parked events through dispatch, wait
        for the drain, then drop the namespace's journal/routes/pin."""
        ns.detached = True
        ns.policy.shutdown()  # releases parked events, emits POLICY_DONE
        drained = True
        marker = getattr(ns, "_flush_marker", None)
        if marker is not None and self._started and not self._shut_down:
            drained = marker.done.wait(timeout=10)
            if not drained:
                log.warning("run %s: flush did not drain within 10s; "
                            "keeping its journal for recovery", ns.name)
        ns.flushed.set()
        if ns.journal is not None:
            if drained:
                # the run completed and every release was journaled:
                # same remove-on-clean-shutdown contract as the base
                # journal
                ns.journal.remove()
            else:
                # the action loop still owes this namespace dispatches:
                # removing the journal here would delete the only
                # durable copy of journaled-but-undispatched events —
                # keep it closed on disk, exactly like a reclaim
                ns.journal.close()
        self._detach_common(ns)

    def reclaim_namespace(self, ns: RunNamespace) -> None:
        """Crash reclamation (lease expiry): parked events are NOT
        dispatched — they stay in the journal for the re-lease —
        and sibling namespaces are untouched."""
        ns.detached = True
        self._close_ns_policy(ns)
        if ns.journal is not None:
            ns.journal.close()
        self._detach_common(ns)

    def _detach_common(self, ns: RunNamespace) -> None:
        # identity-guarded teardown: a reclaim/release racing a
        # concurrent RE-LEASE of the same run name (the advertised
        # crash-recovery flow) must tear down only ITS OWN namespace's
        # name-keyed state — popping/forgetting by name alone would
        # silently detach the successor and strand the new tenant
        with self._ns_lock:
            mine = self._namespaces.get(ns.name) is ns
            if mine:
                self._namespaces.pop(ns.name, None)
        if not mine:
            log.warning("run %s: a newer lease took the name during "
                        "detach; leaving its state untouched", ns.name)
            return
        # withdraw the tenant's published delay table (doc/tenancy.md
        # "Per-namespace tables"): an edge still polling this run's
        # table must see an explicit versioned withdrawal, not a stale
        # table that outlives the lease
        pub = getattr(ns.policy, "table_publisher", None)
        if pub is not None:
            try:
                pub.publish_none()
            except Exception:  # pragma: no cover - defensive
                log.exception("run %s: table withdrawal failed", ns.name)
        _recorder.recorder().end_pinned(ns.name)
        self.hub.forget_namespace(ns.name)
        # drop the tenant's per-entity action queues on every endpoint
        # too: a re-lease of the same run name must not poll the dead
        # incarnation's undelivered actions, and queues must not leak
        # one-per-entity-per-lease on a long-lived host
        for name in ("rest", "uds"):
            ep = self.hub.endpoint(name)
            if ep is not None and hasattr(ep, "forget_namespace"):
                ep.forget_namespace(ns.name)
        obs.tenancy_parked(ns.name, 0)

    # -- loop hooks (the base loops call these) ---------------------------

    def _dispatch_central_batch(self, batch: list) -> None:
        """Partition one drained batch by run namespace: the default
        sub-batch rides the inherited single-run path unchanged; each
        namespace's sub-batch journals + queues against its OWN
        journal/policy."""
        default_batch = []
        by_ns: Dict[str, list] = {}
        for ev in batch:
            name = tenancy.ns_of(ev)
            if not name:
                default_batch.append(ev)
            else:
                by_ns.setdefault(name, []).append(ev)
        if default_batch:
            super()._dispatch_central_batch(default_batch)
        routes_by_ns = None
        for name, sub in by_ns.items():
            with self._ns_lock:
                ns = self._namespaces.get(name)
            if ns is None or ns.detached:
                # late events of a released/reclaimed tenant: dropped,
                # counted — never leaked into the default namespace
                obs.action_unroutable(sub[0].entity_id)
                log.warning("dropping %d event(s) for unknown/detached "
                            "run %s", len(sub), name)
                continue
            ns.events_ingested += len(sub)
            obs.tenancy_events(name, len(sub))
            target = ns.policy if (self.enabled and ns.enabled) \
                else self.dumb
            if ns.journal is not None and routes_by_ns is None:
                # ONE route-table scan per drained batch, shared by
                # every journaled namespace's sub-batch (not one full
                # scan per namespace)
                routes_by_ns = self._partition_routes()
            self._journal_and_queue(
                sub, ns.journal, target,
                routes=(routes_by_ns or {}).get(name, {}))
            obs.tenancy_parked(name, ns.parked_depth())

    def _partition_routes(self):
        out = {}
        for key, endpoint_name in self.hub.routes().items():
            key_ns, entity = tenancy.split_route_key(key)
            out.setdefault(key_ns, {})[entity] = endpoint_name
        return out

    def _trace_append(self, action) -> None:
        name = tenancy.ns_of(action)
        if not name:
            return super()._trace_append(action)
        with self._ns_lock:
            ns = self._namespaces.get(name)
        if ns is not None and ns.collect_trace:
            ns.trace.append(action)

    def _journal_releases(self, released: list) -> None:
        super()._journal_releases(released)  # default namespace
        by_ns: Dict[str, list] = {}
        for uuid, name in released:
            if name:
                by_ns.setdefault(name, []).append(uuid)
        for name, uuids in by_ns.items():
            with self._ns_lock:
                ns = self._namespaces.get(name)
            if ns is None or ns.journal is None:
                continue
            try:
                ns.journal.append_releases(uuids)
            except Exception:
                log.exception("run %s: release journal append failed",
                              name)

    def _policies_for(self, ns: str):
        if not ns:
            return (self.policy, self.dumb)
        with self._ns_lock:
            run = self._namespaces.get(ns)
        return (run.policy, self.dumb) if run is not None \
            else (self.dumb,)

    def _control_namespace(self, name: str, op) -> None:
        """A namespace-scoped control op (the X-Nmz-Run header / framed
        ``run`` field on ``control``): flip THAT tenant's orchestration
        switch and suspend/resume ITS publisher — the process-default
        flag, policy, and publisher stay untouched, so one tenant's
        disable can never starve a sibling's table."""
        from namazu_tpu.signal.control import ControlOp

        with self._ns_lock:
            ns = self._namespaces.get(name)
        if ns is None or ns.detached:
            log.warning("control op %s for unknown/detached run %r "
                        "ignored", op.value, name)
            return
        ns.enabled = op is ControlOp.ENABLE_ORCHESTRATION
        pub = getattr(ns.policy, "table_publisher", None)
        if pub is not None:
            if ns.enabled:
                pub.resume()
            else:
                pub.suspend()
        log.info("run %s orchestration enabled=%s", name, ns.enabled)

