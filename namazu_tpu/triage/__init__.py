"""Triage plane: auto-minimized reproducers + failure-signature dossiers.

At fleet scale, *finding* a failure is no longer the bottleneck —
*explaining* it is. Namazu's premise makes explanation tractable: the
orchestrator owns every injected delay, so a failing run's delay table
IS its root-cause hypothesis, and shrinking that hypothesis is
delta debugging over schedules (the DEMi lineage, PAPERS.md). This
package does the shrink:

* :mod:`namazu_tpu.triage.minimize` — given a failing run, derive the
  candidate ordering flips from the causality plane's
  ``relation_flips`` divergence set, then delta-debug flip subsets
  toward a MINIMAL table. Most probes are **free**: a candidate table's
  realized order is simulated through the guidance plane
  (``bucket_sequence_from_encoded`` + ``CoverageMap.predicted_gain``)
  without executing anything; only the best-scored survivors are
  validated by real replay through the campaign runner. The result is
  a self-contained **dossier**: minimal table + flip set + probe
  journal + a ``tools why`` explanation + a causality DAG slice around
  the critical path.
* :mod:`namazu_tpu.triage.store` — the process-local dossier registry
  behind ``GET /triage``, the analytics TRIAGE section, and the
  ``nmz_triage_signatures`` gauge.

Dossiers travel on the knowledge wire (v3 ``triage_push`` /
``triage_pull``, doc/knowledge.md) keyed by failure signature
(``models/failure_pool.trace_digest``), so every tenant that hits a
known signature pulls the minimized repro instead of re-paying the
replays. Degradation contract matches the rest of the knowledge plane:
outages warn once and never raise into campaign code.

Surfaces: ``nmz-tpu tools minimize`` (cli/tools_cmd.py),
``GET /triage`` + ``GET /triage/<signature>`` (endpoint/rest.py), the
TRIAGE section of ``tools report`` / ``GET /analytics``, and the
``nmz_triage_*`` metrics federated through ``/fleet``
(doc/observability.md "Triage").
"""

from __future__ import annotations

from namazu_tpu.triage.minimize import (  # noqa: F401
    SCHEMA_DOSSIER,
    MinimizeBudget,
    MinimizeError,
    failure_signature,
    minimize_run,
    render_dossier_md,
)
from namazu_tpu.triage.store import (  # noqa: F401
    dossier_for,
    record_dossier,
    reset_store,
    summaries,
)
