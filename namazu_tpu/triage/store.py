"""Process-local dossier registry: the memory behind ``GET /triage``.

One dict, keyed by failure signature. ``tools minimize`` records every
dossier it produces here (when run in-process) and the REST plane
serves it back out; the knowledge pool is the *durable*, cross-tenant
copy — this store is just the live orchestrator's working set, the
same split the failure pool makes between its in-memory ring and the
knowledge wire.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from namazu_tpu import obs

_lock = threading.Lock()
_dossiers: Dict[str, Dict[str, Any]] = {}


def record_dossier(dossier: Dict[str, Any]) -> None:
    """Index one dossier by its failure signature (last write wins —
    the minimizer only re-records when it found a smaller repro)."""
    sig = str(dossier.get("signature") or "")
    if not sig:
        return
    with _lock:
        _dossiers[sig] = dict(dossier)
        n = len(_dossiers)
    obs.triage_signatures(n)


def dossier_for(signature: str) -> Optional[Dict[str, Any]]:
    with _lock:
        doc = _dossiers.get(str(signature))
        return dict(doc) if doc is not None else None


def summaries() -> List[Dict[str, Any]]:
    """One compact row per signature (the ``GET /triage`` listing and
    the analytics TRIAGE table) — full dossiers stay behind
    ``GET /triage/<signature>``."""
    with _lock:
        docs = [dict(d) for d in _dossiers.values()]
    rows = []
    for d in sorted(docs, key=lambda d: str(d.get("signature") or "")):
        rows.append({
            "signature": d.get("signature"),
            "run_index": d.get("run_index"),
            "minimal_flips": d.get("minimal_flips"),
            "candidate_flips": d.get("candidate_flips"),
            "probes_simulated": d.get("probes_simulated"),
            "probes_replayed": d.get("probes_replayed"),
            "minimization_ratio": d.get("minimization_ratio"),
            "validated": bool(d.get("validated")),
        })
    return rows


def reset_store() -> None:
    """Test hook: forget every dossier."""
    with _lock:
        _dossiers.clear()
    obs.triage_signatures(0)
