"""Delta-debug a failing run's delay table toward a MINIMAL reproducer.

The failing run installed a whole delay table (up to H buckets of
injected delay), but the bug almost never needs all of it — usually one
or two ordering flips carry the failure. This module finds them:

1. **Candidates** come from the causality plane: ``relation_flips``
   between the failing run's realized dispatch order and a passing
   baseline names the ordering relations that actually differ, already
   transitively reduced and suspicion-ranked (obs/causality.py). Each
   flip maps — through the occurrence-key identity — back to the hint
   buckets of its two participants, and the failure's own
   ``failure_seed`` table says what delay the recording policy injected
   on each bucket. A candidate reproducer is a SUBSET of flips, i.e. the
   seed table restricted to those flips' buckets.
2. **Probing is mostly free.** A candidate table's realized order is
   simulated, not executed: candidate release times are
   ``arrival + table[bucket]`` and a stable argsort yields the order the
   delay-mode policy would realize (guidance/signature.py
   ``bucket_sequence_from_encoded`` — the exact release rule the search
   plane scores with). A candidate is *feasible* when it re-realizes
   every required flip, and it is *scored* by how far its predicted
   relation coverage diverges from the passing baseline
   (``CoverageMap.predicted_gain``). The whole subset lattice is probed
   this way without running the system once.
3. **Only survivors replay.** The best few feasible candidates
   (smallest first) are validated by a REAL run: a throwaway storage is
   initialized from the experiment's own materials, pre-seeded with the
   failing trace, given the candidate table as an installed search
   checkpoint, and executed through the ordinary campaign runner. A
   replay that fails validation reproduces the bug — that candidate is
   the minimal reproducer, and the dossier says ``validated: true``.
   Each candidate escalates through up to two tables before the next
   candidate gets a slot: the flip subset alone, then the subset plus
   its *causal prefix* (every seeded bucket whose traffic starts no
   later than the flip's target event). The failing run's recorded
   arrivals already embed upstream delay shifts — zeroing the upstream
   buckets replays a run the flip never happens in — so the prefix
   restores the context while the SUBSET remains the explanation. The
   last replay slot is reserved for the full failure seed, the
   always-reproduces fallback that keeps the dossier actionable even
   when no small subset survives.

The result is a self-contained **dossier** (``SCHEMA_DOSSIER``):
minimal table + flip set + probe journal + the ``tools why`` divergence
explanation + a causality-DAG slice around the critical path, keyed by
the run's failure signature (``models/failure_pool.trace_digest`` over
the realized encoding — the same key the knowledge pool dedupes on, so
dossiers attach to pool entries with no new identity scheme).
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from namazu_tpu import obs
from namazu_tpu.guidance.coverage import CoverageMap
from namazu_tpu.guidance.signature import (
    bucket_sequence_from_docs,
    bucket_sequence_from_encoded,
)
from namazu_tpu.models.failure_pool import trace_digest
from namazu_tpu.models.ingest import failure_seed
from namazu_tpu.obs import causality
from namazu_tpu.ops import trace_encoding as te
from namazu_tpu.signal.base import HINT_SPACE
from namazu_tpu.storage import load_storage
from namazu_tpu.utils.config import Config, parse_duration
from namazu_tpu.utils.log import get_logger

log = get_logger("triage")

SCHEMA_DOSSIER = "nmz-triage-v1"

#: journal entries kept in the dossier; past this the tail is counted,
#: never silently dropped (the no-silent-caps stance)
JOURNAL_CAP = 200


class MinimizeError(Exception):
    """Minimization cannot even start (no failing run, no injected
    delays to shrink, ...) — distinct from a run that minimizes to an
    unvalidated candidate, which is a *result* (``validated: false``),
    not an error."""


class MinimizeBudget:
    """How much the minimizer may spend. Simulation is cheap (numpy on
    the encoded trace), replay is a full campaign run — the defaults
    keep the simulated:replayed ratio far past the 80% the triage
    plane promises (``nmz_triage_probes_total`` proves it per run)."""

    def __init__(self, max_probes: int = 4096, max_replays: int = 4,
                 replay_deadline_s: float = 120.0,
                 pair_pool: int = 8) -> None:
        self.max_probes = max(1, int(max_probes))
        self.max_replays = max(0, int(max_replays))
        self.replay_deadline_s = float(replay_deadline_s)
        #: top-scored singles that combine into pairs/triples — the
        #: lattice is probed smallest-first, so the pool only bounds
        #: the combinatorial middle, never the singles or the full set
        self.pair_pool = max(2, int(pair_pool))


# -- trace -> record docs (the causality plane's input shape) --------------

def _docs_from_trace(trace, zero_delay: bool = False) -> List[dict]:
    """A stored trace's actions as flight-recorder-shaped record docs,
    so the causality plane's functions (relation_flips, critical_path)
    apply to storages directly. ``zero_delay=True`` stamps each event's
    dispatch at its ARRIVAL — the synthetic "what the run would have
    looked like with no injected delay" baseline used when the storage
    holds no passing run to diff against."""
    docs = []
    for a in trace:
        arr = getattr(a, "event_arrived", None) or 0.0
        rel = a.triggered_time or 0.0
        dispatched = (arr or rel) if zero_delay else rel
        if not dispatched:
            continue  # never-dispatched: invisible to ordering
        docs.append({
            "entity": a.entity_id,
            "event_class": a.event_class or a.class_name(),
            "hint": getattr(a, "event_hint", "") or "",
            "t": {"intercepted": arr or dispatched,
                  "dispatched": dispatched},
        })
    return docs


def _key_map(docs: Sequence[dict]) -> Tuple[List[str], Dict[str, dict]]:
    """``(dispatch-ordered occurrence keys, key -> doc)`` for one run,
    replicating the causality plane's identity derivation EXACTLY
    (export.order_lines_from_docs + _occurrence_keys: timestamp-only
    stable sort, entity + class:hint line, occurrence counter) — a
    divergence here would map a flip back to the wrong event."""
    rows = []
    for i, doc in enumerate(docs):
        t = doc.get("t") or {}
        if doc.get("kind") or "dispatched" not in t:
            continue
        name = doc.get("event_class") or "event"
        if doc.get("hint"):
            name = f"{name}:{doc['hint']}"
        rows.append((t["dispatched"], f"{doc.get('entity', '')} {name}", i))
    rows.sort(key=lambda r: r[0])
    seen: Dict[str, int] = {}
    order: List[str] = []
    by_key: Dict[str, dict] = {}
    for _, line, i in rows:
        n = seen.get(line, 0)
        seen[line] = n + 1
        key = f"{line}#{n}"
        order.append(key)
        by_key[key] = docs[i]
    return order, by_key


def _bucket_of(doc: dict, H: int) -> int:
    """A doc's delay-table bucket — the failure_seed convention:
    recorded hint, else ``class:entity``."""
    hint = doc.get("hint") or \
        f"{doc.get('event_class') or 'event'}:{doc.get('entity', '')}"
    return te.hint_bucket(hint, H)


def _dag_slice(order: Sequence[str], participants: Sequence[str],
               radius: int = 3) -> List[str]:
    """The dispatch-order window around the flip participants — the
    DAG neighborhood a human reads first."""
    idx = {k: i for i, k in enumerate(order)}
    keep = set()
    for key in participants:
        i = idx.get(key)
        if i is None:
            continue
        keep.update(range(max(0, i - radius),
                          min(len(order), i + radius + 1)))
    return [order[i] for i in sorted(keep)]


# -- the replay harness ----------------------------------------------------

def _replay_once(storage_dir: str, base_cfg: Config, H: int,
                 max_interval_s: float, trace_f, table: np.ndarray,
                 deadline_s: float) -> Dict[str, Any]:
    """Execute ONE candidate table for real: throwaway storage from the
    experiment's own materials, the failing trace pre-seeded as stored
    history, the candidate table installed as a ready search checkpoint,
    then one ordinary ``run``. Returns ``{"reproduced": bool, ...}``.

    The pre-seeded trace matters twice: the tpu_search policy only
    treats a round as install-only when history exists (n=0 would start
    an evolution), and the huge ``search_every`` plus the seeded n=1
    guarantees the round installs ``triage_repro.npz`` verbatim and
    skips evolution — the run executes EXACTLY the candidate delays.
    """
    replay_dir = tempfile.mkdtemp(prefix="nmz-triage-")
    try:
        cfg = dict(base_cfg.to_jsonable())
        # the replay is hermetic: no knowledge wire, no telemetry push,
        # no endpoint ports to collide with a live orchestrator's
        for key in ("knowledge", "telemetry_url", "event_journal",
                    "event_journal_dir", "run_id"):
            cfg.pop(key, None)
        # the testee's inspectors still need a REST endpoint — on a
        # FRESH port (exported as NMZ_REST_PORT for the run scripts, the
        # examples' convention), so a live orchestrator on the
        # experiment's configured port never collides with the replay
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            rest_port = s.getsockname()[1]
        cfg["rest_port"] = rest_port
        cfg["agent_port"] = -1
        cfg["explore_policy"] = "tpu_search"
        param = dict(cfg.get("explore_policy_param")
                     or cfg.get("explorePolicyParam") or {})
        cfg.pop("explorePolicyParam", None)
        param.pop("knowledge", None)
        param.update({
            "checkpoint": "triage_repro.npz",
            "hint_buckets": int(H),
            # numbers mean milliseconds in duration params; write the
            # unit out so the seconds value survives verbatim
            "max_interval": f"{max_interval_s}s",
            "search_every": 1_000_000,
            "generations": 1,
            "population": 8,
            "platform": "cpu",
        })
        cfg["explore_policy_param"] = param
        # NOT config.toml/json: init copies the config by basename, and
        # run must find only the init-written config.json snapshot
        cfg_path = os.path.join(replay_dir, "replay_config.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f, indent=2, sort_keys=True)
        replay_storage = os.path.join(replay_dir, "storage")
        from namazu_tpu.cli import cli_main  # lazy: cli imports us back

        rc = cli_main(["init", cfg_path,
                       os.path.join(storage_dir, "materials"),
                       replay_storage])
        if rc != 0:
            return {"reproduced": False, "error": f"init rc {rc}"}
        st = load_storage(replay_storage)
        try:
            st.create_new_working_dir()
            st.record_new_trace(trace_f)
            st.record_result(False, 0.0,
                             metadata={"hint_space": HINT_SPACE})
        finally:
            st.close()
        np.savez(os.path.join(replay_storage, "triage_repro.npz"),
                 best_delays=np.asarray(table, np.float32),
                 generations_run=np.asarray(1),
                 best_fitness=np.asarray(0.0),
                 hint_space=np.asarray(HINT_SPACE))
        from namazu_tpu.utils.cmd import CmdFactory, kill_process_group

        env = CmdFactory().env()
        env["NMZ_REST_PORT"] = str(rest_port)
        with open(os.path.join(replay_dir, "replay.log"), "ab") as lf:
            child = subprocess.Popen(
                [sys.executable, "-m", "namazu_tpu.cli", "run",
                 replay_storage],
                stdout=lf, stderr=subprocess.STDOUT,
                env=env, start_new_session=True)
            try:
                child.wait(timeout=deadline_s)
            except subprocess.TimeoutExpired:
                kill_process_group(child)
                return {"reproduced": False, "timeout": True}
        try:
            st = load_storage(replay_storage)
            try:
                n = st.nr_stored_histories()
                # index 0 is the pre-seeded history; the replay's own
                # run is the last one — reproduced iff it FAILED
                reproduced = n >= 2 and st.is_successful(n - 1) is False
            finally:
                st.close()
        except Exception:
            log.exception("replay storage unreadable after run")
            return {"reproduced": False,
                    "error": "replay storage unreadable",
                    "rc": child.returncode}
        return {"reproduced": bool(reproduced), "rc": child.returncode}
    finally:
        shutil.rmtree(replay_dir, ignore_errors=True)


def _default_replay(storage_dir: str, cfg: Config, H: int,
                    max_interval_s: float, trace_f,
                    deadline_s: float) -> Callable[[np.ndarray], bool]:
    def replay(table: np.ndarray) -> bool:
        res = _replay_once(storage_dir, cfg, H, max_interval_s,
                           trace_f, table, deadline_s)
        if res.get("error") or res.get("timeout"):
            log.warning("replay probe degraded: %s",
                        res.get("error") or "deadline expired")
        return bool(res.get("reproduced"))
    return replay


# -- the minimizer ---------------------------------------------------------

def failure_signature(storage_dir: str,
                      run_index: Optional[int] = None) -> str:
    """The failure signature a minimization of this run would carry —
    computed WITHOUT minimizing, so callers can ask the knowledge pool
    for an existing dossier (``triage_pull``) before paying for
    anything. Same key the failure pool dedupes on: ``trace_digest``
    over the realized encoding."""
    storage = load_storage(os.path.abspath(storage_dir))
    try:
        i_fail, _ = _pick_runs(storage, run_index, None)
        trace_f = storage.get_stored_history(i_fail)
    finally:
        storage.close()
    cfg = _storage_config(os.path.abspath(storage_dir))
    H = int(cfg.policy_param("hint_buckets", te.DEFAULT_H))
    return trace_digest(te.encode_trace(trace_f, H=H, realized=True))

def _pick_runs(storage, run_index: Optional[int],
               baseline_index: Optional[int]
               ) -> Tuple[int, Optional[int]]:
    n = storage.nr_stored_histories()
    if n == 0:
        raise MinimizeError("storage holds no runs")
    fail = run_index
    if fail is None:
        for i in range(n - 1, -1, -1):
            if storage.is_quarantined(i):
                continue
            if storage.is_successful(i) is False:
                fail = i
                break
        if fail is None:
            raise MinimizeError("storage holds no failing run to "
                                "minimize")
    else:
        if not (0 <= fail < n):
            raise MinimizeError(f"run {fail} out of range (storage "
                                f"holds {n})")
        if storage.is_successful(fail):
            raise MinimizeError(f"run {fail} succeeded — nothing to "
                                "minimize")
    base = baseline_index
    if base is None:
        for i in range(n - 1, -1, -1):
            if i == fail or storage.is_quarantined(i):
                continue
            if storage.is_successful(i):
                base = i
                break
    elif not (0 <= base < n):
        raise MinimizeError(f"baseline {base} out of range")
    return fail, base


def _storage_config(storage_dir: str) -> Config:
    for name in ("config.toml", "config.json"):
        path = os.path.join(storage_dir, name)
        if os.path.exists(path):
            return Config.from_file(path)
    return Config({})


def _enumerate_subsets(actionable: List[dict],
                       budget: MinimizeBudget):
    """Candidate flip subsets, smallest-first: every single, then pairs
    and triples over the top-scored pool, then the full set — ddmin's
    subset lattice walked bottom-up, because the whole point is that
    probes are (nearly) free and small reproducers are the prize."""
    idx = list(range(len(actionable)))
    yield from ([i] for i in idx)
    pool = idx[:budget.pair_pool]
    for size in (2, 3):
        if len(pool) >= size:
            yield from (list(c)
                        for c in itertools.combinations(pool, size))
    if len(idx) > 3:
        yield idx


def minimize_run(storage_dir: str,
                 run_index: Optional[int] = None,
                 baseline_index: Optional[int] = None,
                 top: int = 12,
                 budget: Optional[MinimizeBudget] = None,
                 replay: Optional[Callable[[np.ndarray], bool]] = None
                 ) -> Dict[str, Any]:
    """Minimize one failing stored run to a dossier (module header).

    ``replay`` overrides the real-execution harness — ``None`` uses the
    fork-a-campaign-run default; tests (and the ``--no-replay`` CLI
    path, via ``lambda table: False``-style stubs) inject their own.
    Raises :class:`MinimizeError` when minimization cannot start;
    returns an UNVALIDATED dossier (``validated: false``) when it can
    start but no candidate replays to a failure within budget.
    """
    budget = budget or MinimizeBudget()
    storage_dir = os.path.abspath(storage_dir)
    storage = load_storage(storage_dir)
    try:
        i_fail, i_base = _pick_runs(storage, run_index, baseline_index)
        trace_f = storage.get_stored_history(i_fail)
        trace_p = (storage.get_stored_history(i_base)
                   if i_base is not None else None)
    finally:
        storage.close()

    cfg = _storage_config(storage_dir)
    H = int(cfg.policy_param("hint_buckets", te.DEFAULT_H))
    max_interval_s = parse_duration(cfg.policy_param("max_interval", 100))
    seed = failure_seed(trace_f, H, max_interval_s)
    if seed is None:
        raise MinimizeError(
            f"run {i_fail} carries no injected delays (no "
            "arrival/release stamps) — there is no table to minimize")

    fail_docs = _docs_from_trace(trace_f)
    pass_docs = (_docs_from_trace(trace_p) if trace_p is not None
                 else _docs_from_trace(trace_f, zero_delay=True))
    run_a = f"run-{i_fail:08d}"
    run_b = (f"run-{i_base:08d}" if i_base is not None
             else "baseline-zero-delay")
    why = causality.why_payload(fail_docs, pass_docs, run_a, run_b,
                                top=top)
    diff = why["diff"]
    order_f, by_key = _key_map(fail_docs)

    # flips -> delay-table buckets: a flip is ACTIONABLE when both
    # participants map back to failing-run events in DIFFERENT buckets
    # (a delay table indexes buckets — it cannot reorder within one)
    actionable: List[dict] = []
    for f in diff.get("flips") or []:
        da, db = by_key.get(f["first"]), by_key.get(f["then"])
        if da is None or db is None:
            continue
        bf, bt = _bucket_of(da, H), _bucket_of(db, H)
        if bf == bt:
            continue
        actionable.append({
            "first": f["first"], "then": f["then"],
            "score": f["score"],
            "bucket_first": bf, "bucket_then": bt,
            "buckets": sorted({bf, bt}),
        })
    if not actionable:
        raise MinimizeError(
            "no actionable ordering flips between the failing run and "
            f"{run_b} — the divergence is not bucket-separable "
            f"({diff.get('inverted_pairs', 0)} inverted pair(s))")

    # the free-probe apparatus: the failing run's arrival-anchored
    # encoding (candidate release = arrival + delay), and a coverage
    # frontier trained on the PASSING order so predicted_gain measures
    # "how far from passing does this candidate steer"
    enc = te.encode_trace(trace_f, H=H)
    cov = CoverageMap(H)
    cov.observe(bucket_sequence_from_docs(pass_docs, H))

    def _probe(subset: List[int]) -> Tuple[np.ndarray, bool, float]:
        C = np.zeros((H,), np.float32)
        for i in subset:
            for b in actionable[i]["buckets"]:
                C[b] = seed[b]
        seq = bucket_sequence_from_encoded(
            enc, enc.arrival + C[enc.hint_ids])
        first: Dict[int, int] = {}
        for pos, b in enumerate(seq):
            first.setdefault(int(b), pos)
        feasible = all(
            first.get(actionable[i]["bucket_first"], -1) >= 0
            and first.get(actionable[i]["bucket_then"], -1) >= 0
            and first[actionable[i]["bucket_first"]]
            < first[actionable[i]["bucket_then"]]
            for i in subset)
        return C, feasible, cov.predicted_gain(seq)

    journal: List[dict] = []
    probes_simulated = 0
    scored: List[Tuple[int, float, int, List[int], np.ndarray]] = []
    for subset in _enumerate_subsets(actionable, budget):
        if probes_simulated >= budget.max_probes:
            log.warning("probe budget (%d) exhausted with subsets "
                        "left unprobed", budget.max_probes)
            break
        C, feasible, gain = _probe(subset)
        probes_simulated += 1
        journal.append({
            "mode": "simulated",
            "flips": [[actionable[i]["first"], actionable[i]["then"]]
                      for i in subset],
            "feasible": feasible, "gain": round(gain, 4),
        })
        if feasible:
            scored.append((len(subset), -gain, len(scored), subset, C))
    obs.triage_probe("simulated", probes_simulated)

    # survivors replay smallest-first, best-gain within a size; when
    # simulation screened everything out, the ranking is still the
    # replay order — simulation is a heuristic, replay is the judge
    if not scored:
        log.warning("no candidate passed the feasibility screen; "
                    "replaying the top-scored subsets anyway")
        for k, subset in enumerate(
                _enumerate_subsets(actionable, budget)):
            C, _, gain = _probe(subset)
            scored.append((len(subset), -gain, k, subset, C))
            if len(scored) >= max(1, budget.max_replays):
                break
    scored.sort()

    if replay is None:
        replay = _default_replay(storage_dir, cfg, H, max_interval_s,
                                 trace_f, budget.replay_deadline_s)

    # causal-prefix closure: first arrival per bucket, failure_seed's
    # hint convention (models/ingest.py) so the indices line up
    seed_arr = np.asarray(seed, np.float32)
    first_seen: Dict[int, float] = {}
    for a in trace_f:
        arr = getattr(a, "event_arrived", None)
        if not arr:
            continue
        hint = getattr(a, "event_hint", "") or \
            f"{a.event_class or a.class_name()}:{a.entity_id}"
        b = te.hint_bucket(hint, H)
        if b not in first_seen or arr < first_seen[b]:
            first_seen[b] = float(arr)

    def _with_prefix(C: np.ndarray, subset: List[int]) -> np.ndarray:
        horizon = max((by_key[actionable[i]["then"]]["t"]["intercepted"]
                       for i in subset), default=0.0)
        C2 = C.copy()
        for b, t0 in first_seen.items():
            if seed_arr[b] > 0 and t0 <= horizon:
                C2[b] = seed_arr[b]
        return C2

    # replay plan: per candidate, bare subset then subset+prefix; the
    # last slot is reserved for the full seed (module header, step 3)
    plans: List[Tuple[List[int], np.ndarray, float, str]] = []
    for _, neg_gain, _, subset, C in scored:
        plans.append((subset, C, -neg_gain, "subset"))
        C2 = _with_prefix(C, subset)
        if not np.array_equal(C2, C):
            plans.append((subset, C2, -neg_gain, "subset+prefix"))
    if budget.max_replays > 1:
        plans = plans[:budget.max_replays - 1]
    if budget.max_replays > 0 and np.any(seed_arr > 0):
        plans.append((list(range(len(actionable))),
                      seed_arr.copy(), 0.0, "full_seed"))

    probes_replayed = 0
    minimal: Optional[List[int]] = None
    minimal_table: Optional[np.ndarray] = None
    validated = False
    variant = "subset"
    for subset, C, gain, kind in plans:
        if probes_replayed >= budget.max_replays:
            break
        reproduced = bool(replay(C))
        probes_replayed += 1
        journal.append({
            "mode": "replayed", "table": kind,
            "flips": [[actionable[i]["first"], actionable[i]["then"]]
                      for i in subset],
            "gain": round(gain, 4), "reproduced": reproduced,
        })
        if reproduced:
            minimal, minimal_table, validated = subset, C, True
            variant = kind
            break
    obs.triage_probe("replayed", probes_replayed)
    if minimal is None:
        # best unvalidated candidate: the smallest feasible subset
        # (or the full actionable set when nothing was even feasible)
        minimal = scored[0][3] if scored else list(range(len(actionable)))
        minimal_table, _, _ = _probe(minimal)

    minimal_flips = [dict(actionable[i]) for i in minimal]
    ratio = 1.0 - len(minimal) / float(max(1, len(actionable)))
    obs.triage_minimized(ratio)

    participants = [k for f in minimal_flips
                    for k in (f["first"], f["then"])]
    sig = trace_digest(te.encode_trace(trace_f, H=H, realized=True))
    # the dossier ships the table that actually VALIDATED (it may carry
    # causal-prefix buckets beyond the minimal flips — the flips are
    # the explanation, the table is the reproducer)
    delays = {str(int(b)): float(minimal_table[b])
              for b in np.flatnonzero(minimal_table > 0)}
    dropped = max(0, len(journal) - JOURNAL_CAP)
    dossier = {
        "schema": SCHEMA_DOSSIER,
        "signature": sig,
        "storage": storage_dir,
        "run_index": i_fail,
        "baseline_index": i_base,
        "table": {"H": H, "max_interval_s": max_interval_s,
                  "delays": delays, "variant": variant},
        "flips": minimal_flips,
        "minimal_flips": len(minimal_flips),
        "candidate_flips": len(actionable),
        "probes_simulated": probes_simulated,
        "probes_replayed": probes_replayed,
        "minimization_ratio": round(ratio, 4),
        "validated": validated,
        "why": why,
        "dag_slice": {
            "around_flips": _dag_slice(order_f, participants),
            "critical_path": why["runs"]["a"]["critical_path"],
        },
        "journal": journal[:JOURNAL_CAP],
        "journal_dropped": dropped,
    }
    from namazu_tpu.triage import store as _store

    _store.record_dossier(dossier)
    log.info("minimized run %d: %d/%d flip(s), %d simulated / %d "
             "replayed probe(s), validated=%s", i_fail,
             len(minimal_flips), len(actionable), probes_simulated,
             probes_replayed, validated)
    return dossier


# -- rendering -------------------------------------------------------------

def render_dossier_md(dossier: Dict[str, Any]) -> str:
    """Markdown face of a dossier (``tools minimize --format md``)."""
    table = dossier.get("table") or {}
    lines = [
        f"# Triage dossier `{dossier.get('signature', '?')}`",
        "",
        f"- storage: `{dossier.get('storage', '?')}` "
        f"run {dossier.get('run_index')} "
        f"(baseline: {dossier.get('baseline_index', 'zero-delay')})",
        f"- minimal reproducer: {dossier.get('minimal_flips', 0)} "
        f"flip(s) of {dossier.get('candidate_flips', 0)} candidate(s) "
        f"(minimization ratio "
        f"{dossier.get('minimization_ratio', 0.0)})",
        f"- probe budget: {dossier.get('probes_simulated', 0)} "
        f"simulated / {dossier.get('probes_replayed', 0)} replayed",
        f"- validation: "
        f"{'replay-validated' if dossier.get('validated') else 'NOT validated (no replay reproduced the failure within budget)'}",
    ]
    flips = dossier.get("flips") or []
    if flips:
        lines += ["", "## Minimal ordering flips", "",
                  "| score | first | then | buckets |",
                  "|---|---|---|---|"]
        for f in flips:
            lines.append(f"| {f.get('score')} | `{f.get('first')}` "
                         f"| `{f.get('then')}` | {f.get('buckets')} |")
    delays = table.get("delays") or {}
    if delays:
        lines += ["", "## Minimal delay table "
                  f"(H={table.get('H')}, clip "
                  f"{table.get('max_interval_s')}s)", "",
                  "| bucket | delay (s) |", "|---|---|"]
        for b in sorted(delays, key=int):
            lines.append(f"| {b} | {delays[b]:.6f} |")
    dag = (dossier.get("dag_slice") or {}).get("around_flips") or []
    if dag:
        lines += ["", "## Dispatch order around the flips", ""]
        lines += [f"- `{k}`" for k in dag]
    why = dossier.get("why")
    if why:
        lines += ["", "---", "",
                  causality.render_why_md(why, perfetto=False)]
    lines.append("")
    return "\n".join(lines)
