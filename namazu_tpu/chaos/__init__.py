"""Self-chaos plane: deterministic fault injection for our own stack.

Namazu's reason to exist is amplifying rare failure interleavings in
*other* systems; this package turns the same discipline on the serving
plane we ship. Explicit seams in the transport
(inspector/rest_transceiver.py), the REST endpoint (endpoint/rest.py),
the storage layer (utils/atomic.py), the knowledge client
(knowledge/client.py) and the orchestrator (orchestrator/core.py)
consult a process-global :class:`~namazu_tpu.chaos.plan.FaultPlan`
through :func:`decide`. With no plan installed — the production
default — every seam is one module-global read and a ``None`` check,
the same cost contract as ``obs_enabled`` (pinned by the bench gate in
the acceptance criteria).

Install a plan explicitly (:func:`install`) or through the environment
(:func:`install_from_env`): ``NMZ_CHAOS`` holds a JSON document
``{"seed": S, "faults": {point: rule, ...}}``, which is how the chaos
harness and the campaign kill-tests reach seams inside child
processes (``nmz-tpu run`` / ``inspectors`` install from env at
startup).

The fault-point catalog, rule grammar, and the invariant definitions
live in doc/robustness.md ("Chaos plane"). Scenario presets are in
:mod:`namazu_tpu.chaos.scenarios`; the invariant harness in
:mod:`namazu_tpu.chaos.harness`; the crash-recovery event journal in
:mod:`namazu_tpu.chaos.journal`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from namazu_tpu.chaos.plan import FaultPlan

__all__ = [
    "FaultPlan", "ENV_VAR", "decide", "enabled", "plan",
    "install", "clear", "install_from_env", "env_value",
    "stage_slowdown",
]

#: the cross-process channel: a JSON {"seed": S, "faults": {...}}
ENV_VAR = "NMZ_CHAOS"

_plan: Optional[FaultPlan] = None


def enabled() -> bool:
    return _plan is not None


def plan() -> Optional[FaultPlan]:
    return _plan


def decide(point: str) -> Optional[Dict[str, Any]]:
    """The one call every seam makes. Disabled (no plan installed) =
    one global read + None check — nothing else on the hot path."""
    p = _plan
    if p is None:
        return None
    return p.decide(point)


def stage_slowdown(point: str = "orchestrator.stage.slow") -> None:
    """Profiling-plane seeded slowdown (doc/observability.md
    "Profiling"): a fault at ``point`` parks the calling stage inside
    the distinctively-named frame below, which the sampling profiler
    must localize as the #1 profdiff entry against a clean run — the
    CI seeded-slowdown smoke. Disabled = the one global read of
    :func:`decide`."""
    fault = decide(point)
    if fault is not None:
        _chaos_injected_stage_slowdown(
            float(fault.get("delay_s", 0.002)))


def _chaos_injected_stage_slowdown(delay_s: float) -> None:
    # a deliberate sleep under a name no real code path shares, so the
    # profiler's collapsed stacks pin the injected time to THIS frame
    import time

    time.sleep(max(0.0, delay_s))


def install(new_plan: FaultPlan) -> FaultPlan:
    """Install ``new_plan`` process-globally; returns it."""
    global _plan
    _plan = new_plan
    return new_plan


def clear() -> None:
    global _plan
    _plan = None


def install_from_env(environ: Optional[Dict[str, str]] = None
                     ) -> Optional[FaultPlan]:
    """Install a plan from ``NMZ_CHAOS`` if set (and none is installed
    yet — an explicitly installed plan wins); returns the active plan.
    A malformed value raises: a chaos run with a silently-ignored spec
    would report a meaningless green."""
    env = os.environ if environ is None else environ
    raw = env.get(ENV_VAR, "")
    if not raw or _plan is not None:
        return _plan
    try:
        doc = json.loads(raw)
        seed = int(doc["seed"])
        faults = doc.get("faults") or {}
    except (ValueError, TypeError, KeyError) as e:
        raise ValueError(f"bad {ENV_VAR} value: {e}") from e
    return install(FaultPlan(seed, faults))


def env_value(seed: int, faults: Dict[str, Dict[str, Any]]) -> str:
    """The ``NMZ_CHAOS`` string for a (seed, faults) pair — what the
    harness/tests put in a child's environment."""
    return json.dumps({"seed": int(seed), "faults": faults},
                      sort_keys=True)
