"""FaultPlan: a seeded, deterministic schedule of named fault points.

The chaos plane's contract mirrors what the tool itself promises its
users (PAPER.md): faults must be *reproducible*. A fault point is a
named seam in the serving plane (``wire.post.drop``,
``storage.rename``, ``knowledge.eof`` — the catalog lives in
doc/robustness.md); every time the code reaches a seam it *consults*
the plan, and the plan answers fire/don't-fire as a **pure function of
(seed, point, consult index)**:

    u = sha256(f"{seed}:{point}:{index}")[:8] / 2**64
    fires  iff  index in rule["at"]
           or  (u < rule["prob"] and index >= rule["after"])

No wall clock, no shared RNG stream, no cross-point coupling — so the
schedule for any point is bit-for-bit identical across runs, platforms
and thread interleavings given the same seed. (What *varies* under
thread races is only which real-world operation lands on consult index
n; the decision sequence itself never does.) ``schedule()`` exposes the
pure function for tests and the invariant harness.

A rule is a plain dict::

    {"prob": 0.25}                   # fire ~25% of consults
    {"at": [0, 3]}                   # fire exactly on consults 0 and 3
    {"prob": 0.5, "after": 10}       # let the run warm up first
    {"prob": 1.0, "max_fires": 2}    # stateful cap (not part of the
                                     # pure schedule; documented)

plus arbitrary payload keys the seam interprets (``delay_s``,
``status``, ``retry_after``, ...) which :meth:`FaultPlan.decide`
returns to the caller when the point fires.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional

#: rule keys that control firing; everything else is payload handed to
#: the seam when the point fires
_CONTROL_KEYS = ("prob", "at", "after", "max_fires")


class FaultPlan:
    def __init__(self, seed: int, faults: Dict[str, Dict[str, Any]]):
        self.seed = int(seed)
        self.faults: Dict[str, Dict[str, Any]] = {}
        for point, rule in (faults or {}).items():
            if not isinstance(rule, dict):
                raise ValueError(
                    f"fault rule for {point!r} must be a dict, got "
                    f"{rule!r}")
            self.faults[str(point)] = dict(rule)
        self._lock = threading.Lock()
        self._consults: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}

    # -- the pure schedule ------------------------------------------------

    @staticmethod
    def _u(seed: int, point: str, index: int) -> float:
        """Uniform [0, 1) draw for one (seed, point, index) triple —
        the whole source of chaos randomness, deliberately hash-based so
        per-point schedules are independent and replayable."""
        digest = hashlib.sha256(
            f"{seed}:{point}:{index}".encode()).digest()
        return int.from_bytes(digest[:8], "big") / 2.0 ** 64

    def would_fire(self, point: str, index: int) -> bool:
        """The pure fire function (ignores the stateful ``max_fires``
        cap, which depends on consult history)."""
        rule = self.faults.get(point)
        if rule is None:
            return False
        at = rule.get("at")
        if at is not None and index in at:
            return True
        prob = float(rule.get("prob", 0.0))
        if prob <= 0.0 or index < int(rule.get("after", 0)):
            return False
        return self._u(self.seed, point, index) < prob

    def schedule(self, point: str, n: int) -> List[bool]:
        """The first ``n`` fire decisions for ``point`` — what "same
        seed reproduces the same fault schedule bit-for-bit" means,
        and how tests assert it."""
        return [self.would_fire(point, i) for i in range(n)]

    # -- the consulted (stateful) side ------------------------------------

    def decide(self, point: str) -> Optional[Dict[str, Any]]:
        """Consult ``point`` once: None = don't fire, else the rule's
        payload dict (plus ``point`` and the consult ``index``). Each
        call advances the point's consult counter."""
        rule = self.faults.get(point)
        if rule is None:
            return None
        with self._lock:
            index = self._consults.get(point, 0)
            self._consults[point] = index + 1
            max_fires = rule.get("max_fires")
            if (max_fires is not None
                    and self._fired.get(point, 0) >= int(max_fires)):
                return None
            if not self.would_fire(point, index):
                return None
            self._fired[point] = self._fired.get(point, 0) + 1
        payload = {k: v for k, v in rule.items()
                   if k not in _CONTROL_KEYS}
        payload["point"] = point
        payload["index"] = index
        # metric + log only when a fault actually fires (rare): the
        # consult path itself stays allocation-free. Lazy import — the
        # chaos package must stay importable from leaf modules
        # (utils/atomic.py) without dragging the obs plane in at
        # import time.
        from namazu_tpu.obs.spans import chaos_fault_injected

        chaos_fault_injected(point)
        return payload

    def report(self) -> Dict[str, Any]:
        """Consult/fire counts per point — the harness embeds this in
        every scenario report so a violation names the faults that
        actually landed."""
        with self._lock:
            return {
                "seed": self.seed,
                "points": sorted(self.faults),
                "consults": dict(self._consults),
                "fired": dict(self._fired),
            }

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)
