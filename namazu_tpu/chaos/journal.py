"""Durable append-only event journal: orchestrator crash-recovery.

Before this journal, a killed orchestrator lost every in-flight event —
the parked delays, the waiters blocked in inspectors, the whole run.
The journal is a write-ahead log in the run's storage dir
(``events.journal``): the orchestrator's event loop appends every
inbound event **before** handing it to the policy, and the action loop
appends a release record **after** the answering action is dispatched.
Recovery (`Orchestrator.start` on a dir holding a journal) replays
events with no matching release back through the hub — re-arming the
entity routes, the liveness bookkeeping, and the REST dedupe ring so an
inspector-side replay of the same uuids acks idempotently instead of
doubling.

Durability discipline differs from ``utils/atomic``'s whole-file
replace (wrong tool for an append-only log): each append batch is one
``write`` + ``flush`` + ``fsync``. A hard kill can tear at most the
*final line*, which recovery detects (undecodable JSON) and drops —
the classic WAL torn-tail rule. Release records land *after* dispatch,
so the journal's failure mode across a crash is **at-least-once**
(an event may be re-dispatched if the crash hits the
dispatch→release-record window); the REST endpoint's uuid dedupe and
the transceiver's waiter-keyed dispatch make the duplicate harmless,
and the chaos harness's exactly-once invariant pins the common case.

Wire format: one JSON object per line.
``{"k": "e", "p": <endpoint>, "ev": {...signal jsonable...}}`` = event,
``{"k": "r", "u": [uuid, ...]}`` = released/dispatched uuids.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from namazu_tpu.signal.base import SignalError, signal_from_jsonable
from namazu_tpu.signal.event import Event
from namazu_tpu.utils.log import get_logger

log = get_logger("chaos.journal")

JOURNAL_NAME = "events.journal"


class EventJournal:
    def __init__(self, dir_path: str, fsync: bool = True):
        self.path = os.path.join(os.path.abspath(dir_path), JOURNAL_NAME)
        self._fsync = fsync
        self._fh = None

    # -- writing ----------------------------------------------------------

    def _file(self):
        if self._fh is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._fh = open(self.path, "ab")
        return self._fh

    def _append_lines(self, lines: List[bytes]) -> None:
        fh = self._file()
        fh.write(b"".join(lines))
        fh.flush()
        if self._fsync:
            os.fsync(fh.fileno())

    def append_events(self, events: List[Event],
                      routes: Optional[Dict[str, str]] = None) -> None:
        """Journal a batch of inbound events (one fsync for the whole
        batch). ``routes`` maps entity_id -> endpoint name so recovery
        can restore the hub's routing table."""
        if not events:
            return
        routes = routes or {}
        self._append_lines([
            (json.dumps({"k": "e",
                         "p": routes.get(ev.entity_id, ""),
                         "ev": ev.to_jsonable()},
                        separators=(",", ":")) + "\n").encode()
            for ev in events])

    def append_releases(self, uuids: List[str]) -> None:
        """Journal that these events' answering actions were dispatched
        (one record for the whole batch)."""
        if not uuids:
            return
        self._append_lines([
            (json.dumps({"k": "r", "u": list(uuids)},
                        separators=(",", ":")) + "\n").encode()])

    def close(self) -> None:
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    def remove(self) -> None:
        """Delete the on-disk journal (the run completed cleanly; a
        later run in the same dir must not re-recover it)."""
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # -- recovery ---------------------------------------------------------

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def unreleased(self) -> List[Tuple[Event, str]]:
        """Events journaled but never released, in journal order, each
        with the endpoint name it originally arrived on. Tolerates a
        torn final line (hard kill mid-append) by stopping there;
        duplicate event records for one uuid (a prior recovery
        re-journaled the replay) collapse to one."""
        if not self.exists():
            return []
        events: "Dict[str, Tuple[Event, str]]" = {}
        released = set()
        torn = 0
        with open(self.path, "rb") as fh:
            for raw in fh:
                try:
                    doc = json.loads(raw)
                except ValueError:
                    # a torn tail is expected after a hard kill; a torn
                    # line MID-file would mean lost records — count and
                    # warn either way, keep what parsed
                    torn += 1
                    continue
                kind = doc.get("k")
                if kind == "r":
                    released.update(doc.get("u") or [])
                elif kind == "e":
                    try:
                        sig = signal_from_jsonable(doc.get("ev") or {})
                    except (SignalError, ValueError, TypeError, KeyError):
                        torn += 1
                        continue
                    if isinstance(sig, Event):
                        events.setdefault(
                            sig.uuid, (sig, str(doc.get("p") or "")))
        if torn:
            log.warning("journal %s: dropped %d undecodable line(s) "
                        "(torn tail after a hard kill is expected)",
                        self.path, torn)
        return [pair for uuid, pair in events.items()
                if uuid not in released]
