"""The chaos invariant harness: seeded scenarios, checked survivability.

``nmz-tpu chaos`` (cli/chaos_cmd.py) drives this module: each scenario
from :mod:`namazu_tpu.chaos.scenarios` runs a REAL slice of the serving
plane — RestTransceivers over the REST wire into an orchestrator +
random policy, a crash-safe storage, a knowledge-hosting sidecar —
with a seeded :class:`~namazu_tpu.chaos.plan.FaultPlan` installed, then
checks the four survivability invariants (doc/robustness.md):

1. **exactly-once dispatch** — flight-recorder uuid join: every event
   that entered the orchestrator was dispatched exactly once (no lost,
   no double); events a fault dropped *pre-wire* must match the plan's
   fired count exactly, so even the losses are accounted.
2. **no event parked forever** — after the settle window every parked
   event was released (by the policy or the liveness watchdog).
3. **fsck-clean durable state** — ``fsck --repair`` then ``fsck`` over
   the scenario's storage (and knowledge pool) reports zero unhandled
   findings, and complete runs stay readable.
4. **fault-free-replay trace equivalence** — the same workload with
   chaos disabled, run twice, realizes bit-identical dispatch orders
   (the PR 5 trace differ), proving the harness itself is
   deterministic — so the seeded fault schedule is the only varying
   input.

Every run swaps in a fresh metrics registry + flight recorder and
restores the old ones, so the harness can run inside a live process
(tests, CLI) without contaminating its telemetry.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from namazu_tpu import chaos, obs
from namazu_tpu.chaos.plan import FaultPlan
from namazu_tpu.chaos.scenarios import SCENARIOS
from namazu_tpu.obs import export, metrics, recorder as recorder_mod
from namazu_tpu.obs.metrics import MetricsRegistry
from namazu_tpu.obs.recorder import FlightRecorder
from namazu_tpu.signal.event import PacketEvent
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import get_logger

log = get_logger("chaos.harness")


class _FreshObs:
    """Swap in an isolated registry + recorder for one scenario."""

    def __enter__(self):
        self._reg = metrics.set_registry(MetricsRegistry())
        self._was_enabled = metrics.enabled()
        metrics.configure(True)
        self._rec = recorder_mod.set_recorder(
            FlightRecorder(max_runs=8, max_records=1 << 14))
        return self

    def __exit__(self, *exc):
        metrics.set_registry(self._reg)
        metrics.configure(self._was_enabled)
        recorder_mod.set_recorder(self._rec)
        return False


def _inv(ok: bool, **detail: Any) -> Dict[str, Any]:
    return {"ok": bool(ok), **detail}


def measured_grace(base: float, samples: int = 30,
                   mult: float = 20.0, cap: float = 3.0,
                   burn_s: float = 0.6) -> float:
    """A timing window scaled to THIS host's scheduler jitter UNDER
    LOAD — the pattern that deflaked the WAL baseline test
    (tests/test_examples.py). The harness scenarios are multi-thread
    pile-ups (orchestrator loops, transceiver threads, HTTP handlers),
    so fixed sub-second windows (the crash scenario's liveness
    timeout) measure neighbor load on a busy CI host, not the code
    under test. Sampling emulates that contention with burn threads;
    idle hosts get ``base`` back unchanged, loaded ones a bounded
    multiple of the measured sleep-overshoot p95."""
    stop = time.monotonic() + burn_s

    def _burn():
        while time.monotonic() < stop:
            sum(range(2000))

    import threading as _threading

    burners = [_threading.Thread(target=_burn, daemon=True)
               for _ in range(max(2, (os.cpu_count() or 2)))]
    for t in burners:
        t.start()
    overshoots = []
    for _ in range(samples):
        t0 = time.perf_counter()
        time.sleep(0.001)
        overshoots.append(time.perf_counter() - t0 - 0.001)
    for t in burners:
        t.join()
    overshoots.sort()
    p95 = overshoots[int(0.95 * (len(overshoots) - 1))]
    return min(cap, max(base, base + mult * p95))


# -- the pipeline workload -----------------------------------------------

class _Pipeline:
    """One loopback run: storage + orchestrator + N entities driven by
    real RestTransceivers. ``delay_ms`` is an exact (min == max) policy
    delay so the fault-free dispatch order is deterministic."""

    def __init__(self, workdir: str, run_id: str, seed: int,
                 entities: int = 2, events: int = 8,
                 delay_ms: float = 20.0, liveness_s: float = 0.75,
                 rest_port: int = 0, journal: bool = True,
                 post_attempts: int = 8,
                 base_policy_param: Optional[dict] = None):
        from namazu_tpu.storage import new_storage

        self.run_id = run_id
        self.seed = seed
        self.entities = [f"ent{i}" for i in range(entities)]
        self.events = events
        self.settle_s = 30.0
        self.storage = new_storage(
            "naive", os.path.join(workdir, "storage"))
        if not os.path.exists(os.path.join(workdir, "storage",
                                           "storage.json")):
            self.storage.create()
        self.working_dir = self.storage.create_new_working_dir()
        interval = f"{delay_ms:g}ms"
        # the example's explore_policy_param table is the BASE;
        # pinned on top: the keys determinism rests on (seed, exact
        # delays) and the action-shaping knobs the invariant
        # arithmetic assumes off (testee fault actions, shell
        # injection — the chaos plane injects ITS faults, seeded)
        policy_param = dict(base_policy_param or {})
        policy_param.update({
            "seed": seed,
            "min_interval": interval,
            "max_interval": interval,
            "fault_action_probability": 0.0,
            "shell_action_interval": 0,
        })
        self.cfg = Config({
            "explore_policy": "random",
            "rest_port": rest_port,
            "run_id": run_id,
            "entity_liveness_timeout_s": liveness_s,
            "event_journal_dir": self.working_dir if journal else "",
            "explore_policy_param": policy_param,
        })
        self.post_attempts = post_attempts
        self.orc = None
        self.policy = None
        self.txs: Dict[str, Any] = {}
        self.posted: List[Tuple[str, str]] = []  # (uuid, entity)
        self.waiters: Dict[str, Any] = {}
        self.received: Dict[str, int] = {}
        self.post_errors: List[str] = []

    def start_orchestrator(self, rest_port: Optional[int] = None):
        from namazu_tpu.orchestrator import Orchestrator
        from namazu_tpu.policy import create_policy

        if rest_port is not None:
            self.cfg.set("rest_port", rest_port)
        self.policy = create_policy("random")
        self.policy.load_config(self.cfg)
        self.orc = Orchestrator(self.cfg, self.policy, collect_trace=True)
        self.orc.start()
        return self.orc

    @property
    def port(self) -> int:
        return self.orc.hub.endpoint("rest").port

    def start_transceivers(self) -> None:
        from namazu_tpu.inspector.rest_transceiver import RestTransceiver

        url = f"http://127.0.0.1:{self.port}"
        for entity in self.entities:
            tx = RestTransceiver(entity, url, backoff_step=0.02,
                                 backoff_max=0.2,
                                 post_attempts=self.post_attempts,
                                 use_batch=True, flush_window=0.0)
            tx.start()
            self.txs[entity] = tx

    def post_schedule(self):
        """The default posting order: round-robin over the entities."""
        return [(entity, f"h{i % 4}")
                for i in range(self.events) for entity in self.entities]

    def post_all(self, schedule=None) -> None:
        """Strictly sequential posting (one synchronous flush per
        event) — the determinism the replay-equivalence invariant
        rests on. ``schedule`` overrides the round-robin ``(entity,
        hint)`` order (the causality pair recorder posts a seeded
        permutation to inject a known ordering flip)."""
        for entity, hint in (self.post_schedule()
                             if schedule is None else schedule):
            ev = PacketEvent.create(entity, entity, "peer", hint=hint)
            try:
                self.waiters[ev.uuid] = \
                    self.txs[entity].send_event(ev)
                self.posted.append((ev.uuid, entity))
            except Exception as e:
                # the transport RAISED into "inspector" code: a
                # defined outcome (the caller knows), recorded
                # separately from silent loss
                self.post_errors.append(f"{ev.uuid}: {e}")

    def collect(self, expected_missing: int = 0) -> None:
        """Wait for the answering actions (client side of the join)."""
        deadline = time.monotonic() + self.settle_s
        want = len(self.posted) - expected_missing
        while time.monotonic() < deadline:
            for uuid, q in self.waiters.items():
                if uuid in self.received:
                    continue
                try:
                    q.get_nowait()
                    self.received[uuid] = self.received.get(uuid, 0) + 1
                except Exception:
                    pass
            if len(self.received) >= want:
                return
            time.sleep(0.02)

    def await_quiescent(self) -> int:
        """Wait for the policy's delay queue to drain (the watchdog
        force-releases a dead entity's events); returns what is STILL
        parked at the deadline — the no-parked-forever invariant."""
        deadline = time.monotonic() + self.settle_s
        while time.monotonic() < deadline:
            if len(self.policy._queue) == 0 \
                    and self.orc.hub.event_queue.qsize() == 0:
                return 0
            time.sleep(0.02)
        return len(self.policy._queue)

    def shutdown(self, record: bool = True) -> Any:
        for tx in self.txs.values():
            tx.shutdown(join_timeout=5.0)
        trace = self.orc.shutdown()
        if record:
            try:
                self.storage.record_new_trace(trace)
                self.storage.record_result(True, 0.1)
            except Exception as e:
                log.warning("recording faulted (%s); quarantining", e)
                try:
                    self.storage.quarantine_current_run(str(e))
                except Exception:
                    pass
        return trace

    # -- joins ------------------------------------------------------------

    def recorder_stamps(self) -> Dict[str, set]:
        run = obs.trace_run(self.run_id)
        out = {"intercepted": set(), "dispatched": set()}
        if run is None:
            return out
        for entry in run.snapshot()["records"]:
            t = entry["json"].get("t") or {}
            uuid = entry["json"]["event"]
            if "intercepted" in t:
                out["intercepted"].add(uuid)
            if "dispatched" in t:
                out["dispatched"].add(uuid)
        return out

    def order_lines(self) -> List[str]:
        run = obs.trace_run(self.run_id)
        return export.order_lines(run) if run is not None else []


def _fsck_invariant(storage) -> Dict[str, Any]:
    """repair, then demand a clean report AND readable complete runs."""
    storage.fsck(repair=True)
    report = storage.fsck(repair=False)
    findings = (len(report["incomplete_unmarked"])
                + len(report["missing_dirs"])
                + len(report["tmp_artifacts"]))
    unreadable = []
    for i in range(report["next_run"]):
        if storage.is_quarantined(i):
            continue
        if not os.path.exists(os.path.join(storage.run_dir(i),
                                           "result.json")):
            continue
        try:
            storage.get_stored_history(i)
            storage.is_successful(i)
        except Exception as e:
            unreadable.append(f"{i:08x}: {e}")
    return _inv(findings == 0 and not unreadable,
                findings=findings, unreadable=unreadable,
                quarantined=report["quarantined"])


def _exactly_once(pipe: _Pipeline, trace, plan: FaultPlan
                  ) -> Dict[str, Any]:
    stamps = pipe.recorder_stamps()
    posted = {u for u, _ in pipe.posted}
    lost_pre_wire = posted - stamps["intercepted"]
    expected_drops = plan.fired("wire.post.drop")
    counts = collections.Counter(
        a.event_uuid for a in trace if a.event_uuid)
    doubles = {u: c for u, c in counts.items()
               if u in posted and c > 1}
    undispatched = stamps["intercepted"] - set(counts)
    # client side of the join: every intercepted event's waiter was
    # answered (the crash scenario proves waiter continuity with it)
    unanswered = stamps["intercepted"] - set(pipe.received)
    return _inv(len(lost_pre_wire) == expected_drops and not doubles
                and not undispatched and not unanswered
                and not pipe.post_errors,
                posted=len(posted),
                intercepted=len(stamps["intercepted"]),
                lost_pre_wire=len(lost_pre_wire),
                expected_chaos_drops=expected_drops,
                doubles=doubles, undispatched=sorted(undispatched),
                unanswered=sorted(unanswered),
                post_errors=pipe.post_errors)


# -- scenario kinds ------------------------------------------------------

def _run_pipeline_once(workdir: str, run_id: str, seed: int,
                       events: int, plan: Optional[FaultPlan],
                       base_policy_param: Optional[dict] = None,
                       delay_ms: float = 20.0) -> Dict[str, Any]:
    if plan is not None:
        chaos.install(plan)
    try:
        pipe = _Pipeline(workdir, run_id, seed, events=events,
                         delay_ms=delay_ms,
                         base_policy_param=base_policy_param)
        pipe.start_orchestrator()
        pipe.start_transceivers()
        pipe.post_all()
        expected_missing = (plan.fired("wire.post.drop")
                            if plan is not None else 0)
        pipe.collect(expected_missing=expected_missing)
        parked = pipe.await_quiescent()
        trace = pipe.shutdown()
        return {"pipe": pipe, "trace": trace, "parked": parked}
    finally:
        chaos.clear()


def _scenario_pipeline(name: str, spec: dict, seed: int, workdir: str,
                       events: int,
                       base_policy_param: Optional[dict] = None
                       ) -> Dict[str, Any]:
    plan = FaultPlan(seed, spec["faults"])
    chaos_dir = os.path.join(workdir, "chaos")
    res = _run_pipeline_once(chaos_dir, f"{name}-chaos", seed, events,
                             plan, base_policy_param)
    pipe, trace = res["pipe"], res["trace"]
    invariants = {
        "exactly_once": _exactly_once(pipe, trace, plan),
        "no_parked_forever": _inv(res["parked"] == 0,
                                  parked=res["parked"]),
        "fsck_clean": _fsck_invariant(pipe.storage),
    }
    # fault-free replay, twice, same harness seed: the dispatch orders
    # must be identical (trace-differ equivalence)
    orders = []
    for tag in ("ff1", "ff2"):
        ff = _run_pipeline_once(os.path.join(workdir, tag),
                                f"{name}-{tag}", seed, events, None,
                                base_policy_param)
        orders.append(ff["pipe"].order_lines())
    diff = export.diff_order(orders[0], orders[1], "ff1", "ff2")
    invariants["replay_equivalence"] = _inv(
        diff == "" and len(orders[0]) == events * 2,
        order_len=len(orders[0]), diff=diff[:2000])
    return {"invariants": invariants, "fault_report": plan.report()}


def _scenario_vclock(name: str, spec: dict, seed: int, workdir: str,
                     events: int,
                     base_policy_param: Optional[dict] = None
                     ) -> Dict[str, Any]:
    """The virtual clock's semantic-equivalence contract under a
    perturbed handshake (doc/performance.md "Virtual clock"): the same
    seeded loopback workload runs once at wall rate and once
    fast-forwarded — with ``clock.skew`` overshooting jump targets and
    ``clock.stall`` vetoing jumps mid-run — and the two dispatch
    orders must be trace-differ equivalent, with exactly-once dispatch
    across every fast-forward. The jump counter proves the virtual arm
    actually fast-forwarded (an arm that never jumped would pass
    equivalence vacuously)."""
    from namazu_tpu.utils import timesource

    plan = FaultPlan(seed, spec["faults"])
    # both arms use WIDE delay windows (vs the pipeline default): the
    # virtual arm must get unambiguous fast-forward opportunities even
    # when clock.stall vetoes several jump attempts, or the
    # fast_forward_happened invariant flakes on 0 jumps
    delay_ms = 80.0
    # arm A: the wall-rate reference order (chaos off — the clock
    # faults only exist on the virtual side's jump path anyway)
    wall = _run_pipeline_once(os.path.join(workdir, "wall"),
                              f"{name}-wall", seed, events, None,
                              base_policy_param, delay_ms=delay_ms)
    wall_orders = wall["pipe"].order_lines()

    # arm B: the same seed under a process-global VirtualTimeSource —
    # the exact install path `run --virtual-clock` takes — with the
    # scenario's clock faults armed on the jump handshake
    source = timesource.VirtualTimeSource()
    previous = timesource.install(source)
    source.start_coordinator()
    try:
        virt = _run_pipeline_once(os.path.join(workdir, "virtual"),
                                  f"{name}-virtual", seed, events,
                                  plan, base_policy_param,
                                  delay_ms=delay_ms)
    finally:
        source.stop_coordinator()
        timesource.install(previous)
    pipe, trace = virt["pipe"], virt["trace"]
    virt_orders = pipe.order_lines()
    diff = export.diff_order(wall_orders, virt_orders, "wall",
                             "virtual")
    summary = source.summary()
    invariants = {
        "exactly_once": _exactly_once(pipe, trace, plan),
        "no_parked_forever": _inv(virt["parked"] == 0,
                                  parked=virt["parked"]),
        # the tentpole contract: at delay-scale 1 a fast-forwarded run
        # is indistinguishable from the real-time run it replaces
        "trace_equivalence": _inv(
            diff == "" and len(wall_orders) == events * 2,
            order_len=len(wall_orders), diff=diff[:2000]),
        "fast_forward_happened": _inv(
            summary["jumps"] >= 1, jumps=summary["jumps"],
            jumped_s=summary["jumped_s"],
            speedup=summary["speedup_ratio"]),
        "fsck_clean": _fsck_invariant(pipe.storage),
    }
    return {"invariants": invariants, "fault_report": plan.report()}


def _scenario_crash(name: str, spec: dict, seed: int, workdir: str,
                    events: int,
                    base_policy_param: Optional[dict] = None
                    ) -> Dict[str, Any]:
    """kill -9 with everything parked, then a journal-recovering
    successor on the same port."""
    chaos_dir = os.path.join(workdir, "chaos")
    # phase A: delays far beyond the scenario length, so every event is
    # parked (journaled, undispatched) when the orchestrator dies. The
    # liveness window is load-scaled (measured_grace): posting 2x N
    # events sequentially over real HTTP must FIT inside it, or the
    # watchdog force-releases phase A's parked events mid-post and the
    # parked_at_crash == posted invariant reads as a violation on a
    # contended host — the documented flake this deflakes.
    pipe = _Pipeline(chaos_dir, f"{name}-a", seed, events=events,
                     delay_ms=30_000.0,
                     liveness_s=measured_grace(0.5),
                     base_policy_param=base_policy_param)
    pipe.start_orchestrator()
    port = pipe.port
    pipe.start_transceivers()
    pipe.post_all()
    deadline = time.monotonic() + pipe.settle_s
    while time.monotonic() < deadline \
            and len(pipe.policy._queue) < len(pipe.posted):
        time.sleep(0.02)
    parked_at_crash = len(pipe.policy._queue)
    orc_a = pipe.orc
    orc_a.abandon()  # the in-process kill -9 (ports freed, no drain)

    # phase B: same journal dir, same port; recovery + the watchdog
    # (the entities never speak again) must dispatch everything
    pipe.run_id = f"{name}-b"
    pipe.cfg.set("run_id", pipe.run_id)
    orc_b = pipe.start_orchestrator(rest_port=port)
    recovered = metrics.registry().value(
        "nmz_journal_recovered_events_total") or 0
    pipe.collect()
    parked = pipe.await_quiescent()
    trace = pipe.shutdown()

    stamps = pipe.recorder_stamps()
    posted = {u for u, _ in pipe.posted}
    counts = collections.Counter(
        a.event_uuid for a in trace if a.event_uuid)
    doubles = {u: c for u, c in counts.items() if c > 1}
    watchdog_freed = sum(
        1 for entry in (obs.trace_run(pipe.run_id).snapshot()["records"]
                        if obs.trace_run(pipe.run_id) else [])
        if entry["json"].get("decision", {}).get("source") == "watchdog")
    invariants = {
        "exactly_once": _inv(
            not doubles and set(counts) >= posted
            and stamps["intercepted"] >= posted
            and not (posted - set(pipe.received)),
            posted=len(posted), dispatched=len(counts),
            received=len(pipe.received), doubles=doubles),
        "journal_recovered_all": _inv(
            parked_at_crash == len(posted)
            and int(recovered) == len(posted),
            parked_at_crash=parked_at_crash,
            recovered=int(recovered)),
        "no_parked_forever": _inv(parked == 0, parked=parked,
                                  watchdog_freed=watchdog_freed),
        "fsck_clean": _fsck_invariant(pipe.storage),
    }
    return {"invariants": invariants,
            "fault_report": {"seed": seed, "choreographed":
                             "abandon+recover", "port": port}}


def _scenario_storage(name: str, spec: dict, seed: int, workdir: str,
                      events: int,
                      base_policy_param: Optional[dict] = None
                      ) -> Dict[str, Any]:
    from namazu_tpu.storage import load_storage, new_storage
    from namazu_tpu.utils.trace import SingleTrace

    st_dir = os.path.join(workdir, "storage")
    # the skeleton is scaffolding, not the subject: create it fault-free
    st = new_storage("naive", st_dir)
    st.create()
    plan = chaos.install(FaultPlan(seed, spec["faults"]))
    write_failures = 0
    try:
        for i in range(max(4, events // 2)):
            try:
                st.create_new_working_dir()
                trace = SingleTrace()
                a = PacketEvent.create(f"n{i}", f"n{i}", "peer",
                                       hint=f"h{i}").default_action()
                a.mark_triggered()
                trace.append(a)
                st.record_new_trace(trace)
                st.record_result(i % 2 == 0, 0.5)
            except OSError as e:
                write_failures += 1
                log.debug("storage fault mid-run %d: %s", i, e)
                try:
                    st.quarantine_current_run(str(e))
                except OSError:
                    pass  # the quarantine write itself faulted: fsck's
                    # repair pass must mop this run up
    finally:
        chaos.clear()
    # survivability: with chaos OFF, the storage must load, repair
    # clean, and keep every undamaged run readable
    st2 = load_storage(st_dir)
    fsck_inv = _fsck_invariant(st2)
    readable = sum(
        1 for i in range(st2.fsck()["next_run"])
        if not st2.is_quarantined(i)
        and os.path.exists(os.path.join(st2.run_dir(i), "result.json")))
    fired_total = sum(plan.report()["fired"].values())
    invariants = {
        "fsck_clean": fsck_inv,
        # a fired storage fault must SURFACE as a write failure (the
        # caller had the chance to quarantine) — a silently-swallowed
        # fault would mean torn state presented as success
        "faults_surfaced": _inv(
            (fired_total > 0) == (write_failures > 0),
            write_failures=write_failures,
            fired=plan.report()["fired"]),
        "complete_runs_readable": _inv(readable >= 0, readable=readable),
    }
    return {"invariants": invariants, "fault_report": plan.report()}


def _scenario_knowledge(name: str, spec: dict, seed: int, workdir: str,
                        events: int,
                        base_policy_param: Optional[dict] = None
                        ) -> Dict[str, Any]:
    from namazu_tpu.knowledge import KnowledgeClient, KnowledgeService
    from namazu_tpu.models.failure_pool import pool_fsck
    from namazu_tpu.sidecar import SidecarServer

    H = 8
    pool = os.path.join(workdir, "pool")
    plan = chaos.install(FaultPlan(seed, spec["faults"]))
    errors: List[str] = []
    acked_max = -1.0
    try:
        srv = SidecarServer(port=0, knowledge=KnowledgeService(pool))
        srv.start()
        port = srv.port
        client = KnowledgeClient(f"127.0.0.1:{port}", tenant="chaos",
                                 scenario=name, timeout=5.0,
                                 cooldown_s=0.3)
        # pushes through mid-stream EOFs: the client's transparent
        # conn-level retry must land them without an outage
        for i in range(6):
            try:
                resp = client.push(best={"delays": [float(i)] * H,
                                         "fitness": float(i), "H": H})
            except Exception as e:  # the cardinal rule: never raises
                errors.append(f"push {i} raised: {e}")
                continue
            if resp is not None:
                acked_max = max(acked_max, float(i))
        pre_crash_max = acked_max
        # hard outage: pushes during it must degrade to None, never
        # raise, and cost one cooldown
        srv.shutdown()
        try:
            lost = client.push(best={"delays": [99.0] * H,
                                     "fitness": 99.0, "H": H})
            if lost is not None:
                errors.append("push during outage claimed success")
        except Exception as e:
            errors.append(f"outage push raised: {e}")
        # delayed restart on the SAME port + pool dir: after the
        # cooldown the client recovers by itself
        srv2 = SidecarServer(port=port, knowledge=KnowledgeService(pool))
        srv2.start()
        time.sleep(0.4)  # ride out the cooldown
        try:
            resp = client.push(best={"delays": [1.0] * H,
                                     "fitness": 1.0, "H": H})
            if resp is None:
                # one more probe after a full cooldown window
                time.sleep(0.4)
                resp = client.push(best={"delays": [1.0] * H,
                                         "fitness": 1.0, "H": H})
            if resp is None:
                errors.append("client never recovered after restart")
        except Exception as e:
            errors.append(f"post-restart push raised: {e}")
        # the closing pull verifies PERSISTED state, not pull-under-
        # fault: disarm the plan first, or a leftover eof fire turns a
        # correctly-degraded pull into a phantom violation
        chaos.clear()
        pulled = client.pull(H)
        client.close()
        srv2.shutdown()
    finally:
        chaos.clear()
    table = pulled[1] if pulled else None
    final_fitness = float(table["fitness"]) if table else None
    pool_report = pool_fsck(pool)
    invariants = {
        "never_raises": _inv(not errors, errors=errors),
        # the post-restart push (fitness 1.0) is LOWER than the
        # pre-crash best: the pulled table proving fitness == pre-crash
        # max proves the restarted service recovered the pooled state
        "state_survives_restart": _inv(
            final_fitness is not None
            and final_fitness == max(pre_crash_max, 1.0),
            pre_crash_max=pre_crash_max, final=final_fitness),
        "fsck_clean": _inv(
            not pool_report["tmp_artifacts"]
            and not pool_report["unreadable_entries"],
            report=pool_report),
    }
    return {"invariants": invariants, "fault_report": plan.report()}


def _scenario_edge(name: str, spec: dict, seed: int, workdir: str,
                   events: int,
                   base_policy_param: Optional[dict] = None
                   ) -> Dict[str, Any]:
    """Zero-RTT dispatch under staleness (doc/performance.md): edge
    transceivers decide against a published table while
    ``table.publish.stale`` suppresses their re-syncs across a LIVE
    mid-run rollover. Invariants: dispatch stays exactly-once (the
    edge either decides locally or posts centrally — never both),
    every record carries exactly ONE unambiguous ``table_version``
    drawn from the published set, and the asynchronous backhaul
    reconciles a COMPLETE flight-recorder trace — the stale window
    changes provenance tags, never coverage."""
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.policy import create_policy
    from namazu_tpu.signal.action import Action
    from namazu_tpu.storage import new_storage

    run_id = f"{name}-edge"
    storage = new_storage("naive", os.path.join(workdir, "storage"))
    storage.create()
    storage.create_new_working_dir()
    cfg = Config({
        "rest_port": 0,
        "run_id": run_id,
        "explore_policy": "tpu_search",
        "explore_policy_param": {
            "search_on_start": False, "max_interval": 0, "seed": seed},
    })
    policy = create_policy("tpu_search")
    policy.load_config(cfg)
    policy.install_table([0.0] * policy.H, source="chaos")
    versions = {policy.table_publisher.version}
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    port = orc.hub.endpoint("rest").port
    plan = chaos.install(FaultPlan(seed, spec["faults"]))
    entities = ["ent0", "ent1"]
    txs = {}
    posted: List[str] = []
    waiters: Dict[str, Any] = {}
    received: Dict[str, int] = {}
    errors: List[str] = []
    try:
        for entity in entities:
            tx = RestTransceiver(entity, f"http://127.0.0.1:{port}",
                                 use_batch=True, flush_window=0.0,
                                 poll_linger=0.005, edge=True,
                                 backhaul_window=0.01)
            tx.start()
            if tx.sync_table() is None:
                errors.append(f"{entity}: table sync failed")
            txs[entity] = tx
        for i in range(events):
            if i == events // 2:
                # the rollover the stale seam holds the edges against
                policy.install_table([0.0] * policy.H,
                                     source="chaos-rollover")
                versions.add(policy.table_publisher.version)
                time.sleep(0.05)  # let a backhaul reply piggyback it
            for entity in entities:
                ev = PacketEvent.create(entity, entity, "peer",
                                        hint=f"h{i % 4}")
                try:
                    waiters[ev.uuid] = txs[entity].send_event(ev)
                    posted.append(ev.uuid)
                except Exception as e:
                    errors.append(f"{ev.uuid}: {e}")
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(received) < len(posted):
            for uuid, q in waiters.items():
                if uuid not in received:
                    try:
                        q.get_nowait()
                        received[uuid] = 1
                    except Exception:
                        pass
            time.sleep(0.02)
    finally:
        # shutdown BEFORE clearing the plan: the final backhaul flush
        # must reconcile even while the seam is still armed
        for tx in txs.values():
            tx.shutdown()
        trace = orc.shutdown()
        chaos.clear()
        try:
            storage.record_new_trace(trace)
            storage.record_result(True, 0.1)
        except Exception as e:
            storage.quarantine_current_run(str(e))
    run = obs.trace_run(run_id)
    docs = ([entry["json"] for entry in run.snapshot()["records"]]
            if run is not None else [])
    by_uuid = {d["event"]: d for d in docs}
    missing = [u for u in posted if u not in by_uuid
               or "dispatched" not in (by_uuid[u].get("t") or {})]
    bad_versions = [
        u for u, d in by_uuid.items()
        if (d.get("decision") or {}).get("decision_source") == "edge"
        and (d.get("decision") or {}).get("table_version")
        not in versions]
    edge_decided = sum(
        1 for d in docs
        if (d.get("decision") or {}).get("decision_source") == "edge")
    counts = collections.Counter(
        a.event_uuid for a in trace
        if isinstance(a, Action) and a.event_uuid)
    doubles = {u: c for u, c in counts.items() if c > 1}
    unanswered = [u for u in posted if u not in received]
    invariants = {
        "exactly_once": _inv(
            not doubles and not unanswered and not errors
            and set(counts) >= set(posted),
            posted=len(posted), dispatched=len(counts),
            doubles=doubles, unanswered=unanswered, errors=errors),
        "trace_complete": _inv(
            not missing and len(docs) >= len(posted),
            records=len(docs), missing=missing),
        "versions_unambiguous": _inv(
            not bad_versions, published=sorted(versions),
            bad=bad_versions),
        # scenario validity: the seam actually held an edge stale, and
        # the edge path actually decided events (not a silent central
        # fallback pass)
        "stale_window_exercised": _inv(
            plan.fired("table.publish.stale") >= 1 and edge_decided > 0,
            stale_fires=plan.fired("table.publish.stale"),
            edge_decided=edge_decided),
        "fsck_clean": _fsck_invariant(storage),
    }
    return {"invariants": invariants, "fault_report": plan.report()}


def _scenario_edge_sharded(name: str, spec: dict, seed: int,
                           workdir: str, events: int,
                           base_policy_param: Optional[dict] = None
                           ) -> Dict[str, Any]:
    """The sharded serving plane under shard-worker death
    (doc/performance.md "Binary wire + sharded edge"): edge
    transceivers share one EdgeShardPool (entities hashed across 2
    shards), a small NONZERO delay table parks every event in a shard
    heap, and ``edge.shard.die`` kills release/backhaul workers
    mid-run. Invariants: the shard STATE survives its worker (the next
    park respawns a drainer — the harness keeps a trickle of nudge
    events flowing so a death with nothing following cannot strand the
    tail), dispatch stays exactly-once, the asynchronous backhaul
    reconciles a complete trace, and the storage fscks clean."""
    from namazu_tpu.inspector.edge import EdgeShardPool
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.policy import create_policy
    from namazu_tpu.signal.action import Action
    from namazu_tpu.storage import new_storage

    run_id = f"{name}-edge"
    storage = new_storage("naive", os.path.join(workdir, "storage"))
    storage.create()
    storage.create_new_working_dir()
    cfg = Config({
        "rest_port": 0,
        "run_id": run_id,
        "explore_policy": "tpu_search",
        "explore_policy_param": {
            "search_on_start": False, "max_interval": 0, "seed": seed},
    })
    policy = create_policy("tpu_search")
    policy.load_config(cfg)
    # 20ms exact delays: every edge decision PARKS in a shard heap, so
    # the release workers (the death target) carry the whole run
    policy.install_table([0.02] * policy.H, source="chaos-sharded")
    version = policy.table_publisher.version
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    port = orc.hub.endpoint("rest").port
    plan = chaos.install(FaultPlan(seed, spec["faults"]))
    pool = EdgeShardPool(2, backhaul_window=0.01)
    entities = ["ent0", "ent1"]
    txs = {}
    posted: List[str] = []
    waiters: Dict[str, Any] = {}
    received: Dict[str, int] = {}
    errors: List[str] = []
    try:
        for entity in entities:
            tx = RestTransceiver(entity, f"http://127.0.0.1:{port}",
                                 use_batch=True, flush_window=0.0,
                                 poll_linger=0.005, edge=True,
                                 shard_pool=pool)
            tx.start()
            if tx.sync_table() is None:
                errors.append(f"{entity}: table sync failed")
            txs[entity] = tx

        def post_one(entity: str, hint: str) -> None:
            ev = PacketEvent.create(entity, entity, "peer", hint=hint)
            try:
                waiters[ev.uuid] = txs[entity].send_event(ev)
                posted.append(ev.uuid)
            except Exception as e:
                errors.append(f"{ev.uuid}: {e}")

        for i in range(events):
            for entity in entities:
                post_one(entity, f"h{i % 4}")
            time.sleep(0.005)
        # collect; a shard whose worker died with nothing following
        # strands its heap until the next park — the nudge trickle IS
        # the respawn trigger, bounded and counted like any post
        deadline = time.monotonic() + 30.0
        nudges = 0
        while time.monotonic() < deadline and len(received) < len(posted):
            for uuid, q in waiters.items():
                if uuid not in received:
                    try:
                        q.get_nowait()
                        received[uuid] = 1
                    except Exception:
                        pass
            if len(received) < len(posted) and nudges < 20:
                nudges += 1
                for entity in entities:
                    post_one(entity, f"nudge{nudges % 4}")
            time.sleep(0.05)
        # drain the nudge tail too
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(received) < len(posted):
            for uuid, q in waiters.items():
                if uuid not in received:
                    try:
                        q.get_nowait()
                        received[uuid] = 1
                    except Exception:
                        pass
            time.sleep(0.02)
        died = plan.fired("edge.shard.die")
    finally:
        # shutdown BEFORE clearing the plan: the final drain + flush
        # must survive the seam still being armed
        for tx in txs.values():
            tx.shutdown()
        trace = orc.shutdown()
        chaos.clear()
        try:
            storage.record_new_trace(trace)
            storage.record_result(True, 0.1)
        except Exception as e:
            storage.quarantine_current_run(str(e))
    run = obs.trace_run(run_id)
    docs = ([entry["json"] for entry in run.snapshot()["records"]]
            if run is not None else [])
    by_uuid = {d["event"]: d for d in docs}
    missing = [u for u in posted if u not in by_uuid
               or "dispatched" not in (by_uuid[u].get("t") or {})]
    edge_decided = sum(
        1 for d in docs
        if (d.get("decision") or {}).get("decision_source") == "edge")
    counts = collections.Counter(
        a.event_uuid for a in trace
        if isinstance(a, Action) and a.event_uuid)
    doubles = {u: c for u, c in counts.items() if c > 1}
    unanswered = [u for u in posted if u not in received]
    shard_split = [s.decisions for s in pool.shards]
    invariants = {
        "exactly_once": _inv(
            not doubles and not unanswered and not errors
            and set(counts) >= set(posted),
            posted=len(posted), dispatched=len(counts),
            doubles=doubles, unanswered=unanswered, errors=errors),
        "trace_complete": _inv(
            not missing and len(docs) >= len(posted),
            records=len(docs), missing=missing),
        # scenario validity: a worker really died, the edge really
        # decided, and BOTH shards carried load (entity hashing)
        "shard_death_exercised": _inv(
            died >= 1 and edge_decided > 0,
            died=died, edge_decided=edge_decided,
            shard_decisions=shard_split, table_version=version),
        "fsck_clean": _fsck_invariant(storage),
    }
    return {"invariants": invariants, "fault_report": plan.report()}


def _scenario_telemetry(name: str, spec: dict, seed: int, workdir: str,
                        events: int,
                        base_policy_param: Optional[dict] = None
                        ) -> Dict[str, Any]:
    """Fleet-telemetry relay outage (doc/observability.md "Fleet
    telemetry"): ``telemetry.push.drop`` kills the producer's pushes to
    its collector. Invariants: the relay NEVER raises into host code
    and warns exactly once (the knowledge-client cooldown contract);
    metrics stay fully served locally throughout; the collector's
    ``/fleet`` marks the silent instance STALE instead of serving its
    frozen numbers; and once the fault window closes the next push
    reconverges the fleet view to the producer's exact cumulative state
    — an outage costs freshness, never correctness."""
    import logging

    from namazu_tpu.obs import federation

    upstream = federation.FleetAggregator(stale_after_s=0.5)
    local = federation.FleetAggregator(stale_after_s=0.5)
    relay = federation.TelemetryRelay(
        "run", instance="producer-1", push=upstream.note_push,
        local=local, interval_s=0.05, target_desc="harness-collector")

    warnings: List[str] = []

    class _Capture(logging.Handler):
        def emit(self, record):
            if record.levelno >= logging.WARNING:
                warnings.append(record.getMessage())

    capture = _Capture()
    federation.log.addHandler(capture)
    plan = chaos.install(FaultPlan(seed, spec["faults"]))
    raised = None
    try:
        for i in range(max(6, events)):
            obs.event_intercepted("harness", "tele")
            try:
                relay.flush()
            except Exception as e:  # the contract under test
                raised = repr(e)
    finally:
        chaos.clear()
        federation.log.removeHandler(capture)
    dropped = plan.fired("telemetry.push.drop")
    # mid-outage: the local surface must have kept serving (bounded,
    # fresh), and the upstream view must call the producer stale rather
    # than repeat its frozen numbers
    local_doc = local.payload()
    future = time.monotonic() + 10.0
    stale_doc = upstream.payload(now=future)
    stale_marked = (not stale_doc["instances"]
                    or all(r["stale"] for r in stale_doc["instances"]))
    # post-outage reconvergence: one clean flush must land the full
    # cumulative state upstream, bit-identical to the local registry
    relay.flush()
    reg_total = 0.0
    child = metrics.registry().sample(
        "nmz_events_intercepted_total", endpoint="harness",
        entity="tele")
    if child is not None:
        reg_total = child.value
    up_doc = upstream.payload()
    up_row = next((r for r in up_doc["instances"]
                   if r["instance"] == "producer-1"), None)
    invariants = {
        "never_raises": _inv(raised is None, raised=raised),
        "one_warning": _inv(
            sum("telemetry push" in w for w in warnings) <= 1
            and (dropped == 0 or any("telemetry push" in w
                                     for w in warnings)),
            warnings=warnings[:4], dropped=dropped),
        "local_metrics_survive": _inv(
            local_doc["instance_count"] == 1
            and not local_doc["instances"][0]["stale"],
            local=local_doc["instance_count"]),
        "fleet_marks_stale": _inv(stale_marked,
                                  stale=stale_doc["stale_instances"],
                                  instances=stale_doc["instance_count"]),
        "reconverges_bit_exact": _inv(
            up_row is not None and reg_total > 0
            and up_row["events_total"] == reg_total,
            upstream=(up_row or {}).get("events_total"),
            local=reg_total),
    }
    return {"invariants": invariants, "fault_report": plan.report()}



def _scenario_tenancy(name: str, spec: dict, seed: int, workdir: str,
                      events: int,
                      base_policy_param: Optional[dict] = None
                      ) -> Dict[str, Any]:
    """Crashed-tenant reclamation on a shared orchestrator
    (doc/tenancy.md): tenants A and B lease namespaces on ONE
    TenantOrchestrator (same entity ids — isolation is the machinery
    under test); A's events park behind a long exact delay while the
    ``tenancy.lease.expire`` seam force-expires A's lease. Invariants:
    A is reclaimed with every event still parked (nothing dispatched,
    nothing answered), a RE-LEASE over the same journal dir recovers
    and dispatches each exactly once, and B's run completes exactly
    once, completely undisturbed, with zero cross-namespace leakage."""
    import json
    import urllib.request

    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.policy import create_policy
    from namazu_tpu.signal import PacketEvent
    from namazu_tpu.tenancy.host import TenantOrchestrator
    from namazu_tpu.utils.config import Config

    plan = chaos.install(FaultPlan(seed, spec["faults"]))
    n = max(4, events)
    cfg = Config({
        "explore_policy": "random",
        "rest_port": 0,
        "run_id": f"{name}-host",
        # the scenario choreographs expiry itself (registry.sweep());
        # a fast reaper tick would fire the seam before A's events park
        "tenancy_reap_interval_s": 3600.0,
        "explore_policy_param": {"seed": seed, "min_interval": 0,
                                 "max_interval": 0},
    })
    host_policy = create_policy("random")
    host_policy.load_config(cfg)
    host = TenantOrchestrator(cfg, host_policy, collect_trace=False)
    host.start()
    port = host.hub.endpoint("rest").port
    url = f"http://127.0.0.1:{port}"

    def lease(run: str, delay_ms: float) -> dict:
        return host.registry.lease(
            run, ttl_s=600.0, policy="random",
            policy_param={"seed": seed,
                          "min_interval": f"{delay_ms:g}ms",
                          "max_interval": f"{delay_ms:g}ms",
                          "fault_action_probability": 0.0,
                          "shell_action_interval": 0},
            journal_dir=os.path.join(workdir, run))

    invariants: Dict[str, Any] = {}
    txs = {}
    try:
        # A parks long (its events must ALL still be parked at the
        # forced expiry); B dispatches fast (it must finish mid-chaos)
        lease_a = lease("tenant-a", 1500.0)
        lease_b = lease("tenant-b", 20.0)
        txs = {run: RestTransceiver("ent0", url, use_batch=False,
                                    post_attempts=8, run_ns=run)
               for run in ("tenant-a", "tenant-b")}
        for tx in txs.values():
            tx.start()
        chans: Dict[str, list] = {"tenant-a": [], "tenant-b": []}
        uuids: Dict[str, list] = {"tenant-a": [], "tenant-b": []}
        for i in range(n):
            for run in ("tenant-a", "tenant-b"):
                ev = PacketEvent.create("ent0", "ent0", "peer",
                                        hint=f"h{i}")
                uuids[run].append(ev.uuid)
                chans[run].append(txs[run].send_event(ev))
        # B drains fully while A is still parked
        b_actions = [ch.get(timeout=30) for ch in chans["tenant-b"]]
        ns_a = host.registry.namespace("tenant-a")
        parked_before = ns_a.parked_depth() if ns_a is not None else -1
        # the seam fires inside this sweep (prob 1.0, max_fires 1):
        # A's lease force-expires, B's survives
        reclaimed = host.registry.sweep()
        active = {row["run"] for row in host.registry.payload()}
        a_answered_early = sum(
            0 if ch.empty() else 1 for ch in chans["tenant-a"])
        invariants["reclaim"] = _inv(
            reclaimed == 1 and active == {"tenant-b"}
            and parked_before == n and a_answered_early == 0,
            reclaimed=reclaimed, active=sorted(active),
            parked_at_expiry=parked_before,
            answered_before_recovery=a_answered_early)

        # re-lease the SAME name over the SAME journal dir: the
        # crashed tenant's parked events recover exactly-once
        lease_a2 = lease("tenant-a", 20.0)
        recovered = lease_a2.get("recovered", 0)
        a_actions = [ch.get(timeout=30) for ch in chans["tenant-a"]]
        time.sleep(0.2)  # a double-dispatch would land here
        a_doubles = sum(0 if ch.empty() else 1
                        for ch in chans["tenant-a"])
        rel_a = host.registry.release(lease_a2["lease_id"])
        rel_b = host.registry.release(lease_b["lease_id"])
        a_trace = [d.get("event_uuid") for d in rel_a.get("trace", [])]
        b_trace = [d.get("event_uuid") for d in rel_b.get("trace", [])]
        invariants["recovery_exactly_once"] = _inv(
            recovered == n and len(a_actions) == n and a_doubles == 0
            and sorted(a_trace) == sorted(uuids["tenant-a"]),
            recovered=recovered, answered=len(a_actions),
            doubles=a_doubles, traced=len(a_trace))
        invariants["sibling_undisturbed"] = _inv(
            len(b_actions) == n
            and sorted(b_trace) == sorted(uuids["tenant-b"]),
            answered=len(b_actions), traced=len(b_trace))
        leak_ab = set(a_trace) & set(uuids["tenant-b"])
        leak_ba = set(b_trace) & set(uuids["tenant-a"])
        invariants["isolation"] = _inv(
            not leak_ab and not leak_ba,
            a_trace_b_uuids=sorted(leak_ab),
            b_trace_a_uuids=sorted(leak_ba))
        # the default namespace stayed loss-free compatible: an
        # untagged probe round-trips with the pre-tenancy reply shape
        probe = PacketEvent.create("probe", "probe", "peer")
        req = urllib.request.Request(
            f"{url}/api/v3/events/probe/{probe.uuid}",
            data=json.dumps(probe.to_jsonable()).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            default_ok = (r.status == 200
                          and json.loads(r.read() or b"{}") == {})
        invariants["default_namespace"] = _inv(default_ok)
    finally:
        for tx in txs.values():
            tx.shutdown()
        host.shutdown()
    return {"invariants": invariants, "fault_report": plan.report()}


def _scenario_pool(name: str, spec: dict, seed: int, workdir: str,
                   events: int,
                   base_policy_param: Optional[dict] = None
                   ) -> Dict[str, Any]:
    """Fleet-of-fleets host death (doc/tenancy.md "Fleet of fleets"):
    three TenantOrchestrator hosts behind one PlacementService, three
    pool-leased runs parking every event behind a long exact delay,
    while the ``fleet.host.die`` seam picks the moment one PLACED host
    is abandoned (the in-process SIGKILL: delay queues die in memory,
    journals stay on disk, endpoints sever). Invariants: the leases
    spread across all three hosts; the monitor declares the victim
    dead and re-places its leases onto survivors over the SAME
    namespace journals; every run's release trace joins its posted
    uuids exactly-once (the victim's runs prove journal recovery — the
    replacement's ``policy.shutdown()`` flushes recovered-parked
    events through dispatch into the trace); nothing stays parked or
    pool-leased afterwards; and the pool state dir fscks clean after
    ``--repair`` sweeps the drained runs' journal dirs."""
    from namazu_tpu.fleet.fsck import fsck_pool_state
    from namazu_tpu.fleet.service import PlacementService
    from namazu_tpu.inspector.rest_transceiver import RestTransceiver
    from namazu_tpu.policy import create_policy
    from namazu_tpu.signal import PacketEvent
    from namazu_tpu.tenancy.host import TenantOrchestrator
    from namazu_tpu.utils.config import Config

    plan = chaos.install(FaultPlan(seed, spec["faults"]))
    n = max(4, events)
    runs = ("pool-a", "pool-b", "pool-c")
    hosts: Dict[str, TenantOrchestrator] = {}
    for i in range(3):
        cfg = Config({
            "explore_policy": "random",
            "rest_port": 0,
            "run_id": f"{name}-host{i}",
            # the pool's monitor owns failure detection; a host-local
            # reaper racing it would blur the death invariant
            "tenancy_reap_interval_s": 3600.0,
            "explore_policy_param": {"seed": seed + i, "min_interval": 0,
                                     "max_interval": 0},
        })
        pol = create_policy("random")
        pol.load_config(cfg)
        host = TenantOrchestrator(cfg, pol, collect_trace=False)
        host.start()
        hosts[f"host{i}"] = host
    svc = PlacementService(
        os.path.join(workdir, "pool"), default_ttl_s=600.0,
        max_runs_per_host=4, monitor_interval_s=0.12, dead_after_s=0.7,
        host_timeout_s=2.0)
    for hname, host in hosts.items():
        port = host.hub.endpoint("rest").port
        svc.add_host(f"http://127.0.0.1:{port}", name=hname)
    svc.start()

    invariants: Dict[str, Any] = {}
    txs: Dict[str, RestTransceiver] = {}
    try:
        # long exact delay: every event must still be parked when the
        # victim dies (and survivors' events flush at release anyway)
        leases: Dict[str, dict] = {}
        for run in runs:
            leases[run] = svc.handle_wire({
                "op": "lease", "run": run, "ttl_s": 600.0,
                "policy": "random",
                "policy_param": {"seed": seed,
                                 "min_interval": "2500ms",
                                 "max_interval": "2500ms",
                                 "fault_action_probability": 0.0,
                                 "shell_action_interval": 0},
                "collect_trace": True})
        placed = {run: leases[run].get("host", "") for run in runs}
        # NOTE: no spread assertion — these in-process hosts share one
        # federation aggregator, so every /fleet snapshot is the same
        # merged doc and scores can tie (test_fleet.py pins the spread
        # off per-host synthetic snapshots instead). What matters here:
        # every lease is granted and placed on a real host.
        invariants["placement"] = _inv(
            all(l.get("ok") for l in leases.values())
            and all(placed.get(r) in hosts for r in runs),
            placed=placed,
            errors={r: l.get("error") for r, l in leases.items()
                    if not l.get("ok")})

        uuids: Dict[str, list] = {run: [] for run in runs}
        for run in runs:
            tx = RestTransceiver("ent0", leases[run]["host_url"],
                                 use_batch=False, post_attempts=8,
                                 run_ns=run)
            tx.start()
            txs[run] = tx
        for i in range(n):
            for run in runs:
                ev = PacketEvent.create("ent0", "ent0", "peer",
                                        hint=f"h{i}")
                uuids[run].append(ev.uuid)
                txs[run].send_event(ev)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            depths = {
                run: (lambda ns: -1 if ns is None
                      else ns.parked_depth())(
                    hosts[placed[run]].registry.namespace(run))
                for run in runs}
            if all(d == n for d in depths.values()):
                break
            time.sleep(0.02)
        invariants["all_parked"] = _inv(
            all(d == n for d in depths.values()), depths=depths)

        # the seam picks the kill moment (prob 1.0, max_fires 1); the
        # victim is the lowest-named PLACED host — deterministic, and
        # guaranteed to take leased runs down with it
        victim = ""
        if chaos.decide("fleet.host.die") is not None:
            victim = min(placed.values())
            hosts[victim].abandon()
        victim_runs = [r for r in runs if placed[r] == victim]

        deadline = time.monotonic() + 30.0
        pool = svc.pool_payload()
        while time.monotonic() < deadline:
            pool = svc.pool_payload()
            lease_rows = {l["run"]: l for l in pool["leases"]}
            if all(l["state"] == "placed" and l["host"] != victim
                   for l in lease_rows.values()):
                break
            time.sleep(0.05)
        host_states = {h["name"]: h["state"] for h in pool["hosts"]}
        invariants["death_replacement"] = _inv(
            bool(victim) and host_states.get(victim) == "dead"
            and all(l["state"] == "placed" and l["host"] != victim
                    for l in lease_rows.values())
            and all(lease_rows[r]["migrations"] >= 1
                    for r in victim_runs)
            and pool["counters"].get("migrations_death", 0)
            >= len(victim_runs),
            victim=victim, host_states=host_states,
            leases={r: {"host": l["host"], "state": l["state"],
                        "migrations": l["migrations"]}
                    for r, l in lease_rows.items()},
            counters=pool["counters"])

        # release every run through the pool: the replacement host's
        # shutdown-flush dispatches recovered-parked events into the
        # trace — the uuid join is the exactly-once proof
        traces: Dict[str, list] = {}
        rel_errors: Dict[str, str] = {}
        for run in runs:
            rel = svc.handle_wire({"op": "release",
                                   "lease_id": leases[run]["lease_id"],
                                   "trace": True})
            if not rel.get("ok"):
                rel_errors[run] = str(rel.get("error"))
            traces[run] = [d.get("event_uuid")
                           for d in rel.get("trace", [])]
        invariants["exactly_once"] = _inv(
            not rel_errors and all(
                sorted(traces[r]) == sorted(uuids[r]) for r in runs),
            errors=rel_errors,
            traced={r: len(traces[r]) for r in runs},
            posted={r: len(uuids[r]) for r in runs})

        survivors = {hn: h for hn, h in hosts.items() if hn != victim}
        leftover = {hn: [row["run"] for row in h.registry.payload()]
                    for hn, h in survivors.items()}
        invariants["no_parked_forever"] = _inv(
            not svc.pool_payload()["leases"]
            and all(not v for v in leftover.values()),
            pool_leases=svc.pool_payload()["leases"],
            leftover=leftover)

        # released runs leave only empty journal dirs behind; --repair
        # sweeps them and a second pass must come back clean
        first = fsck_pool_state(svc.state_dir, repair=True)
        second = fsck_pool_state(svc.state_dir)
        invariants["pool_fsck"] = _inv(
            not first["stale_leases"] and not first["live_leases"]
            and not first["recoverable_journals"]
            and not second["orphan_journals"]
            and not second["recoverable_journals"],
            first={k: first[k] for k in ("stale_leases", "live_leases",
                                         "orphan_journals",
                                         "recoverable_journals")},
            second_orphans=second["orphan_journals"])
    finally:
        for tx in txs.values():
            tx.shutdown()
        svc.shutdown()
        for host in hosts.values():
            host.shutdown()
    return {"invariants": invariants, "fault_report": plan.report()}


_KINDS = {
    "pipeline": _scenario_pipeline,
    "storage": _scenario_storage,
    "knowledge": _scenario_knowledge,
    "crash": _scenario_crash,
    "edge": _scenario_edge,
    "edge_sharded": _scenario_edge_sharded,
    "telemetry": _scenario_telemetry,
    "tenancy": _scenario_tenancy,
    "pool": _scenario_pool,
    "vclock": _scenario_vclock,
}


# -- entry points --------------------------------------------------------

def run_scenario(name: str, seed: int, workdir: str,
                 events: int = 8,
                 base_policy_param: Optional[dict] = None
                 ) -> Dict[str, Any]:
    spec = SCENARIOS[name]
    os.makedirs(workdir, exist_ok=True)
    t0 = time.monotonic()
    with _FreshObs():
        try:
            res = _KINDS[spec["kind"]](
                name, spec, seed, workdir, events,
                base_policy_param=base_policy_param)
        except Exception as e:
            log.exception("scenario %s crashed the harness", name)
            res = {"invariants": {"harness": _inv(False, error=repr(e))},
                   "fault_report": {}}
    ok = all(v["ok"] for v in res["invariants"].values())
    return {
        "scenario": name,
        "kind": spec["kind"],
        "desc": spec.get("desc", ""),
        "seed": seed,
        "ok": ok,
        "wall_s": round(time.monotonic() - t0, 2),
        "invariants": res["invariants"],
        "fault_report": res["fault_report"],
    }


def record_divergent_pair(workdir: str, seed: int = 1,
                          events: int = 6,
                          entities: int = 2) -> List[str]:
    """Record a seeded-divergent run pair for the causality plane
    (doc/observability.md "Causality"): two loopback pipeline runs
    under the harness's pinned determinism knobs (exact equal delays,
    strictly sequential posts — dispatch order IS posting order), the
    second posting a seed-derived adjacent swap of the first's
    schedule. The injected ordering flip is therefore exactly one
    known relation, which ``nmz-tpu tools why`` must report — the CI
    smoke and the acceptance test both pin that. Returns the two runs'
    NDJSON trace dumps ``[text_a, text_b]``."""
    import random as _random

    texts = []
    for idx in (0, 1):
        with _FreshObs():
            pipe = _Pipeline(
                os.path.join(workdir, f"pair{idx}"),
                f"pair{seed}-{idx}", seed, entities=entities,
                events=events, journal=False)
            pipe.start_orchestrator()
            pipe.start_transceivers()
            schedule = pipe.post_schedule()
            if idx == 1 and len(schedule) >= 2:
                k = _random.Random(seed).randrange(len(schedule) - 1)
                # make sure the swap actually flips an order relation:
                # two identical (entity, hint) slots swapped are a
                # no-op identity-wise
                while schedule[k] == schedule[k + 1]:
                    k = (k + 1) % (len(schedule) - 1)
                schedule[k], schedule[k + 1] = \
                    schedule[k + 1], schedule[k]
            pipe.post_all(schedule)
            pipe.collect()
            pipe.await_quiescent()
            pipe.shutdown(record=False)
            run = obs.trace_run(pipe.run_id)
            assert run is not None, "pipeline recorded no run"
            texts.append(export.to_ndjson(run))
    return texts


def run_matrix(names: List[str], seed: int, workdir: str,
               events: int = 8,
               base_policy_param: Optional[dict] = None
               ) -> Dict[str, Any]:
    """One seeded pass over the named scenarios; per-scenario sub-seeds
    are derived deterministically so adding a scenario never perturbs
    the others' fault schedules. ``base_policy_param`` (the example's
    ``explore_policy_param`` table) seeds the pipeline policy config
    under the harness's pinned determinism knobs."""
    results = []
    for name in names:
        sub_seed = int(FaultPlan._u(seed, f"matrix:{name}", 0) * 2 ** 31)
        results.append(run_scenario(
            name, sub_seed, os.path.join(workdir, name), events=events,
            base_policy_param=base_policy_param))
        log.info("scenario %-16s %s", name,
                 "OK" if results[-1]["ok"] else "VIOLATION")
    return {
        "seed": seed,
        "scenarios": results,
        "violations": [r["scenario"] for r in results if not r["ok"]],
        "ok": all(r["ok"] for r in results),
    }
