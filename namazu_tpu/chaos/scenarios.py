"""Scenario catalog for the chaos matrix (doc/robustness.md).

Each scenario is one FaultPlan spec plus the harness *kind* that drives
it. Kinds:

* ``pipeline`` — the full loopback event plane: real RestTransceivers
  posting deferred events through the REST endpoint into an
  orchestrator + random policy, faults armed on the wire/endpoint
  seams. Invariants: exactly-once dispatch, nothing parked forever,
  fsck-clean storage, fault-free-replay trace equivalence.
* ``storage`` — a crash-safe-storage workout: repeated run recording
  under injected rename/fsync/torn-tmp failures; invariant: every run
  is either complete or quarantined, and ``fsck --repair`` leaves the
  storage clean and loadable.
* ``knowledge`` — push/pull against a real knowledge-hosting sidecar
  through mid-stream EOFs, a hard stop, and a restart; invariant: no
  exception ever escapes into campaign code, the pooled state survives
  the restart exactly-once, and the pool fscks clean.
* ``crash`` — orchestrator ``kill -9`` mid-run (harness-choreographed
  abandon + journal-recovering successor on the same port); invariant:
  every parked event is recovered and dispatched exactly once, proven
  by the flight-recorder uuid join across both incarnations.
* ``edge`` — zero-RTT edge dispatch under staleness
  (doc/performance.md): edges decide against a published delay table
  while ``table.publish.stale`` pins them stale across a live
  rollover; invariant: exactly-once dispatch, one unambiguous
  ``table_version`` per record, and a complete backhaul-reconciled
  trace.
* ``edge_sharded`` — the sharded serving plane under worker death
  (doc/performance.md "Binary wire + sharded edge"): edge
  transceivers share an EdgeShardPool with nonzero delays so events
  park in shard heaps, while ``edge.shard.die`` kills shard workers
  mid-run; invariant: the respawned workers drain the surviving
  shard state — exactly-once dispatch, a complete backhauled trace,
  fsck-clean storage.
* ``tenancy`` — crashed-tenant reclamation on a shared orchestrator
  (doc/tenancy.md): two namespaces on one TenantOrchestrator while
  ``tenancy.lease.expire`` force-expires one tenant's lease with every
  event parked; invariant: the namespace is reclaimed undispatched, a
  re-lease over the same journal recovers each event exactly-once, the
  sibling namespace completes undisturbed, and nothing crosses
  namespaces.
* ``pool`` — fleet-of-fleets host death (doc/tenancy.md "Fleet of
  fleets"): three orchestrator hosts under one placement service,
  every leased run's events parked, while ``fleet.host.die`` SIGKILLs
  (abandons) one placed host mid-campaign; invariant: the monitor
  declares the host dead and re-places its leases onto survivors, a
  re-grant over the same namespace journal recovers each parked event,
  release traces join the posted uuids exactly-once per run, nothing
  stays parked, and the pool state dir fscks clean after repair.
* ``vclock`` — virtual-clock equivalence under a perturbed epoch-page
  handshake (doc/performance.md "Virtual clock"): the same seeded
  loopback run executes once at wall rate and once fast-forwarded
  while ``clock.skew`` perturbs jump targets and ``clock.stall``
  vetoes jumps mid-run; invariant: the two runs are trace-differ
  equivalent (same events, same dispatch order), dispatch stays
  exactly-once across every fast-forward, and the virtual run never
  releases a delayed event before its virtual deadline.
* ``telemetry`` — fleet-telemetry relay outage
  (doc/observability.md "Fleet telemetry"): ``telemetry.push.drop``
  kills the producer's pushes; invariant: never an exception into
  host code, one warning, local metrics keep serving, ``/fleet``
  marks the instance stale, and the first clean push reconverges the
  fleet view bit-exactly.

The specs keep each scenario to ONE fault family so the invariant
arithmetic (e.g. ``lost == fired("wire.post.drop")``) stays exact.
"""

from __future__ import annotations

from typing import Dict, List

SCENARIOS: Dict[str, dict] = {
    "wire_drop": {
        "kind": "pipeline",
        "desc": "event batches vanish pre-wire; the loss ledger must "
                "match the plan's fired count exactly",
        "faults": {"wire.post.drop": {"prob": 0.25, "max_fires": 3}},
    },
    "wire_dup": {
        "kind": "pipeline",
        "desc": "every POST may be duplicated on the wire; the "
                "endpoint dedupe ring must keep dispatch exactly-once",
        "faults": {"wire.post.dup": {"prob": 0.35}},
    },
    "wire_lost_reply": {
        "kind": "pipeline",
        "desc": "a 200 is poisoned into a lost reply; the bounded "
                "retry replays and the replay must dedupe",
        "faults": {"wire.post.lost_reply": {"prob": 0.3, "max_fires": 4}},
    },
    "wire_sever": {
        "kind": "pipeline",
        "desc": "the keep-alive poll socket is severed; the receive "
                "loop must reconnect and replay unacked events "
                "idempotently",
        "faults": {"wire.poll.sever": {"prob": 0.25, "max_fires": 3}},
    },
    "ingress_429": {
        "kind": "pipeline",
        "desc": "a 429 storm with Retry-After; the transceiver must "
                "honor the header inside its bounded retry, losing "
                "nothing",
        "faults": {"endpoint.ingress.refuse": {
            "prob": 0.35, "max_fires": 6,
            "status": 429, "retry_after": 0.05}},
    },
    "poll_stall": {
        "kind": "pipeline",
        "desc": "long-polls stall server-side; delivery slows but "
                "nothing is lost or doubled",
        "faults": {"endpoint.poll.stall": {
            "prob": 0.3, "max_fires": 3, "delay_s": 0.25}},
    },
    "storage_torn": {
        "kind": "storage",
        "desc": "renames fail and tmp files tear mid-write; fsck must "
                "find + repair every mess and complete runs stay "
                "readable",
        "faults": {"storage.tear": {"prob": 0.2},
                   "storage.rename": {"prob": 0.2}},
    },
    "storage_fsync": {
        "kind": "storage",
        "desc": "fsyncs fail (ENOSPC/EIO class); destinations must "
                "hold complete documents throughout",
        "faults": {"storage.fsync": {"prob": 0.3}},
    },
    "knowledge_outage": {
        "kind": "knowledge",
        "desc": "mid-stream EOFs, a dead service, a delayed restart; "
                "the client degrades without raising and the pooled "
                "state survives exactly-once",
        "faults": {"knowledge.eof": {"prob": 0.3, "max_fires": 3}},
    },
    "crash_restart": {
        "kind": "crash",
        "desc": "orchestrator killed with every event parked; the "
                "journal-recovering successor + transceiver replay "
                "must dispatch each exactly once",
        "faults": {},
    },
    "edge_stale": {
        "kind": "edge",
        "desc": "edges forced stale across a live table rollover; "
                "dispatch must stay exactly-once, every record must "
                "carry one unambiguous table_version, and the "
                "backhaul must reconcile a complete trace",
        "faults": {"table.publish.stale": {"prob": 1.0, "max_fires": 3}},
    },
    "edge_sharded": {
        "kind": "edge_sharded",
        "desc": "a shard's release/backhaul worker dies mid-run "
                "(edge.shard.die); the surviving shard state must be "
                "drained by the respawned worker — dispatch stays "
                "exactly-once, the backhauled trace complete, the "
                "storage fsck-clean",
        "faults": {"edge.shard.die": {"prob": 0.6, "max_fires": 2}},
    },
    "wire_garble": {
        "kind": "pipeline",
        "desc": "negotiated-binary payloads are corrupted in flight; "
                "the server must answer (never sever the keep-alive), "
                "the bounded retry must resend clean copies, and "
                "dispatch stays exactly-once",
        "faults": {"wire.binary.garble": {"prob": 0.3, "max_fires": 4}},
    },
    "tenant_crash": {
        "kind": "tenancy",
        "desc": "a tenant's lease force-expires mid-run "
                "(tenancy.lease.expire) with every event parked; its "
                "namespace must be reclaimed and a re-lease over the "
                "same journal must recover each event exactly-once, "
                "while the sibling namespace dispatches undisturbed "
                "and nothing leaks across namespaces",
        "faults": {"tenancy.lease.expire": {"prob": 1.0,
                                            "max_fires": 1}},
    },
    "pool_host_die": {
        "kind": "pool",
        "desc": "one of three pool hosts is SIGKILLed (fleet.host.die) "
                "with every leased run's events parked; the placement "
                "service must declare it dead, re-place its leases "
                "onto survivors over the same namespace journals, and "
                "every event must dispatch exactly-once into the "
                "release traces — no run left pending, pool state "
                "fsck-clean",
        "faults": {"fleet.host.die": {"prob": 1.0, "max_fires": 1}},
    },
    "vclock_equiv": {
        "kind": "vclock",
        "desc": "a fast-forwarded run races a wall-rate twin of the "
                "same seed while clock.skew perturbs jump targets and "
                "clock.stall vetoes jumps; the runs must be "
                "trace-differ equivalent, dispatch exactly-once "
                "across every mid-run fast-forward, and no delayed "
                "event may release before its virtual deadline",
        "faults": {"clock.skew": {"prob": 0.5, "max_fires": 4,
                                  "skew_s": 0.003},
                   "clock.stall": {"prob": 0.3, "max_fires": 3}},
    },
    "relay_outage": {
        "kind": "telemetry",
        "desc": "the fleet-telemetry collector goes dark; the relay "
                "must degrade to local-only metrics with ONE warning "
                "and bounded buffering, /fleet must mark the instance "
                "stale, and the first clean push must reconverge the "
                "fleet view bit-exactly",
        "faults": {"telemetry.push.drop": {"prob": 1.0, "max_fires": 4}},
    },
}

#: the CI smoke matrix — wire, endpoint, storage, knowledge, crash,
#: and edge fault families all covered (>= 6 scenarios per the
#: acceptance bar)
DEFAULT_MATRIX: List[str] = [
    "wire_drop", "wire_dup", "wire_lost_reply", "wire_sever",
    "ingress_429", "storage_torn", "knowledge_outage", "crash_restart",
    "edge_stale", "edge_sharded", "wire_garble", "relay_outage",
    "tenant_crash", "pool_host_die", "vclock_equiv",
]


def resolve_matrix(spec: str) -> List[str]:
    """``"all"``, ``"default"``, or a comma-separated scenario list."""
    if spec in ("", "default"):
        names = list(DEFAULT_MATRIX)
    elif spec == "all":
        names = sorted(SCENARIOS)
    else:
        names = [s.strip() for s in spec.split(",") if s.strip()]
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        raise ValueError(
            f"unknown scenario(s) {unknown}; known: {sorted(SCENARIOS)}")
    return names
