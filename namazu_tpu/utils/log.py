"""Logging setup.

Parity with /root/reference/nmz/util/log/logutil.go: per-run log file plus
stderr, debug gated on the ``NMZ_TPU_DEBUG`` environment variable.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_INITIALIZED = False


def init_log(log_file: Optional[str] = None, debug: Optional[bool] = None) -> logging.Logger:
    global _INITIALIZED
    root = logging.getLogger("namazu_tpu")
    if debug is None:
        debug = os.environ.get("NMZ_TPU_DEBUG", "") not in ("", "0", "false")
    root.setLevel(logging.DEBUG if debug else logging.INFO)
    fmt = logging.Formatter(
        "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"
    )
    if not _INITIALIZED:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(fmt)
        root.addHandler(h)
        _INITIALIZED = True
    if log_file:
        fh = logging.FileHandler(log_file)
        fh.setFormatter(fmt)
        root.addHandler(fh)
    return root


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"namazu_tpu.{name}")
