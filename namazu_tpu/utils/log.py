"""Logging setup.

Parity with /root/reference/nmz/util/log/logutil.go: per-run log file plus
stderr, debug gated on the ``NMZ_TPU_DEBUG`` environment variable.

Every line is tagged with the active **run id** (``[run-id]``), pushed
here by the flight recorder (``namazu_tpu/obs/recorder.py begin_run``)
and by the orchestrator lifecycle — the one key logs, metrics, and
per-run traces (``GET /traces/<run_id>``) all join on. Outside a run the
tag renders as ``[-]``. The tag is injected by a logging.Filter on each
handler (filters on a logger do not propagate to child loggers'
records; handler filters see everything).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_INITIALIZED = False

_FORMAT = "%(asctime)s %(levelname).1s [%(run_id)s] %(name)s: %(message)s"

# process-global: one `run` process serves one experiment run, and every
# worker thread (hub, orchestrator loops, policy workers, REST handlers)
# belongs to it — a contextvar would NOT propagate to those threads
_run_id = "-"


def set_run_id(run_id: Optional[str]) -> None:
    """Tag subsequent log lines (and trace/metric correlation) with
    ``run_id``; None clears back to the idle tag."""
    global _run_id
    _run_id = run_id or "-"


def get_run_id() -> str:
    """The active run id, or "-" outside a run."""
    return _run_id


class _RunIdFilter(logging.Filter):
    """Injects ``record.run_id`` so the formatter can always render it
    (records from threads that predate set_run_id included)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.run_id = _run_id
        return True


def _make_handler(cls, *args) -> logging.Handler:
    h = cls(*args)
    h.setFormatter(logging.Formatter(_FORMAT, "%H:%M:%S"))
    h.addFilter(_RunIdFilter())
    return h


def init_log(log_file: Optional[str] = None, debug: Optional[bool] = None) -> logging.Logger:
    global _INITIALIZED
    root = logging.getLogger("namazu_tpu")
    if debug is None:
        debug = os.environ.get("NMZ_TPU_DEBUG", "") not in ("", "0", "false")
    root.setLevel(logging.DEBUG if debug else logging.INFO)
    if not _INITIALIZED:
        root.addHandler(_make_handler(logging.StreamHandler, sys.stderr))
        _INITIALIZED = True
    if log_file:
        root.addHandler(_make_handler(logging.FileHandler, log_file))
    return root


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"namazu_tpu.{name}")
