"""sched_setattr(2) via ctypes — no compiled extension needed.

Parity with the reference's go-linuxsched dependency (used by
/root/reference/nmz/inspector/proc/proc.go:148-172): apply per-thread
scheduler attributes (policy, nice, RT priority, DEADLINE runtime/period)
produced by the proc sub-policies.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import platform
from typing import Any, Dict

# scheduling policies, linux/sched.h
SCHED_NORMAL = 0
SCHED_FIFO = 1
SCHED_RR = 2
SCHED_BATCH = 3
SCHED_IDLE = 5
SCHED_DEADLINE = 6

POLICY_BY_NAME = {
    "SCHED_NORMAL": SCHED_NORMAL,
    "SCHED_OTHER": SCHED_NORMAL,
    "SCHED_FIFO": SCHED_FIFO,
    "SCHED_RR": SCHED_RR,
    "SCHED_BATCH": SCHED_BATCH,
    "SCHED_IDLE": SCHED_IDLE,
    "SCHED_DEADLINE": SCHED_DEADLINE,
}

# __NR_sched_setattr per architecture (asm/unistd.h)
_SYSCALL_NR = {
    "x86_64": 314,
    "aarch64": 274,
    "arm": 380,
    "ppc64le": 355,
    "s390x": 345,
    "riscv64": 274,
}


class SchedAttr(ctypes.Structure):
    _fields_ = [
        ("size", ctypes.c_uint32),
        ("sched_policy", ctypes.c_uint32),
        ("sched_flags", ctypes.c_uint64),
        ("sched_nice", ctypes.c_int32),
        ("sched_priority", ctypes.c_uint32),
        ("sched_runtime", ctypes.c_uint64),
        ("sched_deadline", ctypes.c_uint64),
        ("sched_period", ctypes.c_uint64),
    ]


class SchedError(OSError):
    pass


_libc = None


def _get_libc():
    global _libc
    if _libc is None:
        _libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                            use_errno=True)
    return _libc


def _syscall_nr() -> int:
    arch = platform.machine()
    try:
        return _SYSCALL_NR[arch]
    except KeyError:
        raise SchedError(0, f"sched_setattr syscall number unknown for {arch}")


def set_attr(tid: int, attr_dict: Dict[str, Any]) -> None:
    """Apply one attrs dict (as produced by the proc sub-policies,
    namazu_tpu/policy/proc_subpolicies.py) to thread ``tid``.

    Raises SchedError (an OSError) on failure; callers log EPERM and
    continue (parity: proc.go:162-170).
    """
    policy_name = attr_dict.get("policy", "SCHED_NORMAL")
    try:
        policy = POLICY_BY_NAME[policy_name]
    except KeyError:
        raise SchedError(errno.EINVAL, f"unknown policy {policy_name!r}")

    attr = SchedAttr()
    attr.size = ctypes.sizeof(SchedAttr)
    attr.sched_policy = policy
    attr.sched_flags = 0
    attr.sched_nice = int(attr_dict.get("nice", 0))
    attr.sched_priority = int(attr_dict.get("rt_priority", 0))
    if policy == SCHED_DEADLINE:
        attr.sched_runtime = int(attr_dict.get("runtime_ns", 0))
        attr.sched_deadline = int(attr_dict.get("deadline_ns", 0))
        attr.sched_period = int(attr_dict.get("period_ns", 0))

    libc = _get_libc()
    res = libc.syscall(_syscall_nr(), tid, ctypes.byref(attr), 0)
    if res != 0:
        e = ctypes.get_errno()
        raise SchedError(e, f"sched_setattr(tid={tid}, {policy_name}): "
                            f"{os.strerror(e)}")


def reset_to_normal(tid: int) -> None:
    set_attr(tid, {"policy": "SCHED_NORMAL", "nice": 0})
