"""Crash-safe file writes.

A ``kill -9`` (or power loss) in the middle of a plain ``open(...,
"w")``/``json.dump`` leaves a torn file: half a JSON document where
``storage.json`` or ``result.json`` used to be, which then poisons every
later ``load_storage`` / analytics pass over the experiment. All
persistent JSON in the storage layer goes through :func:`atomic_write`
instead: write a sibling temp file, ``fsync`` it, ``os.replace`` onto
the destination (atomic on POSIX within one filesystem), then best-
effort ``fsync`` the directory so the rename itself survives a crash.

The observable contract: at every instant the destination path either
holds the complete previous content or the complete new content — never
a prefix of the new one.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

from namazu_tpu import chaos


def atomic_write(path: str, data: bytes) -> None:
    """Atomically replace ``path``'s content with ``data``."""
    path = os.path.abspath(path)
    dir_path = os.path.dirname(path)
    # the temp file must live in the same directory: os.replace is only
    # atomic within one filesystem
    fd, tmp = tempfile.mkstemp(
        dir=dir_path, prefix=os.path.basename(path) + ".", suffix=".tmp")
    # chaos seam (doc/robustness.md): a torn tmp simulates a hard kill
    # mid-write — half the payload lands, NOTHING is cleaned up, and the
    # stray .tmp is exactly what `tools fsck` exists to sweep
    if chaos.decide("storage.tear") is not None:
        try:
            os.write(fd, data[: max(1, len(data) // 2)])
        finally:
            os.close(fd)
        raise OSError(f"chaos: write torn mid-flight (left {tmp})")
    try:
        try:
            os.write(fd, data)
            # chaos seam: a failed fsync (ENOSPC/EIO class) before the
            # rename — the destination must stay untouched
            if chaos.decide("storage.fsync") is not None:
                raise OSError("chaos: injected fsync failure")
            os.fsync(fd)
        finally:
            os.close(fd)
        if chaos.decide("storage.rename") is not None:
            raise OSError("chaos: injected rename failure")
        os.replace(tmp, path)
    except BaseException:
        # failed before the rename landed: the destination is untouched;
        # don't leave the orphan temp behind (fsck also sweeps strays
        # left by a hard kill, where this handler never runs)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(dir_path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write(path, text.encode())


def atomic_write_json(path: str, obj: Any, **dump_kw) -> None:
    atomic_write(path, json.dumps(obj, **dump_kw).encode())


def _fsync_dir(dir_path: str) -> None:
    """Persist a directory entry (the rename) to disk; best effort —
    some filesystems refuse O_RDONLY directory fsync."""
    try:
        fd = os.open(dir_path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


#: suffix every in-flight atomic write carries; ``tools fsck`` sweeps
#: orphans a hard kill left behind
TMP_SUFFIX = ".tmp"


def is_tmp_artifact(name: str) -> bool:
    return name.endswith(TMP_SUFFIX)
