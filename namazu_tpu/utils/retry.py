"""Bounded retry with capped exponential backoff + jitter.

One policy for every transient-failure path in the stack — the REST
transceiver's event POST, the campaign runner's infra-failure retries —
so "how long do we keep trying" is tuned in one place. Full jitter
(delay drawn uniformly from ``[0, min(cap, base * 2**attempt)]``)
decorrelates retriers: N inspectors that lost the orchestrator at the
same instant must not all re-knock at the same instant too.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


def backoff_delays(
    attempts: int,
    base: float = 0.5,
    cap: float = 10.0,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Yield up to ``attempts`` full-jitter backoff delays (seconds)."""
    rng = rng or random.Random()
    for attempt in range(attempts):
        yield rng.uniform(0.0, min(cap, base * (2.0 ** attempt)))


def retry_call(
    fn: Callable[[], T],
    exceptions: Tuple[Type[BaseException], ...],
    attempts: int = 4,
    base: float = 0.5,
    cap: float = 10.0,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    delay_hint: Optional[Callable[[BaseException],
                                  Optional[float]]] = None,
) -> T:
    """Call ``fn`` up to ``attempts`` times, backing off between tries.

    Only ``exceptions`` are retried; anything else propagates at once,
    as does the final failure. ``on_retry(exc, attempt, delay)`` runs
    before each backoff sleep (logging hook). ``sleep`` is injectable so
    tests and interruptible callers (e.g. a transceiver whose stop event
    doubles as the sleeper) control the wait.

    ``delay_hint(exc)`` lets the failure itself suggest the wait — a
    server's ``Retry-After`` on a 429 (doc/robustness.md). A returned
    hint replaces the drawn backoff: never LESS than the hint
    (re-knocking early would burn an attempt on a refusal the server
    already announced), jittered up to +25% so a whole fleet told
    "come back in 1s" does not re-knock in one synchronized wave, and
    capped at ``cap`` last; ``None`` keeps the normal backoff.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    rng = rng or random.Random()
    delays = backoff_delays(attempts - 1, base=base, cap=cap, rng=rng)
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            attempt += 1
            try:
                delay = next(delays)
            except StopIteration:
                raise e from None
            hint = delay_hint(e) if delay_hint is not None else None
            if hint is not None and hint >= 0:
                delay = min(cap, float(hint) * rng.uniform(1.0, 1.25))
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)
