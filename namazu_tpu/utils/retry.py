"""Bounded retry with capped exponential backoff + jitter.

One policy for every transient-failure path in the stack — the REST
transceiver's event POST, the campaign runner's infra-failure retries —
so "how long do we keep trying" is tuned in one place. Full jitter
(delay drawn uniformly from ``[0, min(cap, base * 2**attempt)]``)
decorrelates retriers: N inspectors that lost the orchestrator at the
same instant must not all re-knock at the same instant too.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


def backoff_delays(
    attempts: int,
    base: float = 0.5,
    cap: float = 10.0,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Yield up to ``attempts`` full-jitter backoff delays (seconds)."""
    rng = rng or random.Random()
    for attempt in range(attempts):
        yield rng.uniform(0.0, min(cap, base * (2.0 ** attempt)))


def retry_call(
    fn: Callable[[], T],
    exceptions: Tuple[Type[BaseException], ...],
    attempts: int = 4,
    base: float = 0.5,
    cap: float = 10.0,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
) -> T:
    """Call ``fn`` up to ``attempts`` times, backing off between tries.

    Only ``exceptions`` are retried; anything else propagates at once,
    as does the final failure. ``on_retry(exc, attempt, delay)`` runs
    before each backoff sleep (logging hook). ``sleep`` is injectable so
    tests and interruptible callers (e.g. a transceiver whose stop event
    doubles as the sleeper) control the wait.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delays = backoff_delays(attempts - 1, base=base, cap=cap, rng=rng)
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            attempt += 1
            try:
                delay = next(delays)
            except StopIteration:
                raise e from None
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)
