"""MockOrchestrator: echoes every event's default action, no policy.

Parity: /root/reference/nmz/util/mockorchestrator/mockorchestrator.go:20-105.
Used to test inspectors and endpoints in isolation.
"""

from __future__ import annotations

import queue
import threading

from namazu_tpu.endpoint.hub import EndpointHub
from namazu_tpu.signal.event import Event

_STOP = object()


class MockOrchestrator:
    def __init__(self, hub: EndpointHub):
        self.hub = hub
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self.hub.start()
        self._thread = threading.Thread(target=self._loop, name="mock-orc", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            ev = self.hub.event_queue.get()
            if ev is _STOP:
                return
            assert isinstance(ev, Event)
            action = ev.default_action()
            action.mark_triggered()
            if not action.orchestrator_side_only:
                self.hub.send_action(action)

    def shutdown(self) -> None:
        self.hub.event_queue.put(_STOP)  # type: ignore[arg-type]
        if self._thread:
            self._thread.join(timeout=5)
        self.hub.shutdown()
