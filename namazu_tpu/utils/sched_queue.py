"""ScheduledQueue: the time-bounded delay queue at the heart of fuzzing.

Capability parity with the reference's TimeBoundedQueue
(/root/reference/nmz/util/queue/impl.go:70-135): each enqueued item carries a
``[min_delay, max_delay]`` bound; the queue emits it after a delay drawn
uniformly from that interval. Permuting concurrent delays is what produces
the adversarial interleavings.

Redesigned mechanism: instead of racing one goroutine timer per item (the
reference's approach, impl.go:110-124), a single scheduler thread drains a
heap keyed by ``(release_time, sequence_number)``. This preserves the two
invariants the reference's tests pin down:

* items with equal bounds keep FIFO order (equal release offsets =>
  sequence-number tiebreak; reference: the ordered InfiniteChannel path,
  impl.go:70-93);
* items with unequal bounds interleave randomly within their windows.

A deterministic ``random.Random`` seeded per-queue makes the *sampled
delays* reproducible under a fixed seed (the reference cannot: its
interleavings come from Go runtime timer races). The realized interleaving
is exactly reproducible whenever distinct items' delays differ by more than
scheduling jitter — which deterministic replay guarantees by using
ms-granular ``put_at`` delays.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from typing import Any, Optional, Tuple

from namazu_tpu import obs
from namazu_tpu.utils import timesource


class QueueClosed(Exception):
    """Raised by get() once the queue is closed and drained."""


class ScheduledQueue:
    def __init__(self, seed: Optional[int] = None, time_scale: float = 1.0,
                 obs_name: str = "",
                 time_source: Optional[timesource.TimeSource] = None):
        """``time_scale`` < 1 compresses all delays (useful in tests).
        ``obs_name`` labels this queue's depth gauge and realized-wait
        histogram in the metrics registry ("" = uninstrumented).
        ``time_source`` is the clock release times are computed and
        waited against (default: the process TimeSource) — under a
        :class:`~namazu_tpu.utils.timesource.VirtualTimeSource` the
        blocked consumer's earliest deadline becomes the fast-forward
        coordinator's jump target, so the queue's delays cost virtual
        seconds, not wall seconds (doc/performance.md "Virtual
        clock")."""
        self._rng = random.Random(seed)
        self._time_scale = float(time_scale)
        self._obs_name = obs_name
        self._ts = time_source if time_source is not None \
            else timesource.get()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # (release_time, seq, put_time, item); the unique seq tiebreak
        # means comparisons never reach put_time/item
        self._heap: list[Tuple[float, int, float, Any]] = []
        self._seq = itertools.count()
        self._closed = False

    def put(self, item: Any, min_delay: float = 0.0, max_delay: float = 0.0) -> None:
        """Enqueue ``item`` to be released after a delay in
        ``[min_delay, max_delay]`` seconds."""
        if max_delay < min_delay:
            raise ValueError(f"max_delay {max_delay} < min_delay {min_delay}")
        if min_delay == max_delay:
            delay = min_delay
        else:
            delay = self._rng.uniform(min_delay, max_delay)
        now = self._ts.now()
        release = now + delay * self._time_scale
        with self._cond:
            if self._closed:
                raise QueueClosed
            heapq.heappush(self._heap, (release, next(self._seq), now, item))
            self._cond.notify()
            if self._obs_name:
                # published under _cond, like get()'s: an unlocked
                # publish could overwrite a newer depth with a stale one
                obs.sched_queue_depth(self._obs_name, len(self._heap))

    def put_at(self, item: Any, delay: float) -> None:
        """Enqueue with an exact delay (used by deterministic replay)."""
        self.put(item, delay, delay)

    def put_many(self, entries) -> None:
        """Enqueue a batch of ``(item, min_delay, max_delay)`` triples
        under ONE condition-lock acquisition and ONE wakeup — the
        event-plane batch path's per-event cost is a heap push, not a
        lock round trip. Delay sampling matches :meth:`put` exactly
        (same RNG, same draw order), so a batch of equal-bound items
        keeps FIFO order by sequence number like sequential puts
        would."""
        entries = list(entries)
        if not entries:
            return
        sampled = []
        for item, min_delay, max_delay in entries:
            if max_delay < min_delay:
                raise ValueError(
                    f"max_delay {max_delay} < min_delay {min_delay}")
            if min_delay == max_delay:
                sampled.append((item, min_delay))
            else:
                sampled.append((item, self._rng.uniform(min_delay,
                                                        max_delay)))
        now = self._ts.now()
        with self._cond:
            if self._closed:
                raise QueueClosed
            for item, delay in sampled:
                heapq.heappush(
                    self._heap,
                    (now + delay * self._time_scale, next(self._seq),
                     now, item))
            self._cond.notify()
            if self._obs_name:
                obs.sched_queue_depth(self._obs_name, len(self._heap))

    def put_at_many(self, pairs) -> None:
        """Batch :meth:`put_at`: ``(item, exact_delay)`` pairs, one lock
        acquisition (the deterministic-replay side of put_many)."""
        self.put_many((item, delay, delay) for item, delay in pairs)

    def get(self, timeout: Optional[float] = None) -> Any:
        """Block until the earliest item's release time passes; return it.

        Raises :class:`QueueClosed` when the queue is closed and empty, and
        :class:`TimeoutError` on timeout.
        """
        return self.get_batch(1, timeout)[0]

    def get_batch(self, max_n: int,
                  timeout: Optional[float] = None) -> list:
        """Block like :meth:`get` for the first ripe item, then return
        every ALREADY-ripe item up to ``max_n``, in release order — the
        consumer's side of the batch fast path: a burst of zero/equal-
        delay releases crosses the queue in one lock acquisition instead
        of one wakeup per item. Never waits for more items once one is
        ripe, so batching cannot delay a release."""
        max_n = max(1, max_n)
        deadline = None if timeout is None else self._ts.now() + timeout
        with self._cond:
            while True:
                now = self._ts.now()
                if self._heap:
                    release = self._heap[0][0]
                    if release <= now:
                        items = []
                        while (self._heap and len(items) < max_n
                               and self._heap[0][0] <= now):
                            _, _, put_ts, item = heapq.heappop(self._heap)
                            if self._obs_name:
                                # metric locks are leaves; safe under
                                # _cond
                                obs.sched_queue_wait(self._obs_name,
                                                     now - put_ts)
                            items.append(item)
                        if self._obs_name:
                            obs.sched_queue_depth(self._obs_name,
                                                  len(self._heap))
                        return items
                    wait = release - now
                elif self._closed:
                    raise QueueClosed
                else:
                    wait = None
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        raise TimeoutError
                    wait = remaining if wait is None else min(wait, remaining)
                # under a virtual source this registers the deadline
                # with the fast-forward coordinator and is woken by a
                # jump; under wall time it IS Condition.wait
                self._ts.wait(self._cond, wait)

    def expedite(self, predicate, collect: bool = False):
        """Make every resident item with ``predicate(item)`` true ripe
        immediately (FIFO among themselves by sequence number); returns
        how many were expedited — or, with ``collect``, the expedited
        items themselves (enqueue order), so the caller can attribute
        the forced release (the watchdog stamps each event's flight-
        recorder decision with ``source="watchdog"``). The liveness
        watchdog's lever: events parked on behalf of an entity declared
        dead are released now so their actions (and the trace) do not
        wait out delays nobody will ever observe."""
        with self._cond:
            changed = []
            heap = []
            for (release, seq, put_ts, item) in self._heap:
                if predicate(item):
                    release = 0.0
                    changed.append((seq, item))
                heap.append((release, seq, put_ts, item))
            if changed:
                self._heap = heap
                heapq.heapify(self._heap)
                self._cond.notify_all()
            if collect:
                return [item for _, item in sorted(changed)]
            return len(changed)

    def reseed(self, seed: Optional[int]) -> None:
        """Reset the delay-sampling RNG (used when a policy's config sets a
        seed after the queue was constructed)."""
        with self._cond:
            self._rng = random.Random(seed)

    def close(self, immediate: bool = False) -> None:
        """Stop accepting puts. With ``immediate``, pending items become
        ripe now (in FIFO order by sequence number) so a shutdown can flush
        the queue without waiting out the remaining delays."""
        with self._cond:
            self._closed = True
            if immediate and self._heap:
                self._heap = [(0.0, seq, put_ts, item)
                              for (_, seq, put_ts, item) in self._heap]
                heapq.heapify(self._heap)
            self._cond.notify_all()

    def drain_remaining(self) -> list:
        """Remove and return every still-resident item (FIFO by enqueue
        order), regardless of ripeness. Shutdown path only: lets the
        owner account for items its dequeue worker never released (e.g.
        record their queue-dwell) instead of dropping them silently."""
        with self._cond:
            items = [item for (_, _, _, item) in sorted(self._heap,
                                                        key=lambda e: e[1])]
            self._heap = []
            if self._obs_name and items:
                obs.sched_queue_depth(self._obs_name, 0)
            self._cond.notify_all()
            return items

    def earliest_release(self) -> Optional[float]:
        """The head item's release time in the queue's TimeSource
        domain (None when empty) — the discrete-event fast-forward
        target a quiescent virtual clock jumps to."""
        with self._lock:
            return self._heap[0][0] if self._heap else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)
