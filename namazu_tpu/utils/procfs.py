"""procfs walking helpers.

Parity: /root/reference/nmz/util/proc/procutil.go:28-111 — enumerate a
process's light-weight processes (threads), children, and the transitive
descendant LWP set, straight from /proc.
"""

from __future__ import annotations

import os
from typing import List, Set


def lwps(pid: int) -> List[int]:
    """Thread ids of ``pid`` (parity: LWPs, procutil.go:28-43)."""
    task_dir = f"/proc/{pid}/task"
    try:
        return sorted(int(t) for t in os.listdir(task_dir) if t.isdigit())
    except (FileNotFoundError, PermissionError):
        return []


def children(pid: int) -> List[int]:
    """Direct children (parity: Children, procutil.go:45-65)."""
    out: Set[int] = set()
    for tid in lwps(pid):
        path = f"/proc/{pid}/task/{tid}/children"
        try:
            with open(path) as f:
                out.update(int(c) for c in f.read().split())
        except (FileNotFoundError, PermissionError, ProcessLookupError):
            continue
    return sorted(out)


def descendants(pid: int, max_depth: int = 64) -> List[int]:
    """Transitive children, excluding ``pid`` itself
    (parity: Descendants, procutil.go:67-87)."""
    seen: Set[int] = set()
    frontier = [pid]
    for _ in range(max_depth):
        nxt: List[int] = []
        for p in frontier:
            for c in children(p):
                if c not in seen:
                    seen.add(c)
                    nxt.append(c)
        if not nxt:
            break
        frontier = nxt
    return sorted(seen)


def descendant_lwps(pid: int) -> List[int]:
    """All LWPs of ``pid`` and of every descendant — the full thread set
    the scheduler fuzzer perturbs (parity: DescendantLWPs,
    procutil.go:89-111)."""
    out: Set[int] = set(lwps(pid))
    for d in descendants(pid):
        out.update(lwps(d))
    return sorted(out)
