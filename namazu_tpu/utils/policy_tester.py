"""Reusable policy test harness.

Parity: /root/reference/nmz/util/explorepolicytester/explorepolicytester.go:
32-68 — pump N packet events across K entities through any policy, both
sequentially and concurrently (deadlock-freedom), and collect the answering
actions.
"""

from __future__ import annotations

import queue
import threading
from typing import List

from namazu_tpu.policy.base import ExplorePolicy
from namazu_tpu.signal.action import Action
from namazu_tpu.signal.event import PacketEvent


def make_packet_events(n: int, entities: int) -> List[PacketEvent]:
    return [
        PacketEvent.create(
            f"entity-{i % entities}",
            src_entity=f"entity-{i % entities}",
            dst_entity=f"entity-{(i + 1) % entities}",
            hint=f"test:{i}",
        )
        for i in range(n)
    ]


def drain_actions(policy: ExplorePolicy, n: int, timeout: float = 30.0) -> List[Action]:
    out: List[Action] = []
    while len(out) < n:
        item = policy.action_out.get(timeout=timeout)
        # action_out items are one Action or a released burst (list) —
        # policy/base.py ExplorePolicy contract
        out.extend(item if isinstance(item, list) else [item])
    return out


def pump_sequential(policy: ExplorePolicy, n: int, entities: int = 3) -> List[Action]:
    """Send one event, await its action, repeat."""
    actions: List[Action] = []
    for ev in make_packet_events(n, entities):
        policy.queue_event(ev)
        actions.extend(drain_actions(policy, 1))
    return actions


def pump_concurrent(policy: ExplorePolicy, n: int, entities: int = 3) -> List[Action]:
    """Send all events before receiving any action (ShouldNotBlock)."""
    events = make_packet_events(n, entities)
    collected: "queue.Queue[Action]" = queue.Queue()

    def collector() -> None:
        got = 0
        while got < n:
            item = policy.action_out.get(timeout=30.0)
            for action in (item if isinstance(item, list) else [item]):
                collected.put(action)
                got += 1

    t = threading.Thread(target=collector, daemon=True)
    t.start()
    for ev in events:
        policy.queue_event(ev)
    t.join(timeout=60.0)
    assert not t.is_alive(), "policy deadlocked: actions not delivered"
    return [collected.get_nowait() for _ in range(n)]
