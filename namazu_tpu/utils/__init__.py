"""Shared utilities: scheduling queue, config, logging, traces, commands."""
