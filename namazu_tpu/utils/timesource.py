"""TimeSource: the one clock the scheduling planes read.

Every campaign today buys reproductions with wall-clock seconds: the
delay queue's ``[min_delay, max_delay]`` windows are real sleeps, so
repros/hour — the north-star unit (RESULTS.md) — is bounded by delays
the orchestrator itself scheduled. Namazu's premise is that the
orchestrator already owns nondeterminism (inspectors park events,
policies decide release order); this module extends that ownership to
TIME (doc/performance.md "Virtual clock"):

* :class:`WallTimeSource` — the default. ``now()`` IS
  ``time.monotonic()`` and ``wait()`` IS ``Condition.wait()``; a
  process that never opts in behaves byte-identically to the
  pre-TimeSource code.
* :class:`VirtualTimeSource` — virtual monotonic = real monotonic + a
  jumpable offset. Between jumps the virtual clock advances at wall
  rate (so a ``cond.wait(remaining)`` computed in virtual seconds is
  EXACT), and a **discrete-event fast-forward** jumps the offset to
  the earliest parked deadline the moment nothing real is left to
  wait for: when every registered waiter (a :class:`ScheduledQueue`
  blocked on its heap's head) and every interposed entity (the epoch
  page's slots, :mod:`namazu_tpu.vclock`) is parked, the busy probes
  (orchestrator queues) are idle, and nobody holds a pin, the
  coordinator jumps the clock to the earliest deadline instead of
  sleeping through it.

The safety valve (the "pinning rule"): any activity OUTSIDE the
virtualized waits keeps the clock at wall rate — a nonzero pin count,
a busy probe reporting work in flight, or an epoch-page entity slot in
the *running* state (an interposed process doing real I/O between
hooked waits) all veto the jump. Fast-forward therefore never races an
un-virtualized wait; at worst it degrades to exactly the wall-clock
behavior it replaced.

Consumers reach the process default through :func:`get` /
:func:`install`; liveness watchdogs, tenancy lease TTLs, and campaign
phase deadlines all read the SAME source as the delay queue, so a
10x fast-forward cannot declare healthy entities stalled or expire
live leases (doc/performance.md "Virtual clock").
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "TimeSource", "WallTimeSource", "VirtualTimeSource",
    "get", "install", "reset",
]


class TimeSource:
    """The clock interface the scheduling planes program against."""

    #: virtual sources override; consumers branch on this to register
    #: busy probes / pins without importing the concrete class
    is_virtual = False

    def now(self) -> float:
        """Monotonic seconds in this source's time domain."""
        raise NotImplementedError

    def wall(self) -> float:
        """Real CLOCK_MONOTONIC seconds, always — for cost accounting
        (how long did this actually take) regardless of virtualization."""
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def wait(self, cond: threading.Condition, timeout: Optional[float]
             ) -> bool:
        """``cond.wait(timeout)`` with ``timeout`` denominated in THIS
        source's seconds. The caller holds ``cond``; returns like
        ``Condition.wait``. Virtual sources register the wait so the
        fast-forward coordinator can see the deadline and wake the
        waiter after a jump."""
        raise NotImplementedError


class WallTimeSource(TimeSource):
    """Real time. Deliberately nothing but pass-throughs: installing
    this source (the default) must be byte-identical to the
    pre-TimeSource behavior."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    def wait(self, cond: threading.Condition,
             timeout: Optional[float]) -> bool:
        return cond.wait(timeout)


class VirtualTimeSource(TimeSource):
    """Virtual monotonic time with discrete-event fast-forward.

    ``now() = time.monotonic() + offset``. The offset only ever grows
    (virtual time is still monotonic) and only via :meth:`advance` /
    the coordinator's :meth:`maybe_jump`, which requires total
    quiescence: no pins, idle busy probes, every epoch-page entity
    parked. Waiters registered through :meth:`wait` are notified after
    every jump so a blocked ``ScheduledQueue`` re-evaluates ripeness
    immediately.
    """

    is_virtual = True

    #: coordinator cadence, and the largest real sleep a quiescence
    #: double-check inserts — small enough that a jump opportunity is
    #: never missed by much, large enough to stay invisible in profiles
    QUANTUM_S = 0.002
    #: the double-check gap before a small jump: long enough to cover
    #: an event in flight between two probed queues (an HTTP body
    #: mid-parse is ~100-200us on loopback), short enough that it is
    #: not the dominant per-jump cost — which it would be at QUANTUM_S,
    #: since futex wakes make everything else on the jump path
    #: microseconds
    CONFIRM_GAP_S = 0.0003
    #: cadence right after a successful jump, while a chain of closely
    #: spaced deadlines is draining (a woken entity re-parks within
    #: microseconds of its futex wake; waiting a full quantum to look
    #: again would triple the per-jump cost)
    DRAIN_CADENCE_S = 0.0001
    #: how many post-jump attempts keep the drain cadence — a woken
    #: entity needs ~0.5-1ms of scheduling to run its loop body and
    #: re-park, during which attempts veto; falling back to QUANTUM_S
    #: on the first such veto would forfeit the fast cadence exactly
    #: when the next deadline of the chain is about to appear (the
    #: window still totals ~2ms of wall time, it is just sliced finer)
    DRAIN_ROUNDS = 20
    #: jumps shorter than this ripen naturally before a waiter could
    #: even be notified; skip them
    MIN_JUMP_S = 0.001
    #: jumps overshoot the earliest deadline by this much — the same
    #: oversleep jitter a wall-rate nanosleep exhibits (sleep(2) means
    #: "at least", and the OS routinely adds 1-5ms), so semantics are
    #: unchanged, but deadlines CLUSTERED within the slack (three
    #: nodes' 20ms poll loops) ripen on one jump instead of three
    JUMP_SLACK_S = 0.002
    #: jumps past this need sustained quiescence: a thread that was
    #: just woken (SIGCHLD delivered, data arrived) still LOOKS parked
    #: until the scheduler runs it, and a big jump taken inside that
    #: few-ms window would fast-forward to some far-out watchdog or
    #: long-poll deadline the wall-rate run would never reach
    BIG_JUMP_S = 1.0
    #: extra confirmation rounds (QUANTUM_S apart) for big jumps —
    #: ~20ms of sustained quiescence, well past scheduler wake latency
    BIG_JUMP_CONFIRMS = 10

    def __init__(self, epoch_page=None, min_entities: int = 0) -> None:
        self._lock = threading.Lock()
        self._offset = 0.0
        self._pins = 0
        self._waiters: Dict[object, Tuple[threading.Condition,
                                          Optional[float]]] = {}
        self._probes: List[Callable[[], bool]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: optional shared-memory epoch page (namazu_tpu.vclock): the
        #: interposed entities' park/run states and the C-visible face
        #: of the offset
        self.epoch_page = epoch_page
        #: jumps are vetoed until this many entity slots are claimed —
        #: guards the window between spawning interposed children and
        #: their first hooked call (config ``vclock_min_entities``)
        self.min_entities = int(min_entities)
        self.started_wall = time.monotonic()
        #: virtual seconds skipped by jumps (the fast-forward win)
        self.jumped_s = 0.0
        #: wall seconds spent with the clock pinned to wall rate
        self.pinned_s = 0.0
        self.jumps = 0
        #: why jump attempts were vetoed, by pinning-rule clause — the
        #: first diagnostic to read when a campaign's speedup is ~1x
        #: (e.g. entity_running dominating means an interposed thread
        #: blocks in an un-hooked call)
        self.veto_counts: Dict[str, int] = {}

    # -- the clock --------------------------------------------------------

    def now(self) -> float:
        with self._lock:
            return time.monotonic() + self._offset

    def sleep(self, seconds: float) -> None:
        """Virtual-aware sleep: park on a private condition until the
        virtual deadline passes (a jump wakes it early)."""
        if seconds <= 0:
            return
        cond = threading.Condition()
        deadline = self.now() + seconds
        with cond:
            while True:
                remaining = deadline - self.now()
                if remaining <= 0:
                    return
                self.wait(cond, remaining)

    def wait(self, cond: threading.Condition,
             timeout: Optional[float]) -> bool:
        """Registered condition wait. Between jumps the virtual clock
        advances at wall rate, so ``cond.wait(timeout)`` with a
        virtual-second timeout is exact; a jump notifies ``cond`` (the
        coordinator holds the cond lock to do so, which a registered
        waiter has released by definition), after which the caller's
        wait loop recomputes its deadline against the jumped clock."""
        key = object()
        deadline = None if timeout is None else self.now() + timeout
        with self._lock:
            self._waiters[key] = (cond, deadline)
        try:
            return cond.wait(timeout)
        finally:
            with self._lock:
                self._waiters.pop(key, None)

    # -- the pinning rule -------------------------------------------------

    def pin(self) -> None:
        """Veto fast-forward until :meth:`unpin` — the explicit face of
        the safety valve (e.g. a run script still booting its
        interposed children). Pinned wall seconds are accounted by the
        coordinator loop (every non-jumping quantum is a pinned
        quantum), so explicit pins and implicit ones (busy probes,
        running entities) land in the same ``pinned_s`` total."""
        with self._lock:
            self._pins += 1

    def unpin(self) -> None:
        with self._lock:
            self._pins = max(0, self._pins - 1)

    class _Pinned:
        def __init__(self, ts: "VirtualTimeSource") -> None:
            self._ts = ts

        def __enter__(self):
            self._ts.pin()
            return self._ts

        def __exit__(self, *exc):
            self._ts.unpin()
            return False

    def pinned(self) -> "VirtualTimeSource._Pinned":
        return VirtualTimeSource._Pinned(self)

    def add_busy_probe(self, probe: Callable[[], bool]) -> None:
        """Register a work-in-flight probe (True = busy). The
        orchestrator registers its event/action queues so a jump can
        never overtake an event already inbound but not yet parked."""
        with self._lock:
            self._probes.append(probe)

    # -- jumping ----------------------------------------------------------

    def advance(self, seconds: float) -> None:
        """Unconditionally advance the virtual clock (tests, and the
        one primitive :meth:`maybe_jump` is built on)."""
        if seconds <= 0:
            return
        with self._lock:
            self._offset += seconds
            self.jumped_s += seconds
            self.jumps += 1
            waiters = list(self._waiters.values())
        page = self.epoch_page
        if page is not None:
            page.publish(self._offset)
        for cond, _ in waiters:
            with cond:
                cond.notify_all()

    def _quiescent_target(self) -> Tuple[Optional[float], Optional[str]]:
        """``(earliest_parked_deadline, None)`` IF the system is
        totally quiescent, else ``(None, veto_reason)``. One pass of
        the pinning rule; the reason names the vetoing clause."""
        with self._lock:
            if self._pins > 0:
                return None, "pinned"
            probes = list(self._probes)
            deadlines = [d for _, d in self._waiters.values()
                         if d is not None]
        for probe in probes:
            try:
                if probe():
                    return None, "probe_busy"
            except Exception:  # pragma: no cover - defensive
                return None, "probe_busy"
        page = self.epoch_page
        if page is not None:
            all_parked, entity_deadline, claimed = page.parked_state()
            if claimed < self.min_entities:
                return None, "entities_below_min"
            if not all_parked:
                return None, "entity_running"
            if entity_deadline is not None:
                deadlines.append(entity_deadline)
        elif self.min_entities > 0:
            return None, "entities_below_min"
        if not deadlines:
            return None, "nothing_parked"
        return min(deadlines), None

    def _veto(self, reason: str) -> float:
        self.veto_counts[reason] = self.veto_counts.get(reason, 0) + 1
        return 0.0

    def maybe_jump(self) -> float:
        """One fast-forward attempt; returns the virtual seconds
        skipped (0.0 when the pinning rule vetoed or nothing is
        parked). Quiescence is sampled twice, ``CONFIRM_GAP_S`` apart,
        and the jump happens only if both passes agree on a target —
        the double-check closes the window where an event is in flight
        between two probed queues. (The coordinator loop pipelines the
        two samples across ticks instead of sleeping inline — same
        protocol, no extra sleep on the steady-state jump path.)"""
        target, veto = self._quiescent_target()
        if target is None:
            return self._veto(veto)
        time.sleep(self.CONFIRM_GAP_S)
        confirm, veto = self._quiescent_target()
        if confirm is None:
            return self._veto(veto)
        return self._commit(min(target, confirm))

    def _commit(self, target: float) -> float:
        """Second half of a jump, after two quiescent sightings agreed
        on ``target``: big jumps take extra sustained-quiescence
        rounds, chaos seams may stall or skew, then the clock
        advances."""
        delta = target - self.now()
        if delta <= self.MIN_JUMP_S:
            return 0.0
        if delta > self.BIG_JUMP_S:
            for _ in range(self.BIG_JUMP_CONFIRMS):
                time.sleep(self.QUANTUM_S)
                confirm, veto = self._quiescent_target()
                if confirm is None:
                    return self._veto(veto)
                target = min(target, confirm)
            delta = target - self.now()
            if delta <= self.MIN_JUMP_S:
                return 0.0
        # chaos seams on the epoch-page handshake (doc/robustness.md):
        # clock.stall skips this advance (parked entities real-sleep
        # through the window — slower, never wrong); clock.skew
        # perturbs the jump target (an over/undershoot the wait loops
        # must absorb). Imported lazily: utils must not import chaos at
        # module load.
        from namazu_tpu import chaos

        if chaos.decide("clock.stall") is not None:
            return 0.0
        skew = chaos.decide("clock.skew")
        if skew is not None:
            delta = max(self.MIN_JUMP_S,
                        delta + float(skew.get("skew_s", 0.002)))
        delta += self.JUMP_SLACK_S
        self.advance(delta)
        return delta

    # -- the coordinator --------------------------------------------------

    def start_coordinator(self) -> None:
        """Start the fast-forward thread (idempotent). It wakes every
        ``QUANTUM_S`` and jumps whenever the pinning rule allows —
        nothing else in the process needs to poll."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._coordinator_loop,
                                        name="vclock-coordinator",
                                        daemon=True)
        self._thread.start()

    def stop_coordinator(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _coordinator_loop(self) -> None:
        pinned_mark = time.monotonic()
        since_jump = self.DRAIN_ROUNDS
        # the double-check pipelined across ticks: a candidate target
        # from the previous tick, committed only if THIS tick (>= the
        # confirm gap later) still finds the system quiescent — the
        # same two-sample protocol as maybe_jump with the tick sleep
        # doubling as the confirm gap, so the steady-state jump path
        # pays no extra inline sleep
        pending: Optional[float] = None
        while True:
            if pending is not None:
                cadence = self.CONFIRM_GAP_S
            elif since_jump < self.DRAIN_ROUNDS:
                # after a jump, deadlines usually come in chains (a
                # woken entity re-parks one poll interval out within
                # ~1ms): keep looking quickly instead of sleeping a
                # full quantum between deadlines
                cadence = self.DRAIN_CADENCE_S
            else:
                cadence = self.QUANTUM_S
            if self._stop.wait(cadence):
                return
            target, veto = self._quiescent_target()
            jumped = 0.0
            if target is None:
                self._veto(veto)
                pending = None
            elif pending is not None:
                jumped = self._commit(min(target, pending))
                pending = None
            else:
                pending = target
            now_wall = time.monotonic()
            if jumped <= 0.0:
                # wall rate: the clock is pinned (probes busy, entities
                # running, or nothing parked) — account the real second
                self.pinned_s += now_wall - pinned_mark
            pinned_mark = now_wall
            since_jump = 0 if jumped > 0.0 else since_jump + 1

    # -- reading ----------------------------------------------------------

    def summary(self) -> Dict[str, float]:
        wall_elapsed = time.monotonic() - self.started_wall
        virtual_elapsed = wall_elapsed + self.jumped_s
        return {
            "wall_elapsed_s": round(wall_elapsed, 3),
            "virtual_elapsed_s": round(virtual_elapsed, 3),
            "jumped_s": round(self.jumped_s, 3),
            "pinned_s": round(self.pinned_s, 3),
            "jumps": self.jumps,
            "speedup_ratio": (round(virtual_elapsed / wall_elapsed, 2)
                              if wall_elapsed > 0 else None),
            "veto_counts": dict(self.veto_counts),
        }


# -- the process default ---------------------------------------------------

_default: TimeSource = WallTimeSource()
_install_lock = threading.Lock()


def get() -> TimeSource:
    """The process's TimeSource. Wall unless a virtual source was
    installed (``run --virtual-clock`` via :mod:`namazu_tpu.vclock`)."""
    return _default


def install(source: TimeSource) -> TimeSource:
    """Install ``source`` process-globally; returns the previous one
    (callers restore it on deactivation)."""
    global _default
    with _install_lock:
        previous = _default
        _default = source
        return previous


def reset() -> None:
    """Back to wall time (tests)."""
    install(WallTimeSource())
