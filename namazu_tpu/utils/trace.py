"""Experiment traces: the ordered action sequence of one run.

Parity: SingleTrace (/root/reference/nmz/util/trace/trace.go:25-31). Stored
as JSON (not gob): each element is the action's wire dict plus its
triggered time, so traces are directly consumable by the JAX search plane's
featurizer.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, List, Optional

from namazu_tpu.signal.action import Action
from namazu_tpu.signal.base import signal_from_jsonable


class SingleTrace:
    def __init__(self, actions: Optional[List[Action]] = None):
        self.actions: List[Action] = list(actions or [])

    def append(self, action: Action) -> None:
        self.actions.append(action)

    def __len__(self) -> int:
        return len(self.actions)

    def __iter__(self) -> Iterator[Action]:
        return iter(self.actions)

    def to_jsonable(self) -> List[Dict[str, Any]]:
        out = []
        for a in self.actions:
            d = a.to_jsonable()
            if a.triggered_time is not None:
                d["triggered_time"] = a.triggered_time
            out.append(d)
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_jsonable())

    @classmethod
    def from_jsonable(cls, items: List[Dict[str, Any]]) -> "SingleTrace":
        trace = cls()
        for d in items:
            a = signal_from_jsonable(d)
            if not isinstance(a, Action):
                raise ValueError(f"trace element is not an action: {d!r}")
            tt = d.get("triggered_time")
            if tt is not None:
                a.triggered_time = float(tt)
            trace.append(a)
        return trace

    @classmethod
    def from_json(cls, s: str) -> "SingleTrace":
        return cls.from_jsonable(json.loads(s))

    def entity_order(self) -> Dict[str, List[str]]:
        """Per-entity subsequence of event classes — the partial-order view
        used for unique-trace counting (parity: the PO-reduction in
        /root/reference/nmz/cli/tools/visualize.go:81-133)."""
        per: Dict[str, List[str]] = {}
        for a in self.actions:
            per.setdefault(a.entity_id, []).append(a.event_class or a.class_name())
        return per
