"""Minimal ELF program-header probe: can LD_PRELOAD interpose a binary?

The LD_PRELOAD fs interposer silently does nothing for statically linked
testees (the dynamic linker never runs, so the hooks never load) — which
includes Go binaries such as etcd, one of the reference's flagship
targets. The reference's FUSE backend (/root/reference/nmz/inspector/fs/
fs.go:56-74) cannot vacuously no-op like that, so the preload launcher
probes the target up front and fails loudly instead of producing a clean
zero-event run.
"""

from __future__ import annotations

import struct
from typing import Optional

PT_INTERP = 3


def has_program_interpreter(path: str) -> Optional[bool]:
    """Whether the file is an ELF executable with a PT_INTERP segment.

    ``True``  — dynamically linked: the LD_PRELOAD interposer will load.
    ``False`` — ELF without an interpreter (statically linked): LD_PRELOAD
    is silently ignored by the kernel/loader.
    ``None``  — not an ELF file (e.g. a ``#!`` script) or unreadable;
    interposability depends on what the file eventually executes.
    """
    try:
        with open(path, "rb") as f:
            ident = f.read(16)
            if len(ident) < 16 or ident[:4] != b"\x7fELF":
                return None
            ei_class, ei_data = ident[4], ident[5]
            end = "<" if ei_data == 1 else ">"
            if ei_class == 2:  # ELF64
                hdr = f.read(48)
                if len(hdr) < 42:
                    return None
                (_t, _m, _v, _entry, e_phoff, _shoff, _flags, _ehsize,
                 e_phentsize, e_phnum) = struct.unpack(
                    end + "HHIQQQIHHH", hdr[:42])
            elif ei_class == 1:  # ELF32
                hdr = f.read(36)
                if len(hdr) < 30:
                    return None
                (_t, _m, _v, _entry, e_phoff, _shoff, _flags, _ehsize,
                 e_phentsize, e_phnum) = struct.unpack(
                    end + "HHIIIIIHHH", hdr[:30])
            else:
                return None
            f.seek(e_phoff)
            for _ in range(e_phnum):
                ph = f.read(e_phentsize)
                if len(ph) < 4:
                    return None
                (p_type,) = struct.unpack(end + "I", ph[:4])
                if p_type == PT_INTERP:
                    return True
            return False
    except OSError:
        return None
