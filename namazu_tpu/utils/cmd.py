"""Experiment script execution.

Parity: /root/reference/nmz/util/cmd/cmdutil.go:27-77 — run the config's
init/run/validate/clean commands via ``sh -c`` with the working dir and
materials dir exported (reference env names NMZ_WORKING_DIR /
NMZ_MATERIALS_DIR; both the reference names and NMZ_TPU_* are exported for
drop-in compatibility with existing experiment scripts).
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Optional

from namazu_tpu.utils.log import get_logger

log = get_logger("utils.cmd")

#: SIGTERM -> SIGKILL escalation grace when a deadline kills a script's
#: process group
KILL_GRACE_S = 3.0


def kill_process_group(proc: subprocess.Popen,
                       grace: float = KILL_GRACE_S) -> None:
    """Terminate ``proc``'s whole process group (it must have been
    started with ``start_new_session=True``): SIGTERM first, SIGKILL
    after ``grace`` seconds. Killing the *group* is the point — an
    experiment ``run`` script forks testee processes and inspectors,
    and killing only ``sh`` would orphan them into the next run."""
    try:
        pgid = os.getpgid(proc.pid)
    except (OSError, ProcessLookupError):
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except (OSError, ProcessLookupError):
        return
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        pass
    # ALWAYS escalate the group: the direct child exiting on SIGTERM
    # says nothing about a SIGTERM-ignoring grandchild
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        pass
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        pass
    # give group stragglers a moment to be reaped (SIGKILL cannot be
    # ignored; this just bounds the observable window)
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        try:
            os.killpg(pgid, 0)
        except (OSError, ProcessLookupError):
            return  # group gone
        time.sleep(0.05)


class CmdFactory:
    def __init__(self, working_dir: str = "", materials_dir: str = "",
                 extra_env: Optional[dict] = None):
        self.working_dir = working_dir
        self.materials_dir = materials_dir
        # when set, deadline-mode phases write their process-group id
        # here while in flight (removed on completion): the breadcrumb
        # a supervisor needs to kill testee groups orphaned by a HARD
        # kill of this process — SIGKILL skips every finally, so the
        # group's pgid must already be on disk (doc/robustness.md)
        self.pgid_file: str = ""
        # extra variables exported to every script — the calibration
        # plane's knob transport (NMZ_CALIB_<NAME>, namazu_tpu/calibrate):
        # a calibrated timing value reaches the experiment scripts as
        # environment, never as an edited source constant
        self.extra_env: dict = dict(extra_env or {})

    def env(self) -> dict:
        env = dict(os.environ)
        env.update({str(k): str(v) for k, v in self.extra_env.items()})
        if self.working_dir:
            env["NMZ_WORKING_DIR"] = self.working_dir
            env["NMZ_TPU_WORKING_DIR"] = self.working_dir
        if self.materials_dir:
            env["NMZ_MATERIALS_DIR"] = self.materials_dir
            env["NMZ_TPU_MATERIALS_DIR"] = self.materials_dir
        # experiment scripts spawn fresh interpreters that must be able to
        # import the framework (e.g. `python -m namazu_tpu.cli inspectors`)
        # even when it is not installed site-wide
        import namazu_tpu

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(namazu_tpu.__file__)))
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_parent not in parts:
            env["PYTHONPATH"] = os.pathsep.join(
                [pkg_parent] + [p for p in parts if p])
        return env

    def run(
        self,
        script: str,
        timeout: Optional[float] = None,
        cwd: Optional[str] = None,
        deadline: Optional[float] = None,
    ) -> subprocess.CompletedProcess:
        """Run ``script`` with sh -c; stdout/stderr inherit the caller's
        (experiment scripts print progress).

        With ``deadline`` the script runs in its own session (process
        group); on expiry the WHOLE group is killed (SIGTERM, then
        SIGKILL) so forked testee children cannot outlive the phase, and
        :class:`subprocess.TimeoutExpired` is raised. The plain
        ``timeout`` keeps subprocess.run semantics (kills only ``sh``)
        for callers that manage their own children."""
        argv = ["sh", "-c", script]
        run_cwd = cwd or self.working_dir or None
        if deadline is None:
            return subprocess.run(
                argv, env=self.env(), cwd=run_cwd, timeout=timeout)
        proc = subprocess.Popen(
            argv, env=self.env(), cwd=run_cwd, start_new_session=True)
        self._write_pgid(proc)
        try:
            proc.wait(timeout=deadline)
        except subprocess.TimeoutExpired:
            log.warning("script exceeded its %.1fs deadline; killing its "
                        "process group: %s", deadline, script)
            kill_process_group(proc)
            raise subprocess.TimeoutExpired(argv, deadline) from None
        except BaseException:
            # interrupted mid-phase (e.g. KeyboardInterrupt): same
            # no-orphans guarantee as the deadline path
            kill_process_group(proc)
            raise
        finally:
            self._clear_pgid()
        return subprocess.CompletedProcess(argv, proc.returncode)

    def _write_pgid(self, proc: subprocess.Popen) -> None:
        if not self.pgid_file:
            return
        try:
            with open(self.pgid_file, "w") as f:
                f.write(str(os.getpgid(proc.pid)))
        except OSError:
            pass  # best effort: supervision degrades, the run continues

    def _clear_pgid(self) -> None:
        if self.pgid_file:
            try:
                os.unlink(self.pgid_file)
            except OSError:
                pass


def sweep_stale_pgid_files(storage_dir: str) -> int:
    """Kill process groups whose ``phase.pgid`` breadcrumb outlived its
    writer (the `run` process was hard-killed mid-phase, so its finally
    never removed the file and never killed the group). Called by the
    campaign supervisor after every attempt; returns how many groups
    were swept. The pgid-recycling race is accepted: the supervisor
    runs this immediately after the slot ends, and a recycled pgid
    would have to land inside that window on a group id we just
    created."""
    swept = 0
    try:
        run_dirs = sorted(os.listdir(storage_dir))
    except OSError:
        return 0
    for name in run_dirs:
        path = os.path.join(storage_dir, name, "phase.pgid")
        try:
            with open(path) as f:
                pgid = int(f.read().strip())
        except (OSError, ValueError):
            continue
        try:
            os.killpg(pgid, 0)
        except (OSError, ProcessLookupError):
            pass  # group already gone: just the breadcrumb to sweep
        else:
            log.warning("sweeping orphaned process group %d left by a "
                        "hard-killed run (%s)", pgid, path)
            try:
                os.killpg(pgid, signal.SIGKILL)
                swept += 1
            except (OSError, ProcessLookupError):
                pass
        try:
            os.unlink(path)
        except OSError:
            pass
    return swept
