"""Experiment script execution.

Parity: /root/reference/nmz/util/cmd/cmdutil.go:27-77 — run the config's
init/run/validate/clean commands via ``sh -c`` with the working dir and
materials dir exported (reference env names NMZ_WORKING_DIR /
NMZ_MATERIALS_DIR; both the reference names and NMZ_TPU_* are exported for
drop-in compatibility with existing experiment scripts).
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional


class CmdFactory:
    def __init__(self, working_dir: str = "", materials_dir: str = ""):
        self.working_dir = working_dir
        self.materials_dir = materials_dir

    def env(self) -> dict:
        env = dict(os.environ)
        if self.working_dir:
            env["NMZ_WORKING_DIR"] = self.working_dir
            env["NMZ_TPU_WORKING_DIR"] = self.working_dir
        if self.materials_dir:
            env["NMZ_MATERIALS_DIR"] = self.materials_dir
            env["NMZ_TPU_MATERIALS_DIR"] = self.materials_dir
        # experiment scripts spawn fresh interpreters that must be able to
        # import the framework (e.g. `python -m namazu_tpu.cli inspectors`)
        # even when it is not installed site-wide
        import namazu_tpu

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(namazu_tpu.__file__)))
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_parent not in parts:
            env["PYTHONPATH"] = os.pathsep.join(
                [pkg_parent] + [p for p in parts if p])
        return env

    def run(
        self,
        script: str,
        timeout: Optional[float] = None,
        cwd: Optional[str] = None,
    ) -> subprocess.CompletedProcess:
        """Run ``script`` with sh -c; stdout/stderr inherit the caller's
        (experiment scripts print progress)."""
        return subprocess.run(
            ["sh", "-c", script],
            env=self.env(),
            cwd=cwd or self.working_dir or None,
            timeout=timeout,
        )
