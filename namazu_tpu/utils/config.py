"""Experiment configuration.

Parity with /root/reference/nmz/util/config/config.go:23-117 (viper-based
TOML/YAML/JSON with centralized defaults). Python 3.11+ ships ``tomllib``,
so TOML needs no third-party dependency; YAML is accepted when PyYAML is
importable, JSON always.

All keys are snake_case. Dotted access (``cfg.get("explore_policy_param.
min_interval_ms")``) walks nested tables. For compatibility with configs
written against the reference's camelCase keys, lookups fall back to the
camelCase spelling of each path segment.
"""

from __future__ import annotations

import json
import re

try:
    import tomllib
except ImportError:  # Python < 3.11: tomli is the same parser/API
    import tomli as tomllib  # type: ignore[no-redef]
from typing import Any, Dict, Optional

DEFAULTS: Dict[str, Any] = {
    # which policy drives the exploration
    "explore_policy": "random",
    # policy-specific parameters, passed verbatim to policy.load_config
    "explore_policy_param": {},
    # history storage backend
    "storage_type": "naive",
    # experiment scripts, run with CWD = materials dir
    "init": "",
    "run": "",
    "validate": "",
    "clean": "",
    # out-of-tree policy plugins: modules or .py files (relative paths
    # resolve against the materials dir) imported before the policy is
    # created; each registers itself via register_policy
    # (namazu_tpu/policy/plugins.py; reference counterpart:
    # example/template/mypolicy.go's compile-your-own-main flow)
    "policy_plugins": [],
    # endpoints: -1 = disabled, 0 = auto-assign, >0 = fixed port
    "rest_port": -1,
    "agent_port": -1,  # framed-TCP guest-agent endpoint (reference: pbPort)
    # do not start the exploration policy until REST /control enables it
    "skip_init_orchestration": False,
    # liveness watchdog (doc/robustness.md): entities with no inbound
    # event for this many seconds are declared dead and their parked
    # events force-released (nmz_entity_stalled_total); 0 disables
    "entity_liveness_timeout_s": 0,
    # per-phase deadlines for the experiment scripts (seconds; 0 = none).
    # enforced with process-group kill so a hung script's forked testee
    # children die with it (utils/cmd.py, cli/run_cmd.py)
    "run_deadline_s": 0,
    "validate_deadline_s": 0,
    "clean_deadline_s": 0,
    # observability plane (namazu_tpu/obs): event-lifecycle spans,
    # metrics registry, GET /metrics on the REST endpoint. Disabling
    # reduces the per-event hot path to one flag check (obs/metrics.py)
    "obs_enabled": True,
    # container mode
    "container": {},
}


def _camel(segment: str) -> str:
    parts = segment.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


class Config:
    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self._data: Dict[str, Any] = dict(data or {})

    # -- constructors ----------------------------------------------------

    @classmethod
    def from_file(cls, path: str) -> "Config":
        text = open(path, "rb").read()
        if path.endswith((".toml", ".tml")):
            return cls(tomllib.loads(text.decode()))
        if path.endswith(".json"):
            return cls(json.loads(text))
        if path.endswith((".yaml", ".yml")):
            import yaml  # optional dependency, present in this image

            return cls(yaml.safe_load(text))
        # sniff: try TOML then JSON
        return cls.from_string(text.decode())

    @classmethod
    def from_string(cls, text: str, fmt: str = "") -> "Config":
        if fmt == "toml" or not fmt:
            try:
                return cls(tomllib.loads(text))
            except tomllib.TOMLDecodeError:
                if fmt:
                    raise
        if fmt in ("", "json"):
            return cls(json.loads(text))
        if fmt in ("yaml", "yml"):
            import yaml

            return cls(yaml.safe_load(text))
        raise ValueError(f"unknown config format {fmt!r}")

    # -- access ----------------------------------------------------------

    def _lookup(self, data: Any, path: str) -> Any:
        cur = data
        for seg in path.split("."):
            if not isinstance(cur, dict):
                raise KeyError(path)
            if seg in cur:
                cur = cur[seg]
            elif _camel(seg) in cur:
                cur = cur[_camel(seg)]
            else:
                raise KeyError(path)
        return cur

    def get(self, path: str, default: Any = None) -> Any:
        try:
            return self._lookup(self._data, path)
        except KeyError:
            pass
        try:
            return self._lookup(DEFAULTS, path)
        except KeyError:
            return default

    def is_set(self, path: str) -> bool:
        """Whether ``path`` was given explicitly (not just a DEFAULT)."""
        try:
            self._lookup(self._data, path)
            return True
        except KeyError:
            return False

    def set(self, path: str, value: Any) -> None:
        # mirror get()'s camelCase fallback: a reference-style config
        # holds e.g. "explorePolicyParam", and creating a snake_case
        # sibling table would SHADOW it on every later lookup — one
        # `run --knowledge` would silently reset every other policy
        # param to defaults
        segs = path.split(".")
        cur = self._data
        for seg in segs[:-1]:
            if seg not in cur and isinstance(cur.get(_camel(seg)), dict):
                seg = _camel(seg)
            nxt = cur.setdefault(seg, {})
            if not isinstance(nxt, dict):
                nxt = cur[seg] = {}
            cur = nxt
        leaf = segs[-1]
        if leaf not in cur and _camel(leaf) in cur:
            leaf = _camel(leaf)
        cur[leaf] = value

    def policy_param(self, key: str, default: Any = None) -> Any:
        return self.get(f"explore_policy_param.{key}", default)

    def to_jsonable(self) -> Dict[str, Any]:
        return dict(self._data)

    def dump_json(self, path: str) -> None:
        # atomic: the config snapshot is part of the storage's persistent
        # state — a kill mid-init must not leave a torn config.json that
        # poisons every later `run` (utils/atomic.py)
        from namazu_tpu.utils.atomic import atomic_write_json

        atomic_write_json(path, self._data, indent=2, sort_keys=True)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Config({self._data!r})"


_DURATION_RE = re.compile(r"^\s*([0-9.]+)\s*(ms|s|m|h|us)?\s*$")
_UNIT_SECONDS = {"us": 1e-6, "ms": 1e-3, "s": 1.0, None: 1e-3, "m": 60.0, "h": 3600.0}


def parse_duration(value: Any, default_unit_ms: bool = True) -> float:
    """Parse a duration into seconds.

    Accepts numbers (interpreted as milliseconds, matching the reference's
    convention for interval params, e.g. minInterval/maxInterval in ms —
    randompolicy.go:156-228) or strings with a unit suffix ("80ms", "1.5s").
    """
    if isinstance(value, (int, float)):
        return float(value) * (1e-3 if default_unit_ms else 1.0)
    m = _DURATION_RE.match(str(value))
    if not m:
        raise ValueError(f"bad duration {value!r}")
    num, unit = float(m.group(1)), m.group(2)
    if unit is None and not default_unit_ms:
        return num
    return num * _UNIT_SECONDS[unit]
