"""Host-side trace featurization: recorded runs -> fixed-shape arrays.

The control plane records variable-length action traces (JSON). The search
plane needs static shapes for XLA, so each trace is encoded as:

* ``hint_ids``  int32[L] — replay hint hashed (fnv64a) into H buckets; the
  hint bucket is the unit the genome's delay table indexes, generalizing
  the replayable policy's ``hash(seed, hint) % max`` delays;
* ``entity_ids`` int32[L] — entity index (stable per experiment);
* ``arrival``   float32[L] — event arrival offset in seconds from run start
  (triggered/arrival times when recorded; index spacing otherwise);
* ``mask``      bool[L] — valid positions (traces are padded/truncated).

Precedence *pairs* are sampled over hint buckets (not positions) so the
feature space is comparable across runs — a failed run's trace and a
candidate schedule's counterfactual interleaving land in the same space.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from namazu_tpu.policy.replayable import fnv64a
# the hint-format version lives with the signal classes that define the
# hints (stdlib-only, so the control plane can stamp runs without numpy);
# re-exported here because the search plane reads it alongside encoding
from namazu_tpu.signal.base import HINT_SPACE  # noqa: F401  (re-export)
from namazu_tpu.utils.trace import SingleTrace

DEFAULT_L = 256  # default length quantum for encoded traces
DEFAULT_H = 256  # hint buckets (genome length)
DEFAULT_K = 256  # precedence pairs (feature dimension)



def checkpoint_hint_space(z) -> str:
    """Hint-space tag of a checkpoint npz mapping; checkpoints predating
    the tag were built from bare content hints ("content-v1"). One home
    for the default so the fast-install path (policy/tpu.py) and the
    full load (models/search.py) can never disagree on compatibility."""
    return str(z["hint_space"]) if "hint_space" in z else "content-v1"

# encoded lengths are rounded up to a multiple of this so XLA sees a
# handful of static shapes instead of one per run length
L_QUANTUM = 128


def _auto_length(n: int) -> int:
    """Padded length for an n-event trace: next multiple of L_QUANTUM,
    at least one quantum. No truncation — a real ZooKeeper run produces
    thousands of packet events and the search must see all of them
    (long traces score blockwise, ops/schedule.py)."""
    return max(L_QUANTUM, -(-n // L_QUANTUM) * L_QUANTUM)


def hint_bucket(hint: str, n_buckets: int = DEFAULT_H) -> int:
    return fnv64a(hint.encode()) % n_buckets


def fault_coin(seed: int, H: int = DEFAULT_H) -> np.ndarray:
    """Deterministic per-bucket fault coin f32[H] in [0, 1).

    The policy drops an event iff ``coin[bucket] < faults[bucket]``
    (policy/tpu.py _fault_for) and the scorer removes exactly those events
    from the counterfactual (ops/schedule.py drop_mask) — same formula,
    same coin, so a searched fault table replays to the interleaving it
    was scored as."""
    return np.array(
        [fnv64a(f"{seed}|fault|{h}".encode()) % 10_000 / 10_000.0
         for h in range(H)],
        np.float32,
    )


def class_supports_fault(class_name: str) -> bool:
    """Whether events of this signal class carry a fault action (packet
    drop / EIO) — i.e. whether the control plane can actually realize a
    drop for them (policy/tpu.py _action_for checks
    ``default_fault_action() is not None``). Unknown or unrecorded
    classes are treated as faultable (the pre-flag behavior)."""
    if not class_name:
        return True
    cached = _FAULTABLE_CACHE.get(class_name)
    if cached is not None:
        return cached
    from namazu_tpu.signal.base import SignalError, get_signal_class
    from namazu_tpu.signal.event import Event

    try:
        cls = get_signal_class(class_name)
    except SignalError:
        result = True
    else:
        result = (isinstance(cls, type) and issubclass(cls, Event)
                  and cls.default_fault_action
                  is not Event.default_fault_action)
    _FAULTABLE_CACHE[class_name] = result
    return result


_FAULTABLE_CACHE: Dict[str, bool] = {}


class EncodedTrace:
    """One trace in array form (plain numpy; converted to jnp at the device
    boundary)."""

    def __init__(self, hint_ids, entity_ids, arrival, mask, truncated=0,
                 faultable=None):
        self.hint_ids = np.asarray(hint_ids, np.int32)
        self.entity_ids = np.asarray(entity_ids, np.int32)
        self.arrival = np.asarray(arrival, np.float32)
        self.mask = np.asarray(mask, bool)
        self.truncated = int(truncated)  # events beyond an explicit L cap
        # events whose cause class supports a fault action; defaults to
        # all-faultable (pre-flag encodes score exactly as before)
        self.faultable = (np.ones_like(self.mask) if faultable is None
                          else np.asarray(faultable, bool))

    @property
    def length(self) -> int:
        return int(self.mask.sum())


def encode_trace(
    trace: SingleTrace,
    L: Optional[int] = None,
    H: int = DEFAULT_H,
    entity_index: Optional[Dict[str, int]] = None,
    realized: bool = False,
) -> EncodedTrace:
    """Encode a recorded action trace.

    Each action's preserved cause-event hint (``action.event_hint``, set by
    ``Action.for_event``) is the semantic identity; actions recorded
    without one (e.g. traces from before a semantic parser was attached)
    fall back to cause-event class + entity.

    ``realized=True`` timestamps each event at its RELEASE
    (``triggered_time`` — where the recording policy actually placed it in
    the interleaving) instead of its arrival. This is the right view for
    *embedding* executed runs into feature space: a failure induced by
    injected delays carries its signature in the release times, while its
    arrivals look like any healthy run's — arrival-anchored failure
    features would let the zero-delay genome sit at distance ~0 from the
    failure archive and the search would feel no pressure to inject
    anything. Counterfactual *reference* traces keep the default
    (arrival) anchoring: candidate release times are
    ``arrival + delay``, so both sides of the feature distance live in
    release-time space.

    ``L=None`` (default) sizes the arrays to the whole trace — nothing is
    ever silently dropped. An explicit ``L`` is a hard cap for callers
    that want to bound device memory; events past it are truncated (the
    returned ``EncodedTrace.truncated`` says how many).
    """
    views = encode_trace_views(trace, L=L, H=H, entity_index=entity_index)
    return views[1] if realized else views[0]


def encode_trace_views(
    trace: SingleTrace,
    L: Optional[int] = None,
    H: int = DEFAULT_H,
    entity_index: Optional[Dict[str, int]] = None,
) -> Tuple[EncodedTrace, EncodedTrace]:
    """Both time views of one trace in a single pass:
    ``(arrival_view, realized_view)``.

    Identity arrays (hint buckets, entities, mask, faultable flags) are
    computed once and SHARED between the two EncodedTraces; only the
    time vectors differ. Callers that need both views (the policy's
    history ingest encodes the counterfactual reference from arrivals
    and the archive embedding from releases) pay one encode instead of
    two.
    """
    entity_index = entity_index if entity_index is not None else {}
    if L is None:
        L = _auto_length(len(trace))
    hint_ids = np.zeros(L, np.int32)
    entity_ids = np.zeros(L, np.int32)
    arrival = np.zeros(L, np.float32)
    released = np.zeros(L, np.float32)
    mask = np.zeros(L, bool)
    faultable = np.ones(L, bool)

    # Arrival view: anchor on the cause event's ARRIVAL at the
    # orchestrator when the trace recorded it (Action.event_arrived,
    # round-3 field; reference semantics: BasicSignal.Arrived,
    # signal.go:75-191) — triggered_time contains the recording
    # policy's own injected delay, so a counterfactual anchored on it
    # would evolve against the recorder's jitter instead of the
    # system's natural interleaving. Realized view: the opposite
    # preference — release times ARE the interleaving the run executed.
    # Either view falls back to the other's timestamp when one was not
    # recorded.
    arr_times: List[float] = []
    rel_times: List[float] = []
    for a in trace:
        arrived = getattr(a, "event_arrived", None) or 0.0
        rel = a.triggered_time or 0.0
        arr_times.append(arrived if arrived else rel)
        rel_times.append(rel if rel else arrived)
    a0 = min((t for t in arr_times if t), default=0.0)
    r0 = min((t for t in rel_times if t), default=0.0)

    for i, action in enumerate(trace):
        if i >= L:
            break
        ent = action.entity_id
        if ent not in entity_index:
            entity_index[ent] = len(entity_index)
        hint = getattr(action, "event_hint", "") or \
            f"{action.event_class or action.class_name()}:{ent}"
        hint_ids[i] = hint_bucket(hint, H)
        entity_ids[i] = entity_index[ent]
        arrival[i] = (arr_times[i] - a0) if arr_times[i] else i * 1e-3
        released[i] = (rel_times[i] - r0) if rel_times[i] else i * 1e-3
        mask[i] = True
        faultable[i] = class_supports_fault(
            getattr(action, "event_class", ""))
    truncated = max(0, len(trace) - L)
    return (
        EncodedTrace(hint_ids, entity_ids, arrival, mask,
                     truncated=truncated, faultable=faultable),
        EncodedTrace(hint_ids, entity_ids, released, mask,
                     truncated=truncated, faultable=faultable),
    )


def encode_event_stream(
    hints: Sequence[str],
    arrivals: Optional[Sequence[float]] = None,
    entities: Optional[Sequence[str]] = None,
    L: Optional[int] = None,
    H: int = DEFAULT_H,
) -> EncodedTrace:
    """Encode a live event stream (the TPU policy's view of the current
    run) from raw replay hints. ``L=None`` sizes to the whole stream."""
    if L is None:
        L = _auto_length(len(hints))
    n = min(len(hints), L)
    hint_ids = np.zeros(L, np.int32)
    entity_ids = np.zeros(L, np.int32)
    arrival = np.zeros(L, np.float32)
    mask = np.zeros(L, bool)
    ent_index: Dict[str, int] = {}
    for i in range(n):
        hint_ids[i] = hint_bucket(hints[i], H)
        if entities is not None:
            e = entities[i]
            if e not in ent_index:
                ent_index[e] = len(ent_index)
            entity_ids[i] = ent_index[e]
        arrival[i] = arrivals[i] if arrivals is not None else i * 1e-3
        mask[i] = True
    return EncodedTrace(hint_ids, entity_ids, arrival, mask,
                        truncated=max(0, len(hints) - L))


def sample_pairs(
    K: int = DEFAULT_K, H: int = DEFAULT_H, seed: int = 0
) -> np.ndarray:
    """Deterministically sample K ordered hint-bucket pairs (u != v); the
    precedence of bucket-u's first event vs bucket-v's first event is one
    feature dimension."""
    rng = np.random.RandomState(seed)
    u = rng.randint(0, H, size=K).astype(np.int32)
    v = rng.randint(0, H - 1, size=K).astype(np.int32)
    v = np.where(v >= u, v + 1, v).astype(np.int32)  # ensure u != v
    return np.stack([u, v], axis=1)  # [K, 2]


def informative_pairs(
    occupied: Sequence[int],
    K: int = DEFAULT_K,
    H: int = DEFAULT_H,
    seed: int = 0,
) -> np.ndarray:
    """K ordered hint-bucket pairs concentrated on the buckets that
    actually occur in the recorded traces.

    ``sample_pairs`` draws uniformly over all H buckets; with H=64 and
    ~8 occupied buckets the expected number of informative pairs (both
    ends occupied) is < 1, making the failure signature invisible in
    feature space. Enumerating the occupied-bucket pairs first makes
    every realizable precedence a feature dimension; the remainder (if
    any) is filled with uniform pairs so future, unseen buckets still
    project somewhere."""
    occ = sorted({int(b) for b in occupied})
    pairs = [(u, v) for u in occ for v in occ if u != v]
    rng = np.random.RandomState(seed)
    if len(pairs) >= K:
        idx = rng.choice(len(pairs), size=K, replace=False)
        return np.array([pairs[i] for i in sorted(idx)], np.int32)
    fill = sample_pairs(K - len(pairs), H, seed)
    if not pairs:
        return fill
    return np.concatenate([np.array(pairs, np.int32), fill])


def envelope_trace(encs: Sequence[EncodedTrace]) -> EncodedTrace:
    """Per-bucket minimum-arrival envelope of several encoded traces.

    The scorer's features depend only on each hint bucket's FIRST
    occurrence (ops/schedule.py first_occurrence), so a synthetic trace
    with one event per observed bucket at its minimum arrival over the
    inputs is feature-equivalent to the tightest lower envelope of those
    runs. Used as the counterfactual anchor for repro-rate search:
    recorded arrivals include whatever delays the recording policy
    injected, and the min over several runs is the best available proxy
    for the *natural* (uninspected) arrival the next run will produce —
    so a delay table evolved against the envelope transfers."""
    firsts: Dict[int, float] = {}
    ents: Dict[int, int] = {}
    flts: Dict[int, bool] = {}
    for e in encs:
        hid = e.hint_ids[e.mask]
        arr = e.arrival[e.mask]
        ent = e.entity_ids[e.mask]
        flt = e.faultable[e.mask]
        for b, t, en, fb in zip(hid, arr, ent, flt):
            b = int(b)
            if b not in firsts or t < firsts[b]:
                firsts[b] = float(t)
                ents[b] = int(en)
                flts[b] = bool(fb)
    items = sorted(firsts.items(), key=lambda kv: kv[1])
    L = _auto_length(len(items))
    hint_ids = np.zeros(L, np.int32)
    entity_ids = np.zeros(L, np.int32)
    arrival = np.zeros(L, np.float32)
    mask = np.zeros(L, bool)
    faultable = np.ones(L, bool)
    for i, (b, t) in enumerate(items):
        hint_ids[i] = b
        entity_ids[i] = ents[b]
        arrival[i] = t
        mask[i] = True
        faultable[i] = flts[b]
    return EncodedTrace(hint_ids, entity_ids, arrival, mask,
                        faultable=faultable)


def pad_trace_row(enc: EncodedTrace, L: int) -> Dict[str, np.ndarray]:
    """One trace's scoring arrays right-padded to ``L`` — 0 for
    ids/times, False for the mask/faultable flags. The ONE home for the
    pad fills, shared by :func:`stack_traces` and the fused loop's
    device-resident trace store (models/search.py ``_ResidentTraces``):
    a resident row sliced back to a batch's length must be
    value-identical to the host stacker's padding, or fused and
    stepwise scoring would diverge on the pad region."""
    def pad(a, fill):
        n = L - a.shape[0]
        if n <= 0:
            return a
        return np.concatenate([a, np.full((n,), fill, a.dtype)])

    return {
        "hint": pad(enc.hint_ids, 0),
        "ent": pad(enc.entity_ids, 0),
        "arr": pad(enc.arrival, 0),
        "mask": pad(enc.mask, False),
        "flt": pad(enc.faultable, False),
    }


def stack_traces(traces: Sequence[EncodedTrace]) -> Tuple[np.ndarray, ...]:
    """Stack encoded traces into batched arrays [T, L]
    ``(hint_ids, entity_ids, arrival, mask, faultable)``, right-padding
    ragged lengths to the longest (auto-length encodes make ragged
    batches the normal case). Pad fills live in :func:`pad_trace_row`."""
    L = max(t.hint_ids.shape[0] for t in traces)
    rows = [pad_trace_row(t, L) for t in traces]
    return (
        np.stack([r["hint"] for r in rows]),
        np.stack([r["ent"] for r in rows]),
        np.stack([r["arr"] for r in rows]),
        np.stack([r["mask"] for r in rows]),
        np.stack([r["flt"] for r in rows]),
    )
