"""Host-side trace featurization: recorded runs -> fixed-shape arrays.

The control plane records variable-length action traces (JSON). The search
plane needs static shapes for XLA, so each trace is encoded as:

* ``hint_ids``  int32[L] — replay hint hashed (fnv64a) into H buckets; the
  hint bucket is the unit the genome's delay table indexes, generalizing
  the replayable policy's ``hash(seed, hint) % max`` delays;
* ``entity_ids`` int32[L] — entity index (stable per experiment);
* ``arrival``   float32[L] — event arrival offset in seconds from run start
  (triggered/arrival times when recorded; index spacing otherwise);
* ``mask``      bool[L] — valid positions (traces are padded/truncated).

Precedence *pairs* are sampled over hint buckets (not positions) so the
feature space is comparable across runs — a failed run's trace and a
candidate schedule's counterfactual interleaving land in the same space.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from namazu_tpu.policy.replayable import fnv64a
from namazu_tpu.utils.trace import SingleTrace

DEFAULT_L = 256  # default length quantum for encoded traces
DEFAULT_H = 256  # hint buckets (genome length)
DEFAULT_K = 256  # precedence pairs (feature dimension)

# encoded lengths are rounded up to a multiple of this so XLA sees a
# handful of static shapes instead of one per run length
L_QUANTUM = 128


def _auto_length(n: int) -> int:
    """Padded length for an n-event trace: next multiple of L_QUANTUM,
    at least one quantum. No truncation — a real ZooKeeper run produces
    thousands of packet events and the search must see all of them
    (long traces score blockwise, ops/schedule.py)."""
    return max(L_QUANTUM, -(-n // L_QUANTUM) * L_QUANTUM)


def hint_bucket(hint: str, n_buckets: int = DEFAULT_H) -> int:
    return fnv64a(hint.encode()) % n_buckets


def fault_coin(seed: int, H: int = DEFAULT_H) -> np.ndarray:
    """Deterministic per-bucket fault coin f32[H] in [0, 1).

    The policy drops an event iff ``coin[bucket] < faults[bucket]``
    (policy/tpu.py _fault_for) and the scorer removes exactly those events
    from the counterfactual (ops/schedule.py drop_mask) — same formula,
    same coin, so a searched fault table replays to the interleaving it
    was scored as."""
    return np.array(
        [fnv64a(f"{seed}|fault|{h}".encode()) % 10_000 / 10_000.0
         for h in range(H)],
        np.float32,
    )


class EncodedTrace:
    """One trace in array form (plain numpy; converted to jnp at the device
    boundary)."""

    def __init__(self, hint_ids, entity_ids, arrival, mask, truncated=0):
        self.hint_ids = np.asarray(hint_ids, np.int32)
        self.entity_ids = np.asarray(entity_ids, np.int32)
        self.arrival = np.asarray(arrival, np.float32)
        self.mask = np.asarray(mask, bool)
        self.truncated = int(truncated)  # events beyond an explicit L cap

    @property
    def length(self) -> int:
        return int(self.mask.sum())


def encode_trace(
    trace: SingleTrace,
    L: Optional[int] = None,
    H: int = DEFAULT_H,
    entity_index: Optional[Dict[str, int]] = None,
) -> EncodedTrace:
    """Encode a recorded action trace.

    Each action's preserved cause-event hint (``action.event_hint``, set by
    ``Action.for_event``) is the semantic identity; actions recorded
    without one (e.g. traces from before a semantic parser was attached)
    fall back to cause-event class + entity.

    ``L=None`` (default) sizes the arrays to the whole trace — nothing is
    ever silently dropped. An explicit ``L`` is a hard cap for callers
    that want to bound device memory; events past it are truncated (the
    returned ``EncodedTrace.truncated`` says how many).
    """
    entity_index = entity_index if entity_index is not None else {}
    if L is None:
        L = _auto_length(len(trace))
    hint_ids = np.zeros(L, np.int32)
    entity_ids = np.zeros(L, np.int32)
    arrival = np.zeros(L, np.float32)
    mask = np.zeros(L, bool)

    times: List[float] = []
    for a in trace:
        times.append(a.triggered_time if a.triggered_time else 0.0)
    t0 = min((t for t in times if t), default=0.0)

    for i, action in enumerate(trace):
        if i >= L:
            break
        ent = action.entity_id
        if ent not in entity_index:
            entity_index[ent] = len(entity_index)
        hint = getattr(action, "event_hint", "") or \
            f"{action.event_class or action.class_name()}:{ent}"
        hint_ids[i] = hint_bucket(hint, H)
        entity_ids[i] = entity_index[ent]
        arrival[i] = (times[i] - t0) if times[i] else i * 1e-3
        mask[i] = True
    return EncodedTrace(hint_ids, entity_ids, arrival, mask,
                        truncated=max(0, len(trace) - L))


def encode_event_stream(
    hints: Sequence[str],
    arrivals: Optional[Sequence[float]] = None,
    entities: Optional[Sequence[str]] = None,
    L: Optional[int] = None,
    H: int = DEFAULT_H,
) -> EncodedTrace:
    """Encode a live event stream (the TPU policy's view of the current
    run) from raw replay hints. ``L=None`` sizes to the whole stream."""
    if L is None:
        L = _auto_length(len(hints))
    n = min(len(hints), L)
    hint_ids = np.zeros(L, np.int32)
    entity_ids = np.zeros(L, np.int32)
    arrival = np.zeros(L, np.float32)
    mask = np.zeros(L, bool)
    ent_index: Dict[str, int] = {}
    for i in range(n):
        hint_ids[i] = hint_bucket(hints[i], H)
        if entities is not None:
            e = entities[i]
            if e not in ent_index:
                ent_index[e] = len(ent_index)
            entity_ids[i] = ent_index[e]
        arrival[i] = arrivals[i] if arrivals is not None else i * 1e-3
        mask[i] = True
    return EncodedTrace(hint_ids, entity_ids, arrival, mask,
                        truncated=max(0, len(hints) - L))


def sample_pairs(
    K: int = DEFAULT_K, H: int = DEFAULT_H, seed: int = 0
) -> np.ndarray:
    """Deterministically sample K ordered hint-bucket pairs (u != v); the
    precedence of bucket-u's first event vs bucket-v's first event is one
    feature dimension."""
    rng = np.random.RandomState(seed)
    u = rng.randint(0, H, size=K).astype(np.int32)
    v = rng.randint(0, H - 1, size=K).astype(np.int32)
    v = np.where(v >= u, v + 1, v).astype(np.int32)  # ensure u != v
    return np.stack([u, v], axis=1)  # [K, 2]


def envelope_trace(encs: Sequence[EncodedTrace]) -> EncodedTrace:
    """Per-bucket minimum-arrival envelope of several encoded traces.

    The scorer's features depend only on each hint bucket's FIRST
    occurrence (ops/schedule.py first_occurrence), so a synthetic trace
    with one event per observed bucket at its minimum arrival over the
    inputs is feature-equivalent to the tightest lower envelope of those
    runs. Used as the counterfactual anchor for repro-rate search:
    recorded arrivals include whatever delays the recording policy
    injected, and the min over several runs is the best available proxy
    for the *natural* (uninspected) arrival the next run will produce —
    so a delay table evolved against the envelope transfers."""
    firsts: Dict[int, float] = {}
    ents: Dict[int, int] = {}
    for e in encs:
        hid = e.hint_ids[e.mask]
        arr = e.arrival[e.mask]
        ent = e.entity_ids[e.mask]
        for b, t, en in zip(hid, arr, ent):
            b = int(b)
            if b not in firsts or t < firsts[b]:
                firsts[b] = float(t)
                ents[b] = int(en)
    items = sorted(firsts.items(), key=lambda kv: kv[1])
    L = _auto_length(len(items))
    hint_ids = np.zeros(L, np.int32)
    entity_ids = np.zeros(L, np.int32)
    arrival = np.zeros(L, np.float32)
    mask = np.zeros(L, bool)
    for i, (b, t) in enumerate(items):
        hint_ids[i] = b
        entity_ids[i] = ents[b]
        arrival[i] = t
        mask[i] = True
    return EncodedTrace(hint_ids, entity_ids, arrival, mask)


def stack_traces(traces: Sequence[EncodedTrace]) -> Tuple[np.ndarray, ...]:
    """Stack encoded traces into batched arrays [T, L], right-padding
    ragged lengths to the longest (auto-length encodes make ragged
    batches the normal case)."""
    L = max(t.hint_ids.shape[0] for t in traces)

    def pad(a, fill=0):
        n = L - a.shape[0]
        if n == 0:
            return a
        return np.concatenate([a, np.full((n,), fill, a.dtype)])

    return (
        np.stack([pad(t.hint_ids) for t in traces]),
        np.stack([pad(t.entity_ids) for t in traces]),
        np.stack([pad(t.arrival) for t in traces]),
        np.stack([pad(t.mask, False) for t in traces]),
    )
