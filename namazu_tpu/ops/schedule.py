"""Pure-JAX schedule scoring: the search plane's inner loop.

A *schedule genome* is a per-hint-bucket delay table ``delays f32[H]``
(seconds) plus a per-hint fault-probability table ``faults f32[H]``. Given
a recorded trace, the counterfactual interleaving under a genome is defined
by release times ``t[e] = arrival[e] + delays[hint_ids[e]]`` — exactly what
the control plane's ScheduledQueue realizes when the policy replays the
genome (namazu_tpu/policy/tpu.py), so scored schedules and executed
schedules agree by construction.

Scoring (vmapped over a population [P, H]):

1. first-occurrence time per hint bucket, ``first f32[H]`` (scatter-min);
2. precedence features over K sampled bucket pairs:
   ``feat[k] = sigmoid((first[v_k] - first[u_k]) / tau)`` — a smooth
   "does u happen before v" indicator in (0,1); buckets absent from the
   trace get BIG times, making their pairs a neutral 0.5;
3. novelty = min squared L2 distance to an archive of previously executed
   schedules' features (one [P,K]x[K,A] matmul — MXU work);
4. bug affinity = -min squared distance to the features of traces that
   actually reproduced the bug (failure archive);
5. fitness = w_novelty * novelty + w_bug * bug_affinity
   - w_delay_cost * mean(delays)  (prefer fast schedules, tie-break).

This plane generalizes the reference's whole exploration stack: the random
policy samples ONE schedule per wall-clock run (~minutes); here millions
are scored per second between runs, and only the argmax is paid for with
wall-clock (SURVEY.md section 6, BASELINE.json north star).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

BIG = 1e9  # "never happens" release time
# min-identity used to mask archive rows out of a distance min (padded
# rows and rows past a ring's occupancy): large enough that a masked row
# can never win against any real feature distance (features live in
# (0,1)^K so real d2 <= K), small enough that f32 arithmetic on it stays
# finite
MASK_BIG = 3.4e38


class TraceArrays(NamedTuple):
    """Static-shape view of one encoded trace on device.

    ``faultable`` marks events whose cause class supports a fault action
    (packet drop / EIO); ``None`` means "treat everything as faultable"
    (pre-faultable encodes, and fault-off scoring where it is unused).
    """

    hint_ids: jax.Array  # int32[L]
    arrival: jax.Array  # float32[L]
    mask: jax.Array  # bool[L]
    faultable: Optional[jax.Array] = None  # bool[L] or None


class ScoreWeights(NamedTuple):
    novelty: float = 1.0
    bug: float = 1.0
    delay_cost: float = 0.01
    tau: float = 0.005  # precedence smoothing, seconds
    # cost per dropped event (as a fraction of live events): dropping
    # *everything* is maximally novel, so fault search needs a
    # counterweight that scales with how much of the trace the genome
    # erases (reference: faults are rare, faultActionProbability ~ 0.0,
    # randompolicy.go:300-317)
    fault_cost: float = 0.05
    # order mode (BASELINE config 3, "permutation+delay genomes"): the
    # genome table is interpreted as per-hint *priorities* realized by the
    # policy's reorder window, not as literal delays. Events are bucketed
    # into arrival windows of order_window seconds (0 = one global
    # window) and permuted by (priority, arrival) *within* each window —
    # exactly the set of interleavings the control plane's windowed
    # reorder buffer can realize, so scored schedules stay executable.
    order_mode: bool = False
    order_gap: float = 0.001  # seconds between consecutive releases
    order_window: float = 0.0  # reorder-window size; 0 = whole trace


def normalize_fault_trace(trace: TraceArrays,
                          coin: Optional[jax.Array]) -> TraceArrays:
    """One home for the faultable-flag contract at scoring entry points:
    without a fault coin the flag is never consumed, so it is stripped
    (keeps the fault-off pytree and jit cache entry flag-free); with a
    coin but no flag, everything is faultable (pre-flag behavior)."""
    if coin is None:
        return trace._replace(faultable=None)
    if trace.faultable is None:
        return trace._replace(faultable=jnp.ones_like(trace.mask))
    return trace


def replicated_trace_specs():
    """(fault, nofault) TraceArrays PartitionSpec pytrees for shard_map
    entry points that replicate the trace: the fault variant ships the
    per-event faultable flag, the fault-off variant never does."""
    from jax.sharding import PartitionSpec as P

    return (
        TraceArrays(hint_ids=P(), arrival=P(), mask=P(), faultable=P()),
        TraceArrays(hint_ids=P(), arrival=P(), mask=P()),
    )


def release_times(delays: jax.Array, trace: TraceArrays) -> jax.Array:
    """t[e] = arrival[e] + delays[hint_ids[e]] (masked -> BIG)."""
    t = trace.arrival + delays[trace.hint_ids]
    return jnp.where(trace.mask, t, BIG)


def drop_mask(faults: jax.Array, coin: jax.Array,
              trace: TraceArrays) -> jax.Array:
    """bool[L]: events the genome's fault table removes from the
    counterfactual interleaving.

    The control plane's fault decision is a deterministic per-bucket coin
    (policy/tpu.py _fault_for): event e is dropped iff
    ``coin[hint_ids[e]] < faults[hint_ids[e]]``, so the scored
    counterfactual and the replayed schedule agree by construction. A
    dropped packet never arrives (PacketFaultAction, reference
    action_fault_packet.go:29-46); EIO-style filesystem faults are
    approximated the same way — the op's normal effect vanishes from the
    interleaving.

    The control plane only realizes a drop when the event supports a
    fault action (``default_fault_action() is not None``); a hint-bucket
    hash collision between a faultable and a non-faultable hint must not
    produce scored drops that never replay, so non-faultable events are
    masked out of the drop set when the trace carries the flag.
    """
    d = trace.mask & (coin[trace.hint_ids] < faults[trace.hint_ids])
    if trace.faultable is not None:
        d = d & trace.faultable
    return d


def apply_faults(trace: TraceArrays, faults: Optional[jax.Array],
                 coin: Optional[jax.Array]) -> TraceArrays:
    """Trace with fault-dropped events masked out (identity when the
    genome has no fault half)."""
    if faults is None:
        return trace
    dropped = drop_mask(faults, coin, trace)
    return TraceArrays(trace.hint_ids, trace.arrival,
                       trace.mask & ~dropped, trace.faultable)


def order_release_times(prio: jax.Array, trace: TraceArrays,
                        gap: float, window: float = 0.0) -> jax.Array:
    """Counterfactual release times under *windowed permutation*
    scheduling — what the policy's reorder buffer (policy/tpu.py
    release_mode "reorder") actually realizes: events are batched into
    arrival windows of ``window`` seconds and each batch is released in
    ``(prio[hint], arrival)`` order, ``gap`` seconds apart, starting at
    the window's end. ``window=0`` scores one global window (the upper
    bound of reachable permutations). Only co-pending events can be
    permuted, so scored interleavings stay executable.

    1-D trace only (vmap over genomes; use score_population_multi for
    stacked traces). Masked positions sort last and stay BIG.
    """
    if trace.hint_ids.ndim != 1:
        raise ValueError(
            "order_release_times takes a single [L] trace; got shape "
            f"{trace.hint_ids.shape}"
        )
    L = trace.hint_ids.shape[0]
    if window > 0:
        win = jnp.floor(trace.arrival / window).astype(jnp.int32)
    else:
        win = jnp.zeros((L,), jnp.int32)
    win = jnp.where(trace.mask, win, jnp.iinfo(jnp.int32).max)
    key = jnp.where(trace.mask, prio[trace.hint_ids], jnp.inf)
    # window-major, then priority, then arrival (stable within window)
    order = jnp.lexsort((trace.arrival, key, win))  # [L] ids by rank
    idx = jnp.arange(L, dtype=jnp.int32)
    # within-window rank, computed in sorted order: position minus the
    # start index of the event's window segment (cummax of segment
    # starts — no bound on the number of windows)
    sw = win[order]
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sw[1:] != sw[:-1]])
    seg_start = jax.lax.cummax(jnp.where(is_start, idx, 0))
    within_sorted = idx - seg_start
    within = jnp.zeros((L,), jnp.int32).at[order].set(within_sorted)
    base = (win.astype(jnp.float32) + 1.0) * window  # window close time
    t = base + within.astype(jnp.float32) * gap
    return jnp.where(trace.mask, t, BIG)


def first_occurrence(t: jax.Array, trace: TraceArrays, H: int) -> jax.Array:
    """Earliest release time per hint bucket, BIG where absent."""
    return jnp.full((H,), BIG, t.dtype).at[trace.hint_ids].min(
        jnp.where(trace.mask, t, BIG)
    )


def precedence_features(
    first: jax.Array, pairs: jax.Array, tau: float
) -> jax.Array:
    """feat[k] = sigmoid((first[v_k] - first[u_k]) / tau) in (0,1)."""
    du = first[pairs[:, 0]]
    dv = first[pairs[:, 1]]
    # clip the argument so BIG-vs-finite saturates instead of overflowing
    z = jnp.clip((dv - du) / tau, -30.0, 30.0)
    return jax.nn.sigmoid(z)


def _genome_features(
    delays: jax.Array, trace: TraceArrays, pairs: jax.Array, tau: float,
    order_mode: bool = False, order_gap: float = 0.001,
    order_window: float = 0.0,
    faults: Optional[jax.Array] = None,
    coin: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """(features f32[K], dropped-event count i32) for one genome.

    Delay-mode traces longer than ``LONG_TRACE_THRESHOLD`` take the
    blockwise scan (bounded memory under a population vmap — no [P, L]
    intermediates); everything else takes the dense path. The dispatch is
    on static shape, so each jit specialization compiles exactly one
    branch."""
    H = delays.shape[0]
    L = trace.hint_ids.shape[-1]
    if not order_mode and L > LONG_TRACE_THRESHOLD:
        first, ndrop = first_occurrence_blockwise(
            delays, trace.hint_ids, trace.arrival, trace.mask,
            faults=faults, coin=coin, faultable=trace.faultable,
        )
        return precedence_features(first, pairs, tau), ndrop
    eff = apply_faults(trace, faults, coin)
    if faults is None:
        ndrop = jnp.zeros((), jnp.int32)
    else:
        ndrop = (jnp.sum(trace.mask) - jnp.sum(eff.mask)).astype(jnp.int32)
    if order_mode:
        t = order_release_times(delays, eff, order_gap, order_window)
    else:
        t = release_times(delays, eff)
    first = first_occurrence(t, eff, H)
    return precedence_features(first, pairs, tau), ndrop


def schedule_features(
    delays: jax.Array, trace: TraceArrays, pairs: jax.Array, tau: float,
    order_mode: bool = False, order_gap: float = 0.001,
    order_window: float = 0.0,
    faults: Optional[jax.Array] = None,
    coin: Optional[jax.Array] = None,
) -> jax.Array:
    """One genome -> feature vector f32[K]. In order mode the genome is a
    priority table and tau should be of the order of order_gap so adjacent
    ranks still produce saturated precedence features. When ``faults`` (and
    the per-bucket ``coin``) are given, fault-dropped events vanish from
    the counterfactual before first-occurrence — the fault half of the
    genome shapes the features (BASELINE config 4)."""
    feats, _ = _genome_features(delays, trace, pairs, tau, order_mode,
                                order_gap, order_window, faults, coin)
    return feats


def trace_features(
    trace: TraceArrays, pairs: jax.Array, tau: float, H: int
) -> jax.Array:
    """Feature vector of a trace *as recorded* (zero extra delay) — used to
    embed executed runs (including failures) into the same space."""
    zero = jnp.zeros((H,), jnp.float32)
    return schedule_features(zero, trace, pairs, tau)


def _matmul_dtype():
    """bf16 on TPU (MXU-native), f32 elsewhere (the CPU backend has no
    bf16xbf16->f32 dot)."""
    return jnp.bfloat16 if jax.default_backend() in ("tpu", "axon") else jnp.float32


def min_sq_distance(feats: jax.Array, archive: jax.Array,
                    valid_n: Optional[jax.Array] = None) -> jax.Array:
    """min_a ||f_p - a||^2 via the matmul expansion (MXU-friendly).

    feats [P,K], archive [A,K] -> [P]. bf16 inputs on TPU, f32 accumulation.

    ``valid_n`` (optional TRACED i32 scalar) is the archive's occupancy:
    rows at index >= valid_n are masked with :data:`MASK_BIG` so they
    never win the min — equivalent to calling with ``archive[:n]``
    while keeping the buffer shape fixed, so a caller that holds a
    fixed-capacity ring can grow its occupancy without a new jit
    specialization per size (compile-count pinned by
    tests/test_fused_loop.py). ``None`` keeps the pre-occupancy graph:
    every row is live — the in-repo search passes None, because its
    rings deliberately treat unoccupied slots as neutral 0.5 feature
    points (SearchBase), and masking them out would change fitness.
    """
    dt = _matmul_dtype()
    f16 = feats.astype(dt)
    a16 = archive.astype(dt)
    cross = jax.lax.dot_general(
        f16, a16,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [P, A]
    f2 = jnp.sum(feats * feats, axis=-1, keepdims=True)  # [P,1]
    a2 = jnp.sum(archive * archive, axis=-1)  # [A]
    if valid_n is not None:
        a2 = jnp.where(jnp.arange(archive.shape[0]) < valid_n, a2,
                       MASK_BIG)
    d2 = f2 + a2[None, :] - 2.0 * cross
    return jnp.maximum(jnp.min(d2, axis=-1), 0.0)


def _min_sq_distance_best(feats: jax.Array, archive: jax.Array,
                          valid_n: Optional[jax.Array] = None) -> jax.Array:
    """The Pallas fused-min kernel on TPU (~10% whole-scorer win at
    production sizes, no [P,A] HBM round-trip), plain XLA elsewhere.
    Dispatch lives in pallas_score; lazily imported because that module
    imports this one."""
    from namazu_tpu.ops.pallas_score import min_sq_distance_auto

    return min_sq_distance_auto(feats, archive, valid_n=valid_n)


def _min_sq_pair_best(feats: jax.Array, archive: jax.Array,
                      failures: jax.Array,
                      archive_n: Optional[jax.Array] = None,
                      failure_n: Optional[jax.Array] = None
                      ) -> tuple[jax.Array, jax.Array]:
    """(novelty d2 [P], bug d2 [P]) against both archives in one pass:
    the Pallas pair kernel on TPU streams each feats tile through BOTH
    distance mins (one kernel launch, no [P] intermediate round-trips
    between them — the fused score epilogue of doc/performance.md
    "Fused search loop"); two XLA mins elsewhere. An occupancy of zero
    yields a neutral 0.0 distance instead of the mask identity: an
    empty ring carries no information, not an infinitely-far one."""
    from namazu_tpu.ops.pallas_score import min_sq_distance_pair_auto

    nov, bug = min_sq_distance_pair_auto(feats, archive, failures,
                                         archive_n=archive_n,
                                         failure_n=failure_n)
    if archive_n is not None:
        nov = jnp.where(archive_n > 0, nov, 0.0)
    if failure_n is not None:
        bug = jnp.where(failure_n > 0, bug, 0.0)
    return nov, bug


def score_population(
    delays: jax.Array,  # [P, H]
    trace: TraceArrays,
    pairs: jax.Array,  # [K, 2]
    archive: jax.Array,  # [A, K] features of executed schedules
    failure_feats: jax.Array,  # [F, K] features of bug-reproducing runs
    weights: ScoreWeights = ScoreWeights(),
    faults: Optional[jax.Array] = None,  # [P, H] fault probabilities
    coin: Optional[jax.Array] = None,  # [H] deterministic fault coin
    novelty_scale: Optional[jax.Array] = None,  # dynamic f32 scalar
    archive_n: Optional[jax.Array] = None,  # dynamic i32 occupancy
    failure_n: Optional[jax.Array] = None,  # dynamic i32 occupancy
) -> tuple[jax.Array, jax.Array]:
    """Fitness f32[P] and features f32[P,K] for a whole population.

    With ``faults``/``coin``, the genome's fault half is part of the
    counterfactual: dropped events reshape the features, and a
    ``fault_cost`` per dropped event keeps "drop everything" from being
    the novelty optimum. Long delay-mode traces score blockwise (see
    :func:`_genome_features`).

    ``novelty_scale`` multiplies ``weights.novelty`` as a *traced*
    scalar — the novelty-anneal lever (exploration weight decays as the
    failure archive accumulates distinct signatures) without a new jit
    specialization per annealed value. ``None`` keeps the pre-anneal
    graph.

    ``archive_n``/``failure_n`` (traced i32 scalars) are ring
    occupancies for fixed-capacity archive buffers: rows past the
    occupancy are masked out of the distance min, equivalent to slicing
    ``archive[:n]`` but shape-stable, so an external driver whose
    archive grows mid-run pays ZERO recompilations instead of one per
    occupancy (compile-count pinned by test). This is the EXPORTED
    scoring seam's contract; the in-repo search passes ``None`` (the
    default, and the pre-occupancy graphs) on purpose — SearchBase's
    rings treat unoccupied slots as neutral 0.5 feature points, and
    masking them would change fitness."""
    if faults is None:
        feats, _ = jax.vmap(
            lambda d: _genome_features(d, trace, pairs, weights.tau,
                                       weights.order_mode,
                                       weights.order_gap,
                                       weights.order_window)
        )(delays)
        fault_pen = 0.0
    else:
        feats, ndrop = jax.vmap(
            lambda d, f: _genome_features(d, trace, pairs, weights.tau,
                                          weights.order_mode,
                                          weights.order_gap,
                                          weights.order_window,
                                          faults=f, coin=coin)
        )(delays, faults)
        live = jnp.maximum(jnp.sum(trace.mask), 1)
        fault_pen = weights.fault_cost * ndrop / live
    nov_d2, bug_d2 = _min_sq_pair_best(feats, archive, failure_feats,
                                       archive_n=archive_n,
                                       failure_n=failure_n)
    novelty = nov_d2
    bug = -bug_d2
    delay_cost = jnp.mean(delays, axis=-1)
    w_nov = (weights.novelty if novelty_scale is None
             else weights.novelty * novelty_scale)
    fitness = (
        w_nov * novelty
        + weights.bug * bug
        - weights.delay_cost * delay_cost
        - fault_pen
    )
    return fitness, feats


@functools.partial(jax.jit, static_argnames=("weights",))
def score_population_jit(delays, trace, pairs, archive, failure_feats,
                         weights: ScoreWeights = ScoreWeights(),
                         faults=None, coin=None, novelty_scale=None,
                         archive_n=None, failure_n=None):
    """Jitted :func:`score_population`. ``archive_n``/``failure_n`` are
    TRACED occupancy scalars — one compiled specialization serves every
    occupancy of a fixed-capacity archive buffer (the mid-run recompile
    fix; see ``score_population``)."""
    return score_population(delays, trace, pairs, archive, failure_feats,
                            weights, faults=faults, coin=coin,
                            novelty_scale=novelty_scale,
                            archive_n=archive_n, failure_n=failure_n)


# -- multi-trace scoring ----------------------------------------------------


def score_population_multi(
    delays: jax.Array,  # [P, H]
    traces: TraceArrays,  # arrays with leading trace dim [T, L]
    pairs: jax.Array,  # [K, 2]
    archive: jax.Array,  # [A, K]
    failure_feats: jax.Array,  # [F, K]
    weights: ScoreWeights = ScoreWeights(),
    faults: Optional[jax.Array] = None,  # [P, H]
    coin: Optional[jax.Array] = None,  # [H]
    novelty_scale: Optional[jax.Array] = None,  # dynamic f32 scalar
    archive_n: Optional[jax.Array] = None,  # dynamic i32 occupancy
    failure_n: Optional[jax.Array] = None,  # dynamic i32 occupancy
) -> tuple[jax.Array, jax.Array]:
    """Fitness aggregated over T recorded traces.

    A schedule that is only novel against one historical run is usually
    just exploiting that run's noise; averaging novelty/bug affinity over
    every stored trace rewards schedules whose *interleaving structure*
    transfers. Returns (fitness f32[P], feats f32[P, T, K]).
    """
    def per_trace(tr: TraceArrays):
        """(feats [P, K], drop fraction [P]) against one trace."""
        if faults is None:
            f, _ = jax.vmap(
                lambda d: _genome_features(d, tr, pairs, weights.tau,
                                           weights.order_mode,
                                           weights.order_gap,
                                           weights.order_window)
            )(delays)
            return f, jnp.zeros((delays.shape[0],), jnp.float32)
        f, ndrop = jax.vmap(
            lambda d, ft: _genome_features(d, tr, pairs, weights.tau,
                                           weights.order_mode,
                                           weights.order_gap,
                                           weights.order_window,
                                           faults=ft, coin=coin)
        )(delays, faults)
        live = jnp.maximum(jnp.sum(tr.mask), 1)
        return f, ndrop / live

    feats, frac = jax.vmap(per_trace)(traces)  # [T, P, K], [T, P]
    feats = jnp.swapaxes(feats, 0, 1)  # [P, T, K]
    P, T, K = feats.shape
    flat = feats.reshape(P * T, K)
    nov_d2, bug_d2 = _min_sq_pair_best(flat, archive, failure_feats,
                                       archive_n=archive_n,
                                       failure_n=failure_n)
    novelty = nov_d2.reshape(P, T).mean(axis=1)
    bug = -bug_d2.reshape(P, T).mean(axis=1)
    delay_cost = jnp.mean(delays, axis=-1)
    fault_pen = (0.0 if faults is None
                 else weights.fault_cost * frac.mean(axis=0))
    w_nov = (weights.novelty if novelty_scale is None
             else weights.novelty * novelty_scale)
    fitness = (
        w_nov * novelty
        + weights.bug * bug
        - weights.delay_cost * delay_cost
        - fault_pen
    )
    return fitness, feats


# -- long traces: blockwise first-occurrence --------------------------------

# delay-mode traces longer than this are scored blockwise; below it the
# dense path is cheaper (one fused gather + scatter-min). Order mode
# always scores dense: a windowed permutation needs the whole trace in
# one lexsort.
LONG_TRACE_THRESHOLD = 1024
LONG_TRACE_CHUNK = 512


def first_occurrence_blockwise(
    delays: jax.Array,  # [H]
    hint_ids: jax.Array,  # [L], any length (padded internally)
    arrival: jax.Array,  # [L]
    mask: jax.Array,  # [L]
    chunk: int = LONG_TRACE_CHUNK,
    faults: Optional[jax.Array] = None,  # [H]
    coin: Optional[jax.Array] = None,  # [H]
    faultable: Optional[jax.Array] = None,  # [L]
) -> tuple[jax.Array, jax.Array]:
    """(first-occurrence times f32[H], dropped-event count i32) over an
    arbitrarily long trace via lax.scan.

    min is associative, so the [H] running minimum is a scan carry and the
    peak live buffer is one [chunk] block instead of the whole trace —
    the long-sequence analogue of blockwise attention for this workload
    (SURVEY.md section 5.7: schedule genomes over long event traces are
    this framework's long sequences). Fault drops are applied per chunk so
    a vmapped population never materialises a [P, L] drop mask.
    """
    H = delays.shape[0]
    L = hint_ids.shape[0]
    n_chunks = -(-L // chunk)
    pad = n_chunks * chunk - L
    hint_ids = jnp.pad(hint_ids, (0, pad))
    arrival = jnp.pad(arrival, (0, pad))
    mask = jnp.pad(mask, (0, pad))
    if faultable is None:
        faultable = jnp.ones_like(mask)
    else:
        faultable = jnp.pad(faultable, (0, pad))

    def step(carry, blk):
        first, ndrop = carry
        h, a, m, fb = blk
        if faults is not None:
            # one home for the "non-faultable events never drop"
            # invariant: the same drop_mask the dense path uses
            drop = drop_mask(faults, coin, TraceArrays(h, a, m, fb))
            m = m & ~drop
            ndrop = ndrop + jnp.sum(drop)
        t = jnp.where(m, a + delays[h], BIG)
        first = first.at[h].min(t)
        return (first, ndrop), None

    init = (jnp.full((H,), BIG, jnp.float32), jnp.zeros((), jnp.int32))
    (first, ndrop), _ = jax.lax.scan(
        step,
        init,
        (
            hint_ids.reshape(n_chunks, chunk),
            arrival.reshape(n_chunks, chunk),
            mask.reshape(n_chunks, chunk),
            faultable.reshape(n_chunks, chunk),
        ),
    )
    return first, ndrop


def schedule_features_long(
    delays: jax.Array, trace: TraceArrays, pairs: jax.Array, tau: float,
    chunk: int = LONG_TRACE_CHUNK,
    faults: Optional[jax.Array] = None,
    coin: Optional[jax.Array] = None,
) -> jax.Array:
    """Feature vector for long traces (thousands of events) with bounded
    memory; numerically identical to :func:`schedule_features`."""
    first, _ = first_occurrence_blockwise(
        delays, trace.hint_ids, trace.arrival, trace.mask, chunk,
        faults=faults, coin=coin, faultable=trace.faultable,
    )
    return precedence_features(first, pairs, tau)
