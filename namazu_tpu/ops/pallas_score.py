"""Pallas TPU kernel: fused archive-distance + min reduction.

``min_sq_distance`` (namazu_tpu/ops/schedule.py) is the scoring hot spot:
``d2[p,a] = |f_p|^2 + |a|^2 - 2 f_p.a`` followed by a min over ``a``. In
XLA the [P, A] distance matrix is materialized in HBM before the reduce;
at production sizes (P=8192, A=1024) that is 32 MB of HBM round-trip per
scoring call. This kernel tiles the matmul over (P, A) blocks on the MXU
and folds the min into the epilogue, so only the [P] result ever leaves
VMEM.

The kernel is numerically identical to the XLA path (f32 accumulation;
bf16 operands on TPU). ``min_sq_distance_auto`` dispatches: Pallas on TPU,
plain XLA elsewhere (tests run the kernel in interpret mode either way).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from namazu_tpu.ops import schedule as _sched

BIG = 3.4e38  # min-identity for f32


def _kernel(f_ref, a_ref, f2_ref, a2_ref, out_ref):
    """Grid (P/TP, A/TA). Block shapes: f [TP,K], a [TA,K], f2 [TP,1],
    a2 [TA,1] -> out [TP,1] running min across the A-tile axis."""
    j = pl.program_id(1)

    f = f_ref[:]
    a = a_ref[:]
    cross = jax.lax.dot_general(
        f, a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TP, TA]
    d2 = f2_ref[:] + a2_ref[:].reshape(1, -1) - 2.0 * cross
    m = jnp.min(d2, axis=1, keepdims=True)  # [TP, 1]

    @pl.when(j == 0)
    def _init():
        out_ref[:] = m

    @pl.when(j > 0)
    def _acc():
        out_ref[:] = jnp.minimum(out_ref[:], m)


@functools.partial(jax.jit, static_argnames=("tile_p", "tile_a", "interpret"))
def min_sq_distance_pallas(
    feats: jax.Array,  # [P, K] f32
    archive: jax.Array,  # [A, K] f32
    tile_p: int = 256,
    tile_a: int = 256,
    interpret: bool = False,
    valid_n: jax.Array | None = None,  # traced occupancy (None = all)
) -> jax.Array:
    P, K = feats.shape
    A = archive.shape[0]
    if A == 0:
        # min over zero rows is undefined and a zero-length grid axis
        # would leave the output unwritten; callers with a
        # not-yet-populated ring hold a fixed-capacity buffer and mask
        # with valid_n instead (the occupancy contract, ops/schedule.py)
        raise ValueError(
            "min_sq_distance_pallas: empty archive; use a "
            "fixed-capacity buffer with valid_n occupancy masking")
    # pad P and A up to tile multiples; padded archive rows use BIG norms
    # so they never win the min — rows past a ring's occupancy
    # (``valid_n``, a TRACED scalar so occupancy growth never recompiles)
    # are masked the same way
    Pp = -(-P // tile_p) * tile_p
    Ap = -(-A // tile_a) * tile_a
    f = jnp.pad(feats, ((0, Pp - P), (0, 0)))
    a = jnp.pad(archive, ((0, Ap - A), (0, 0)))
    f2 = jnp.sum(f * f, axis=1, keepdims=True)  # [Pp, 1]
    a2 = jnp.sum(a * a, axis=1)
    live = A if valid_n is None else jnp.minimum(valid_n, A)
    a2 = jnp.where(jnp.arange(Ap) < live, a2, BIG).reshape(Ap, 1)

    dt = _sched._matmul_dtype()
    f = f.astype(dt)
    a = a.astype(dt)

    grid = (Pp // tile_p, Ap // tile_a)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_p, K), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_a, K), lambda i, j: (j, 0)),
            pl.BlockSpec((tile_p, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_a, 1), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((tile_p, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Pp, 1), jnp.float32),
        interpret=interpret,
    )(f, a, f2, a2)
    return jnp.maximum(out[:P, 0], 0.0)


def min_sq_distance_auto(feats: jax.Array, archive: jax.Array,
                         valid_n: jax.Array | None = None) -> jax.Array:
    """Pallas on TPU, XLA elsewhere."""
    if jax.default_backend() in ("tpu", "axon"):
        return min_sq_distance_pallas(feats, archive, valid_n=valid_n)
    return _sched.min_sq_distance(feats, archive, valid_n=valid_n)


# -- fused pair distance: score epilogue of the fused search loop ----------


def _pair_kernel(na_tiles, f_ref, c_ref, f2_ref, c2_ref,
                 nov_ref, bug_ref):
    """Grid (P/TP, (Ap+Fp)/TA) over the CONCATENATED archive+failure
    buffer. Each feats tile is loaded once per column tile and streamed
    through whichever running min (novelty vs bug) the column tile
    belongs to — the segment boundary sits on a tile multiple by
    construction, so a tile never straddles both archives. One kernel
    launch scores both distances; neither [P, A] nor [P, F] ever leaves
    VMEM (the "pallas-fused score" half of score->select fusion; the
    select — argmax over the [P] fitness — is XLA's, inside the same
    jitted scan program)."""
    j = pl.program_id(1)

    f = f_ref[:]
    c = c_ref[:]
    cross = jax.lax.dot_general(
        f, c, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TP, TA]
    d2 = f2_ref[:] + c2_ref[:].reshape(1, -1) - 2.0 * cross
    m = jnp.min(d2, axis=1, keepdims=True)  # [TP, 1]

    @pl.when(j == 0)
    def _init_nov():
        nov_ref[:] = m

    @pl.when((j > 0) & (j < na_tiles))
    def _acc_nov():
        nov_ref[:] = jnp.minimum(nov_ref[:], m)

    @pl.when(j == na_tiles)
    def _init_bug():
        bug_ref[:] = m

    @pl.when(j > na_tiles)
    def _acc_bug():
        bug_ref[:] = jnp.minimum(bug_ref[:], m)


@functools.partial(jax.jit, static_argnames=("tile_p", "tile_a", "interpret"))
def min_sq_distance_pair_pallas(
    feats: jax.Array,  # [P, K] f32
    archive: jax.Array,  # [A, K] f32
    failures: jax.Array,  # [F, K] f32
    tile_p: int = 256,
    tile_a: int = 256,
    interpret: bool = False,
    archive_n: jax.Array | None = None,  # traced occupancies
    failure_n: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(min d2 vs archive [P], min d2 vs failures [P]) in one pass.

    Both buffers pad to tile multiples (padded/over-occupancy rows carry
    BIG norms, never winning a min) and concatenate along the row axis;
    the kernel routes each column tile into the right running min by its
    static tile index. Numerically identical to two
    :func:`min_sq_distance_pallas` calls (same tile shapes, same f32
    accumulation) — the win is one launch and one feats read per column
    tile instead of two kernels with separate feats streams."""
    P, K = feats.shape
    A = archive.shape[0]
    F = failures.shape[0]
    if A == 0 or F == 0:
        # an empty segment would break the tile-index routing: with
        # na_tiles == 0 the j == 0 tile would initialize BOTH mins from
        # failures rows, and an empty failures segment would return
        # bug_ref unwritten. Empty-ring callers hold fixed-capacity
        # buffers and mask with archive_n/failure_n instead.
        raise ValueError(
            "min_sq_distance_pair_pallas: empty archive/failures; use "
            "fixed-capacity buffers with archive_n/failure_n occupancy "
            "masking")
    Pp = -(-P // tile_p) * tile_p
    Ap = -(-A // tile_a) * tile_a
    Fp = -(-F // tile_a) * tile_a
    f = jnp.pad(feats, ((0, Pp - P), (0, 0)))
    a = jnp.pad(archive, ((0, Ap - A), (0, 0)))
    fl = jnp.pad(failures, ((0, Fp - F), (0, 0)))
    f2 = jnp.sum(f * f, axis=1, keepdims=True)  # [Pp, 1]
    a2 = jnp.sum(a * a, axis=1)
    live_a = A if archive_n is None else jnp.minimum(archive_n, A)
    a2 = jnp.where(jnp.arange(Ap) < live_a, a2, BIG)
    fl2 = jnp.sum(fl * fl, axis=1)
    live_f = F if failure_n is None else jnp.minimum(failure_n, F)
    fl2 = jnp.where(jnp.arange(Fp) < live_f, fl2, BIG)
    cat = jnp.concatenate([a, fl])  # [Ap + Fp, K]
    cat2 = jnp.concatenate([a2, fl2]).reshape(Ap + Fp, 1)

    dt = _sched._matmul_dtype()
    f = f.astype(dt)
    cat = cat.astype(dt)

    na_tiles = Ap // tile_a
    grid = (Pp // tile_p, (Ap + Fp) // tile_a)
    nov, bug = pl.pallas_call(
        functools.partial(_pair_kernel, na_tiles),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_p, K), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_a, K), lambda i, j: (j, 0)),
            pl.BlockSpec((tile_p, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_a, 1), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile_p, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_p, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Pp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Pp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(f, cat, f2, cat2)
    return (jnp.maximum(nov[:P, 0], 0.0), jnp.maximum(bug[:P, 0], 0.0))


def min_sq_distance_pair_auto(
    feats: jax.Array, archive: jax.Array, failures: jax.Array,
    archive_n: jax.Array | None = None,
    failure_n: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Pallas pair kernel on TPU, two XLA mins elsewhere."""
    if jax.default_backend() in ("tpu", "axon"):
        return min_sq_distance_pair_pallas(
            feats, archive, failures,
            archive_n=archive_n, failure_n=failure_n)
    return (
        _sched.min_sq_distance(feats, archive, valid_n=archive_n),
        _sched.min_sq_distance(feats, failures, valid_n=failure_n),
    )
