"""Pallas TPU kernel: fused archive-distance + min reduction.

``min_sq_distance`` (namazu_tpu/ops/schedule.py) is the scoring hot spot:
``d2[p,a] = |f_p|^2 + |a|^2 - 2 f_p.a`` followed by a min over ``a``. In
XLA the [P, A] distance matrix is materialized in HBM before the reduce;
at production sizes (P=8192, A=1024) that is 32 MB of HBM round-trip per
scoring call. This kernel tiles the matmul over (P, A) blocks on the MXU
and folds the min into the epilogue, so only the [P] result ever leaves
VMEM.

The kernel is numerically identical to the XLA path (f32 accumulation;
bf16 operands on TPU). ``min_sq_distance_auto`` dispatches: Pallas on TPU,
plain XLA elsewhere (tests run the kernel in interpret mode either way).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from namazu_tpu.ops import schedule as _sched

BIG = 3.4e38  # min-identity for f32


def _kernel(f_ref, a_ref, f2_ref, a2_ref, out_ref):
    """Grid (P/TP, A/TA). Block shapes: f [TP,K], a [TA,K], f2 [TP,1],
    a2 [TA,1] -> out [TP,1] running min across the A-tile axis."""
    j = pl.program_id(1)

    f = f_ref[:]
    a = a_ref[:]
    cross = jax.lax.dot_general(
        f, a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TP, TA]
    d2 = f2_ref[:] + a2_ref[:].reshape(1, -1) - 2.0 * cross
    m = jnp.min(d2, axis=1, keepdims=True)  # [TP, 1]

    @pl.when(j == 0)
    def _init():
        out_ref[:] = m

    @pl.when(j > 0)
    def _acc():
        out_ref[:] = jnp.minimum(out_ref[:], m)


@functools.partial(jax.jit, static_argnames=("tile_p", "tile_a", "interpret"))
def min_sq_distance_pallas(
    feats: jax.Array,  # [P, K] f32
    archive: jax.Array,  # [A, K] f32
    tile_p: int = 256,
    tile_a: int = 256,
    interpret: bool = False,
) -> jax.Array:
    P, K = feats.shape
    A = archive.shape[0]
    # pad P and A up to tile multiples; padded archive rows use BIG norms
    # so they never win the min
    Pp = -(-P // tile_p) * tile_p
    Ap = -(-A // tile_a) * tile_a
    f = jnp.pad(feats, ((0, Pp - P), (0, 0)))
    a = jnp.pad(archive, ((0, Ap - A), (0, 0)))
    f2 = jnp.sum(f * f, axis=1, keepdims=True)  # [Pp, 1]
    a2 = jnp.sum(a * a, axis=1)
    a2 = jnp.where(jnp.arange(Ap) < A, a2, BIG).reshape(Ap, 1)

    dt = _sched._matmul_dtype()
    f = f.astype(dt)
    a = a.astype(dt)

    grid = (Pp // tile_p, Ap // tile_a)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_p, K), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_a, K), lambda i, j: (j, 0)),
            pl.BlockSpec((tile_p, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tile_a, 1), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((tile_p, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Pp, 1), jnp.float32),
        interpret=interpret,
    )(f, a, f2, a2)
    return jnp.maximum(out[:P, 0], 0.0)


def min_sq_distance_auto(feats: jax.Array, archive: jax.Array) -> jax.Array:
    """Pallas on TPU, XLA elsewhere."""
    if jax.default_backend() in ("tpu", "axon"):
        return min_sq_distance_pallas(feats, archive)
    return _sched.min_sq_distance(feats, archive)
