"""TPU search-plane ops: trace encoding, schedule scoring, Pallas kernels.

No reference counterpart — this plane replaces the reference's random timer
races (nmz/util/queue/impl.go) with a massively parallel, learned search
over schedule genomes (BASELINE.json north star).
"""
