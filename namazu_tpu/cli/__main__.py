from namazu_tpu.cli import main

main()
