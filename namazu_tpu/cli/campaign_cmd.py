"""``nmz-tpu campaign <storage> -n N`` — the supervised repro loop.

The resilient replacement for ``for i in $(seq 1 N); do nmz-tpu run d;
done`` (BASELINE.md): per-run and per-phase deadlines enforced with
process-group kills, outcome classification (experiment / timeout /
infra), capped-backoff retries for infra failures, a resumable
``campaign.json`` checkpoint, and graceful SIGINT/SIGTERM handling.
Semantics: doc/robustness.md; machinery: namazu_tpu/campaign.py.
"""

from __future__ import annotations

import json
import sys

from namazu_tpu.campaign import (
    Campaign,
    CampaignError,
    CampaignSpec,
    EXIT_USAGE,
    summarize,
)


def register(sub) -> None:
    p = sub.add_parser(
        "campaign",
        help="run N supervised experiments (deadlines, retries, "
             "resumable checkpoint)",
    )
    p.add_argument("storage", help="storage directory created by init")
    p.add_argument("-n", "--runs", type=int, default=10,
                   help="run slots to supervise (default 10)")
    p.add_argument("--wall-deadline", type=float, default=0.0, metavar="S",
                   help="wall-clock deadline for one whole run child; its "
                        "entire process group is killed on expiry "
                        "(0 = none)")
    for phase in ("run", "validate", "clean"):
        p.add_argument(f"--{phase}-deadline", type=float, default=0.0,
                       metavar="S",
                       help=f"deadline forwarded to the child's {phase} "
                            "phase (0 = the storage config's "
                            f"{phase}_deadline_s)")
    p.add_argument("--retries", type=int, default=2,
                   help="extra attempts per slot after an infra/timeout "
                        "failure (default 2)")
    p.add_argument("--backoff-base", type=float, default=1.0, metavar="S",
                   help="base of the capped exponential retry backoff "
                        "(default 1.0s; full jitter)")
    p.add_argument("--backoff-cap", type=float, default=30.0, metavar="S",
                   help="retry backoff cap (default 30s)")
    p.add_argument("--max-consecutive-infra", type=int, default=3,
                   metavar="K",
                   help="stop after K consecutive non-experiment run "
                        "slots (default 3; 0 = never)")
    p.add_argument("--knowledge", default="", metavar="HOST:PORT",
                   help="global failure-knowledge service address, "
                        "forwarded to every run child (doc/knowledge.md)")
    p.add_argument("--virtual-clock", action="store_true",
                   help="forward --virtual-clock to every run child "
                        "(doc/performance.md \"Virtual clock\"): each "
                        "run fast-forwards its scheduled delays, "
                        "decoupling campaign throughput from the "
                        "scenario's idle time; repro classification is "
                        "unchanged at delay-scale 1")
    p.add_argument("--telemetry-collector", default="auto",
                   metavar="PATH",
                   help="fleet telemetry collector socket "
                        "(doc/observability.md \"Fleet telemetry\"): "
                        "the supervisor aggregates every child "
                        "process's metrics here and `tools top --url "
                        "uds://PATH` shows the whole campaign. "
                        "Default: auto (<storage>/telemetry.sock); "
                        "'' disables")
    p.add_argument("--serve", default="", metavar="URL",
                   help="tenancy serve mode (doc/tenancy.md): lease "
                        "namespaced run slots on a shared orchestrator "
                        "(http://host:port or uds:///path) instead of "
                        "forking run children; slots drive their "
                        "workload through the wire and record the "
                        "released trace into the storage")
    p.add_argument("--serve-events", type=int, default=200, metavar="N",
                   help="with --serve: events per slot workload "
                        "(default 200)")
    p.add_argument("--serve-entities", type=int, default=2, metavar="K",
                   help="with --serve: loopback entities per slot "
                        "(default 2)")
    p.add_argument("--serve-ttl", type=float, default=15.0, metavar="S",
                   help="with --serve: lease TTL; the supervisor renews "
                        "at TTL/3, and a crashed slot's namespace is "
                        "reclaimed on expiry (default 15s)")
    p.add_argument("--serve-policy", default="random",
                   help="with --serve: exploration policy for the "
                        "leased namespace (default random)")
    p.add_argument("--no-resume", action="store_true",
                   help="ignore an existing campaign.json and start a "
                        "fresh campaign")
    p.add_argument("--json", action="store_true",
                   help="print the final campaign summary as JSON")
    p.set_defaults(func=run)


def run(args) -> int:
    spec = CampaignSpec(
        storage_dir=args.storage,
        runs=args.runs,
        run_wall_deadline_s=args.wall_deadline,
        run_deadline_s=args.run_deadline,
        validate_deadline_s=args.validate_deadline,
        clean_deadline_s=args.clean_deadline,
        retries=args.retries,
        backoff_base_s=args.backoff_base,
        backoff_cap_s=args.backoff_cap,
        max_consecutive_infra=args.max_consecutive_infra,
        extra_run_args=(["--knowledge", args.knowledge]
                        if args.knowledge else []),
        virtual_clock=args.virtual_clock,
        telemetry_collector=args.telemetry_collector,
        serve_url=args.serve,
        serve_ttl_s=args.serve_ttl,
        serve_events=args.serve_events,
        serve_entities=args.serve_entities,
        serve_policy=args.serve_policy,
    )
    campaign = Campaign(spec)
    try:
        status = campaign.run(resume=not args.no_resume)
    except CampaignError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    summary = summarize(campaign.state)
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        print(f"campaign {summary['stopped_reason']}: "
              f"{summary['experiment']} experiment run(s) recorded over "
              f"{summary['completed_slots']} slot(s) "
              f"({summary['timeout']} timeout, {summary['infra']} infra, "
              f"{summary['interrupted']} interrupted); "
              f"checkpoint {campaign.checkpoint_path}")
    return status
