"""``nmz-tpu init [--force] <config> <materials_dir> <storage_dir>``

Parity: /root/reference/nmz/cli/init.go:108-227 — validate the config,
copy config + materials into the storage dir, create the history storage,
and run the experiment's ``init`` script once.
"""

from __future__ import annotations

import os
import shutil
import sys

from namazu_tpu.policy import create_policy
from namazu_tpu.storage import new_storage
from namazu_tpu.utils.cmd import CmdFactory
from namazu_tpu.utils.config import Config


def register(sub) -> None:
    p = sub.add_parser("init", help="set up an experiment storage directory")
    p.add_argument("--force", action="store_true",
                   help="remove an existing storage dir first")
    p.add_argument("config", help="experiment config (.toml/.json/.yaml)")
    p.add_argument("materials", help="directory with run/validate/clean scripts")
    p.add_argument("storage", help="storage directory to create")
    p.set_defaults(func=run)


def run(args) -> int:
    cfg = Config.from_file(args.config)
    # fail early on a bad policy name (validation parity: init.go checks
    # the config before touching the filesystem)
    from namazu_tpu.policy.plugins import load_policy_plugins

    load_policy_plugins(cfg, args.materials)
    policy = create_policy(cfg.get("explore_policy"))
    policy.load_config(cfg)
    policy.shutdown()

    if os.path.exists(args.storage):
        if not args.force:
            print(f"error: {args.storage} exists (use --force)", file=sys.stderr)
            return 1
        shutil.rmtree(args.storage)
    os.makedirs(args.storage)

    cfg.dump_json(os.path.join(args.storage, "config.json"))
    shutil.copy2(args.config,
                 os.path.join(args.storage, os.path.basename(args.config)))
    # a calibration artifact beside the config (namazu_tpu/calibrate:
    # `tools calibrate` writes it into the example dir) travels with the
    # storage — `run` exports its knob values to the experiment scripts
    calib_src = os.path.join(os.path.dirname(os.path.abspath(args.config)),
                             "calibration.json")
    if os.path.exists(calib_src):
        shutil.copy2(calib_src, os.path.join(args.storage,
                                             "calibration.json"))
    materials_dst = os.path.join(args.storage, "materials")
    shutil.copytree(args.materials, materials_dst)

    storage = new_storage(cfg.get("storage_type"), args.storage)
    storage.create()

    init_script = cfg.get("init")
    if init_script:
        factory = CmdFactory(materials_dir=materials_dst)
        res = factory.run(init_script, cwd=materials_dst)
        if res.returncode != 0:
            print(f"error: init script failed ({res.returncode})", file=sys.stderr)
            return 1
    print(f"initialized {args.storage}")
    return 0
