"""``nmz-tpu chaos [example] --seed S --matrix M`` — the chaos matrix.

Runs the seeded fault-injection scenario matrix
(namazu_tpu/chaos/scenarios.py) through the invariant harness
(namazu_tpu/chaos/harness.py) and reports per-scenario verdicts. Exit
status 0 = every invariant held in every scenario; 1 = at least one
violation (the report names it). The same seed reproduces the same
fault schedule bit-for-bit, so a red matrix is a *repro*, not a flake
— doc/robustness.md "Chaos plane".

The optional example dir (default ``examples/flaky-init``) supplies
the ``explore_policy_param`` table the pipeline scenarios' policy is
configured from; the harness pins the knobs determinism needs (exact
policy delays, seeded RNGs, port 0, no testee fault actions) on top of
it. A missing example dir is an error — a typo must not silently run
the built-in defaults while claiming the example.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

from namazu_tpu.chaos.scenarios import DEFAULT_MATRIX, SCENARIOS, \
    resolve_matrix
from namazu_tpu.utils.log import init_log

DEFAULT_EXAMPLE = "examples/flaky-init"


def register(sub) -> None:
    p = sub.add_parser(
        "chaos",
        help="run the seeded fault-injection matrix against the "
             "serving plane and check the survivability invariants "
             "(doc/robustness.md)")
    p.add_argument("example", nargs="?", default=DEFAULT_EXAMPLE,
                   help="example dir whose config's "
                        "explore_policy_param table seeds the pipeline "
                        "scenarios' policy (determinism knobs pinned "
                        f"on top; default {DEFAULT_EXAMPLE})")
    p.add_argument("--seed", type=int, default=1,
                   help="matrix seed; the whole fault schedule is a "
                        "pure function of it (default 1)")
    p.add_argument("--matrix", default="default",
                   help="comma-separated scenario names, 'default' "
                        f"({','.join(DEFAULT_MATRIX)}), or 'all'")
    p.add_argument("--events", type=int, default=8,
                   help="events per entity per scenario (default 8)")
    p.add_argument("--workdir", default="",
                   help="scenario scratch dir (default: a fresh temp "
                        "dir)")
    p.add_argument("--out", default="",
                   help="write the full JSON report here (the CI "
                        "artifact)")
    p.add_argument("--list", action="store_true",
                   help="list scenarios and exit")
    p.set_defaults(func=run)


def run(args) -> int:
    if args.list:
        for name in sorted(SCENARIOS):
            spec = SCENARIOS[name]
            print(f"{name:<18} [{spec['kind']:<9}] {spec['desc']}")
        return 0
    try:
        names = resolve_matrix(args.matrix)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    cfg_path = os.path.join(args.example, "config.toml")
    if not os.path.exists(cfg_path):
        print(f"error: {cfg_path} not found (the example dir supplies "
              "the pipeline scenarios' policy params)", file=sys.stderr)
        return 2
    from namazu_tpu.utils.config import Config

    base_policy_param = Config.from_file(cfg_path).get(
        "explore_policy_param", {}) or {}
    init_log()
    workdir = args.workdir or tempfile.mkdtemp(prefix="nmz-chaos-")
    os.makedirs(workdir, exist_ok=True)

    from namazu_tpu.chaos.harness import run_matrix

    report = run_matrix(names, args.seed, workdir, events=args.events,
                        base_policy_param=dict(base_policy_param))
    report["example"] = os.path.abspath(args.example)
    report["workdir"] = workdir
    for res in report["scenarios"]:
        verdict = "OK " if res["ok"] else "FAIL"
        print(f"{verdict} {res['scenario']:<18} [{res['kind']:<9}] "
              f"seed={res['seed']} {res['wall_s']}s")
        if not res["ok"]:
            for inv, detail in res["invariants"].items():
                if not detail["ok"]:
                    print(f"     violated: {inv}: "
                          f"{json.dumps(detail, default=str)[:400]}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True, default=str)
        print(f"wrote {args.out}")
    if report["ok"]:
        print(f"chaos matrix green: {len(names)} scenario(s), seed "
              f"{args.seed}")
        return 0
    print(f"chaos matrix RED: violations in "
          f"{', '.join(report['violations'])} (seed {args.seed} "
          "reproduces this exactly)", file=sys.stderr)
    return 1
