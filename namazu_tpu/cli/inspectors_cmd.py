"""``nmz-tpu inspectors proc|fs|ethernet`` — run an inspector process.

Parity: /root/reference/nmz/cli/inspectors (inspectorsutil.go:14-69) —
common flags ``--orchestrator-url``, ``--entity-id``, ``--autopilot``;
with ``local://`` as the URL an embedded autopilot orchestrator is started
in-process (no separate orchestrator needed).
"""

from __future__ import annotations

import os
import sys

from namazu_tpu.inspector.transceiver import new_transceiver
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import init_log


def register(sub) -> None:
    p = sub.add_parser("inspectors", help="run an inspector")
    isub = p.add_subparsers(dest="inspector", required=True)

    pp = isub.add_parser("proc", help="process-scheduling inspector")
    _common_flags(pp)
    pp.add_argument("--pid", type=int, default=None, help="root PID to watch")
    pp.add_argument("--cmd", default=None,
                    help="spawn this shell command and watch it instead")
    pp.add_argument("--watch-interval", type=float, default=1.0,
                    help="seconds between procfs snapshots")
    pp.set_defaults(func=run_proc)

    pf = isub.add_parser("fs", help="filesystem inspector")
    _common_flags(pf)
    pf.add_argument("--mount-point", default=None)
    pf.add_argument("--original-dir", default=None)
    pf.add_argument("--cmd", default=None,
                    help="spawn this shell command under the LD_PRELOAD "
                         "interposer (probes the target binary first: "
                         "statically linked testees fail loudly instead "
                         "of silently producing zero events)")
    pf.add_argument("--root", default=None,
                    help="watched subtree (NMZ_TPU_FS_ROOT) for --cmd")
    pf.add_argument("--preload-lib", default=None,
                    help="libnmz_fs_interpose.so path (default: the "
                         "in-tree native/build)")
    pf.add_argument("--agent-addr", default=None,
                    help="host:port of a running agent endpoint; default "
                         "= embedded autopilot orchestrator")
    pf.set_defaults(func=run_fs)

    pe = isub.add_parser("ethernet", help="ethernet (packet) inspector")
    _common_flags(pe)
    pe.add_argument("--listen", default=None,
                    help="proxy listen address host:port")
    pe.add_argument("--upstream", default=None,
                    help="upstream address host:port")
    pe.add_argument("--parser", default=None,
                    help="semantic parser: zookeeper (protocol by upstream "
                         "port), zookeeper-fle, zookeeper-zab, "
                         "zookeeper-client, http/etcd")
    pe.add_argument("--udp", action="store_true",
                    help="relay UDP datagrams instead of a TCP stream "
                         "(per-datagram defer/drop/reorder)")
    pe.add_argument("--hookswitch", default=None,
                    help="serve hookswitch verdicts on this ZMQ address "
                         "(e.g. ipc:///tmp/hookswitch-socket) instead of "
                         "proxying; raw ethernet frames from an external "
                         "switch, any-IP capture")
    pe.add_argument("--no-tcp-watcher", action="store_true",
                    help="disable TCP retransmit suppression "
                         "(hookswitch mode)")
    pe.set_defaults(func=run_ethernet)


def _common_flags(p) -> None:
    p.add_argument("--orchestrator-url", default="local://",
                   help="local:// (autopilot), http://host:port, or "
                        "uds:///path/to.sock (same-host framed wire)")
    p.add_argument("--entity-id", default=None)
    p.add_argument("--autopilot", default=None,
                   help="config file for the embedded autopilot orchestrator")
    p.add_argument("--edge", action="store_true",
                   help="zero-RTT edge dispatch (doc/performance.md): "
                        "decide deferred events locally against the "
                        "orchestrator's published delay table, with "
                        "asynchronous trace backhaul; falls back to "
                        "the central wire until a table is published")
    p.add_argument("--edge-shards", type=int, default=0, metavar="K",
                   help="with --edge: hash this process's entities "
                        "across K shared shard engines (per-shard "
                        "release + backhaul workers; "
                        "doc/performance.md \"Binary wire + sharded "
                        "edge\"); 0 = one dispatcher per entity")
    p.add_argument("--codec", default="auto",
                   choices=("auto", "json", "binary"),
                   help="wire codec preference: auto negotiates the "
                        "binary signal codec per connection (JSON "
                        "stays the default for pre-binary peers), "
                        "json pins the legacy wire")


def _make_transceiver(args, default_entity: str):
    """Build transceiver (+ autopilot orchestrator for local://)."""
    # chaos harnesses reach wire seams inside inspector processes via
    # the environment (doc/robustness.md); a no-op unless NMZ_CHAOS set
    from namazu_tpu import chaos

    chaos.install_from_env()
    entity = args.entity_id or default_entity
    url = args.orchestrator_url
    if url.startswith("local://"):
        from namazu_tpu.orchestrator import AutopilotOrchestrator

        cfg = Config.from_file(args.autopilot) if args.autopilot else Config()
        orc = AutopilotOrchestrator(cfg)
        orc.start()
        trans = new_transceiver(url, entity, orc.local_endpoint)
        return trans, orc
    # fleet telemetry (doc/observability.md "Fleet telemetry"): an
    # inspector process is a producer — it pushes its registry (edge
    # gauges, interception counters) to the orchestrator it already
    # talks to (REST or uds both answer the telemetry push), which
    # merges it into /fleet and forwards it up any federation hop.
    # $NMZ_TELEMETRY_URL overrides the target (e.g. straight to a
    # campaign supervisor's collector).
    from namazu_tpu.obs import federation

    push_url = os.environ.get("NMZ_TELEMETRY_URL", "") or url
    if push_url.startswith("shm://"):
        # the shm ring is one-way; telemetry rides the uds control
        # wire of the same endpoint
        push_url = "uds://" + push_url[len("shm://"):]
    if not push_url.startswith(("http://", "https://", "uds://",
                                "tcp://")):
        push_url = ""  # e.g. agent:// — no telemetry wire; stay local
    federation.ensure_self_relay(
        "inspector", push_url=push_url,
        instance=federation.default_instance(entity))
    # continuous profiling: the inspector's profile (edge decide /
    # release hot paths) rides the same relay as a delta payload
    from namazu_tpu.obs import profiling

    profiling.ensure_profiler("inspector")
    return new_transceiver(
        url, entity,
        edge=bool(getattr(args, "edge", False)),
        edge_shards=int(getattr(args, "edge_shards", 0) or 0),
        codec=str(getattr(args, "codec", "auto") or "auto")), None


def run_proc(args) -> int:
    init_log()
    from namazu_tpu.inspector.proc import ProcInspector, serve_with_command

    if (args.pid is None) == (args.cmd is None):
        print("error: exactly one of --pid / --cmd is required", file=sys.stderr)
        return 1
    trans, orc = _make_transceiver(args, "_nmz_proc_inspector")
    try:
        if args.cmd is not None:
            return serve_with_command(
                trans, ["sh", "-c", args.cmd],
                entity_id=trans.entity_id,
                watch_interval=args.watch_interval,
            )
        inspector = ProcInspector(
            trans, args.pid,
            entity_id=trans.entity_id,
            watch_interval=args.watch_interval,
        )
        inspector.serve()
        return 0
    finally:
        if orc is not None:
            orc.shutdown()


def run_fs(args) -> int:
    init_log()
    if args.cmd is not None:
        return _run_fs_preload(args)
    from namazu_tpu.inspector.fs import serve_fs_inspector

    if not (args.mount_point and args.original_dir):
        print("error: --mount-point and --original-dir are required "
              "(or use --cmd for the LD_PRELOAD launcher)",
              file=sys.stderr)
        return 1
    trans, orc = _make_transceiver(args, "_nmz_fs_inspector")
    try:
        return serve_fs_inspector(trans, args.mount_point, args.original_dir)
    finally:
        if orc is not None:
            orc.shutdown()


def _default_preload_lib() -> str:
    import os

    import namazu_tpu

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(
        namazu_tpu.__file__)))
    return os.path.join(pkg, "native", "build", "libnmz_fs_interpose.so")


def _run_fs_preload(args) -> int:
    """Launch a testee under the LD_PRELOAD interposer, fail-loud.

    Two silent-failure modes of preload interposition are made loud
    (the reference's FUSE hooks, fs.go:56-74, cannot no-op this way):

    * statically linked testee — the dynamic loader never runs, so the
      hooks never load; detected UP FRONT via the ELF PT_INTERP probe;
    * zero intercepted events (wrong --root, testee never touched the
      watched subtree) — detected AFTER the run from the recorded trace
      (embedded-orchestrator mode).
    """
    import os
    import shlex
    import shutil as _shutil
    import subprocess

    from namazu_tpu.utils.elf import has_program_interpreter

    if not args.root:
        print("error: --root is required with --cmd", file=sys.stderr)
        return 1
    if args.orchestrator_url != "local://" and not args.agent_addr:
        # the preloaded testee speaks the framed-TCP agent protocol, not
        # REST; silently ignoring the URL would send its events to a
        # fresh embedded orchestrator while the one the user pointed at
        # sees nothing
        print("error: --cmd mode talks the agent protocol; for a remote "
              "orchestrator pass --agent-addr host:port (its agent "
              "endpoint), not --orchestrator-url", file=sys.stderr)
        return 1
    lib = os.path.abspath(args.preload_lib or _default_preload_lib())
    if not os.path.exists(lib):
        print(f"error: interposer library not found: {lib}\n"
              "build it with: make -C native", file=sys.stderr)
        return 1

    # Probe the command's target binary. --cmd runs through `sh -c`, so
    # the probe inspects the first token (the common case: a single
    # program invocation); shell builtins/pipelines probe as None.
    tokens = shlex.split(args.cmd)
    target = _shutil.which(tokens[0]) if tokens else None
    interp = has_program_interpreter(target) if target else None
    if interp is False:
        print(
            f"error: {target} is a statically linked executable — "
            "LD_PRELOAD interposition is silently ignored for it, so the "
            "run would produce zero filesystem events and look healthy. "
            "Use a dynamically linked build of the testee, or "
            "library-level interposition (namazu_tpu.inspector.fs."
            "InterposedFs).", file=sys.stderr)
        return 1
    if interp is None and target:
        print(f"note: cannot probe {target} (not ELF — a script?); "
              "interposability depends on what it executes",
              file=sys.stderr)

    entity = args.entity_id or "_nmz_fs_preload"
    env = dict(os.environ,
               LD_PRELOAD=lib,
               NMZ_TPU_ENTITY_ID=entity,
               NMZ_TPU_FS_ROOT=os.path.abspath(args.root))

    if args.agent_addr:
        # remote orchestrator: no trace visibility from here, so only
        # the up-front probe can be enforced
        env["NMZ_TPU_AGENT_ADDR"] = args.agent_addr
        return subprocess.run(["sh", "-c", args.cmd], env=env).returncode

    from namazu_tpu.orchestrator import Orchestrator
    from namazu_tpu.policy import create_policy

    cfg = Config.from_file(args.autopilot) if args.autopilot else Config()
    # agent_port 0 makes the default hub include an agent endpoint on an
    # auto-assigned port (orchestrator/core.py; same wiring container.py
    # uses) — and a rest_port in the --autopilot config still works
    cfg.set("agent_port", 0)
    if args.autopilot:
        from namazu_tpu.policy.plugins import load_policy_plugins

        load_policy_plugins(
            cfg, os.path.dirname(os.path.abspath(args.autopilot)))
    policy = create_policy(cfg.get("explore_policy"))
    policy.load_config(cfg)
    orc = Orchestrator(cfg, policy, collect_trace=True)
    orc.start()
    agent = orc.hub.endpoint("agent")
    env["NMZ_TPU_AGENT_ADDR"] = f"127.0.0.1:{agent.port}"
    try:
        rc = subprocess.run(["sh", "-c", args.cmd], env=env).returncode
    finally:
        trace = orc.shutdown()
    n_fs = sum(1 for a in trace if a.event_class == "FilesystemEvent")
    if n_fs == 0:
        print(
            "error: the run completed but ZERO filesystem events were "
            f"intercepted under {args.root!r}. Either the testee never "
            "touched the watched subtree, or interposition did not load "
            "(statically linked helper? exec of a static child?). "
            "Refusing to report this as a clean run.", file=sys.stderr)
        return 1
    print(f"{n_fs} filesystem events intercepted; testee exited {rc}")
    return rc


def make_parser(name, upstream: str = ""):
    """Resolve a --parser flag value to a PacketParser (or None)."""
    if not name:
        return None
    if name == "zookeeper":
        from namazu_tpu.inspector.zookeeper import zk_parser_for_port

        _, _, port = upstream.rpartition(":")
        return zk_parser_for_port(int(port or 0))
    if name.startswith("zookeeper-"):
        from namazu_tpu.inspector.zookeeper import ZkStreamParser

        return ZkStreamParser(name[len("zookeeper-"):])
    if name in ("http", "etcd"):
        from namazu_tpu.inspector.http_parser import HttpStreamParser

        return HttpStreamParser()
    raise ValueError(f"unknown parser {name!r}")


def run_ethernet(args) -> int:
    init_log()
    from namazu_tpu.inspector.ethernet import serve_proxy_inspector

    if args.hookswitch:
        if args.udp:
            print("error: --udp and --hookswitch are mutually exclusive "
                  "(the switch sends raw frames of any protocol)",
                  file=sys.stderr)
            return 1
        from namazu_tpu.inspector.hookswitch import (
            serve_hookswitch_inspector,
            zmq_available,
        )

        if not zmq_available():
            print("error: the hookswitch backend needs pyzmq; use the "
                  "TCP-proxy or UDP backends instead", file=sys.stderr)
            return 1
        trans, orc = _make_transceiver(args, "_nmz_ethernet_inspector")
        try:
            return serve_hookswitch_inspector(
                trans, args.hookswitch,
                enable_tcp_watcher=not args.no_tcp_watcher)
        finally:
            if orc is not None:
                orc.shutdown()
    if not (args.listen and args.upstream):
        print("error: --listen and --upstream are required", file=sys.stderr)
        return 1
    try:
        parser = make_parser(args.parser, args.upstream)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.udp and parser is not None and hasattr(parser, "segment"):
        print(f"error: --parser {args.parser} is a stream parser and "
              "cannot apply to UDP datagrams", file=sys.stderr)
        return 1
    trans, orc = _make_transceiver(args, "_nmz_ethernet_inspector")
    try:
        return serve_proxy_inspector(trans, args.listen, args.upstream,
                                     parser=parser, udp=args.udp)
    finally:
        if orc is not None:
            orc.shutdown()
