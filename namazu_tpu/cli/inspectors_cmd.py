"""``nmz-tpu inspectors proc|fs|ethernet`` — run an inspector process.

Parity: /root/reference/nmz/cli/inspectors (inspectorsutil.go:14-69) —
common flags ``--orchestrator-url``, ``--entity-id``, ``--autopilot``;
with ``local://`` as the URL an embedded autopilot orchestrator is started
in-process (no separate orchestrator needed).
"""

from __future__ import annotations

import sys

from namazu_tpu.inspector.transceiver import new_transceiver
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import init_log


def register(sub) -> None:
    p = sub.add_parser("inspectors", help="run an inspector")
    isub = p.add_subparsers(dest="inspector", required=True)

    pp = isub.add_parser("proc", help="process-scheduling inspector")
    _common_flags(pp)
    pp.add_argument("--pid", type=int, default=None, help="root PID to watch")
    pp.add_argument("--cmd", default=None,
                    help="spawn this shell command and watch it instead")
    pp.add_argument("--watch-interval", type=float, default=1.0,
                    help="seconds between procfs snapshots")
    pp.set_defaults(func=run_proc)

    pf = isub.add_parser("fs", help="filesystem inspector")
    _common_flags(pf)
    pf.add_argument("--mount-point", default=None)
    pf.add_argument("--original-dir", default=None)
    pf.set_defaults(func=run_fs)

    pe = isub.add_parser("ethernet", help="ethernet (packet) inspector")
    _common_flags(pe)
    pe.add_argument("--listen", default=None,
                    help="proxy listen address host:port")
    pe.add_argument("--upstream", default=None,
                    help="upstream address host:port")
    pe.add_argument("--parser", default=None,
                    help="semantic parser: zookeeper (protocol by upstream "
                         "port), zookeeper-fle, zookeeper-zab, "
                         "zookeeper-client, http/etcd")
    pe.set_defaults(func=run_ethernet)


def _common_flags(p) -> None:
    p.add_argument("--orchestrator-url", default="local://",
                   help="local:// (autopilot) or http://host:port")
    p.add_argument("--entity-id", default=None)
    p.add_argument("--autopilot", default=None,
                   help="config file for the embedded autopilot orchestrator")


def _make_transceiver(args, default_entity: str):
    """Build transceiver (+ autopilot orchestrator for local://)."""
    entity = args.entity_id or default_entity
    url = args.orchestrator_url
    if url.startswith("local://"):
        from namazu_tpu.orchestrator import AutopilotOrchestrator

        cfg = Config.from_file(args.autopilot) if args.autopilot else Config()
        orc = AutopilotOrchestrator(cfg)
        orc.start()
        trans = new_transceiver(url, entity, orc.local_endpoint)
        return trans, orc
    return new_transceiver(url, entity), None


def run_proc(args) -> int:
    init_log()
    from namazu_tpu.inspector.proc import ProcInspector, serve_with_command

    if (args.pid is None) == (args.cmd is None):
        print("error: exactly one of --pid / --cmd is required", file=sys.stderr)
        return 1
    trans, orc = _make_transceiver(args, "_nmz_proc_inspector")
    try:
        if args.cmd is not None:
            return serve_with_command(
                trans, ["sh", "-c", args.cmd],
                entity_id=trans.entity_id,
                watch_interval=args.watch_interval,
            )
        inspector = ProcInspector(
            trans, args.pid,
            entity_id=trans.entity_id,
            watch_interval=args.watch_interval,
        )
        inspector.serve()
        return 0
    finally:
        if orc is not None:
            orc.shutdown()


def run_fs(args) -> int:
    init_log()
    from namazu_tpu.inspector.fs import serve_fs_inspector

    if not (args.mount_point and args.original_dir):
        print("error: --mount-point and --original-dir are required",
              file=sys.stderr)
        return 1
    trans, orc = _make_transceiver(args, "_nmz_fs_inspector")
    try:
        return serve_fs_inspector(trans, args.mount_point, args.original_dir)
    finally:
        if orc is not None:
            orc.shutdown()


def make_parser(name, upstream: str = ""):
    """Resolve a --parser flag value to a PacketParser (or None)."""
    if not name:
        return None
    if name == "zookeeper":
        from namazu_tpu.inspector.zookeeper import zk_parser_for_port

        _, _, port = upstream.rpartition(":")
        return zk_parser_for_port(int(port or 0))
    if name.startswith("zookeeper-"):
        from namazu_tpu.inspector.zookeeper import ZkStreamParser

        return ZkStreamParser(name[len("zookeeper-"):])
    if name in ("http", "etcd"):
        from namazu_tpu.inspector.http_parser import HttpStreamParser

        return HttpStreamParser()
    raise ValueError(f"unknown parser {name!r}")


def run_ethernet(args) -> int:
    init_log()
    from namazu_tpu.inspector.ethernet import serve_proxy_inspector

    if not (args.listen and args.upstream):
        print("error: --listen and --upstream are required", file=sys.stderr)
        return 1
    try:
        parser = make_parser(args.parser, args.upstream)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    trans, orc = _make_transceiver(args, "_nmz_ethernet_inspector")
    try:
        return serve_proxy_inspector(trans, args.listen, args.upstream,
                                     parser=parser)
    finally:
        if orc is not None:
            orc.shutdown()
