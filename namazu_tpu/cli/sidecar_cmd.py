"""``nmz-tpu sidecar`` — run the persistent search sidecar.

The orchestrator ⇄ JAX boundary of SURVEY.md §5.8: a long-lived process
holding the compiled search plane (device mesh, jitted GA/MCTS step,
archives) that per-run policies query over loopback instead of paying
search construction + jit warm-up inside every two-second experiment
process. Point a policy at it with ``sidecar = "127.0.0.1:10990"`` in
``explore_policy_param``.
"""

from __future__ import annotations


def register(sub) -> None:
    p = sub.add_parser("sidecar", help="persistent search sidecar")
    p.add_argument("--listen", default="127.0.0.1:10990",
                   help="host:port to serve on (default 127.0.0.1:10990)")
    p.add_argument("--platform", default="",
                   help="jax platform override (e.g. cpu); empty = "
                        "process default")
    p.add_argument("--pool-dir", default="",
                   help="global failure-pool directory: enables the "
                        "multi-tenant knowledge service (pool_push/"
                        "pool_pull/surrogate_predict/stats ops, "
                        "doc/knowledge.md); empty = search ops only")
    p.add_argument("--state-dir", default="",
                   help="knowledge-service state directory (scenario "
                        "tables, surrogate examples); default: the "
                        "pool dir")
    p.add_argument("--telemetry-url", default="",
                   help="push this process's metrics to a fleet "
                        "aggregator (doc/observability.md \"Fleet "
                        "telemetry\"): http://host:port (orchestrator "
                        "REST) or uds:///path (campaign collector). "
                        "Defaults to $NMZ_TELEMETRY_URL")
    p.set_defaults(func=run_sidecar)


def run_sidecar(args) -> int:
    from namazu_tpu.utils.log import init_log

    init_log()
    if args.platform:
        import os

        os.environ["JAX_PLATFORMS"] = args.platform
        import jax

        try:
            jax.config.update("jax_platforms", args.platform)
        except Exception:
            pass
        if args.platform == "cpu":
            os.environ["PALLAS_AXON_POOL_IPS"] = ""
    from namazu_tpu.sidecar import serve_sidecar

    host, _, port = args.listen.rpartition(":")
    return serve_sidecar(host or "127.0.0.1", int(port),
                         pool_dir=args.pool_dir, state_dir=args.state_dir,
                         telemetry_url=args.telemetry_url)
