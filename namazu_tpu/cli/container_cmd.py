"""``nmz-tpu container run [-v HOST:CONT]... IMAGE CMD...``

Parity: the reference's docker-like `nmz container run`
(/root/reference/nmz/cli/container/run/run.go:83-124). Gated on a docker
CLI being present; see namazu_tpu/container.py for the interception
wiring (LD_PRELOAD interposer + proc inspector instead of FUSE + NFQUEUE).
"""

from __future__ import annotations

import sys

from namazu_tpu.container import ContainerRunError, run_container
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import init_log


def register(sub) -> None:
    p = sub.add_parser("container", help="fuzz a containerized testee")
    csub = p.add_subparsers(dest="container_cmd", required=True)
    pr = csub.add_parser("run", help="docker-like run with fuzzing pre-wired")
    pr.add_argument("-v", "--volume", action="append", default=[],
                    help="HOST:CONT bind mount (repeatable)")
    pr.add_argument("--autopilot", default=None,
                    help="config for the embedded orchestrator")
    pr.add_argument("--fs-root", default="/data",
                    help="container path subtree to intercept")
    pr.add_argument("image")
    pr.add_argument("command", nargs="+")
    pr.set_defaults(func=run)


def run(args) -> int:
    init_log()
    cfg = Config.from_file(args.autopilot) if args.autopilot else Config()
    try:
        return run_container(
            args.image, args.command,
            volumes=args.volume, config=cfg, fs_root=args.fs_root,
        )
    except ContainerRunError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
