"""``nmz-tpu orchestrator [--config FILE]`` — standalone orchestrator.

Parity: /root/reference/nmz/cli/orchestrator.go:21-66 — REST on port 10080
by default; runs until interrupted. Used when inspectors live in other
processes/hosts and there is no experiment loop (no trace recording).
"""

from __future__ import annotations

import os
import signal as _signal
import threading

from namazu_tpu.orchestrator import Orchestrator
from namazu_tpu.policy import create_policy
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import init_log

DEFAULT_REST_PORT = 10080


def register(sub) -> None:
    p = sub.add_parser("orchestrator", help="run a standalone orchestrator")
    p.add_argument("--config", default=None, help="config file")
    p.add_argument("--rest-port", type=int, default=None,
                   help=f"REST port (default {DEFAULT_REST_PORT}; 0 = auto)")
    p.add_argument("--journal-dir", default=None,
                   help="crash-recovery event journal dir "
                        "(doc/robustness.md): a restarted orchestrator "
                        "pointed at the same dir resumes the parked "
                        "events a kill -9 stranded")
    p.add_argument("--serve", action="store_true",
                   help="host the tenancy plane (doc/tenancy.md): N "
                        "concurrent campaigns lease namespaced run "
                        "slots on this one orchestrator over the wire "
                        "(POST /api/v3/tenancy, framed lease ops); "
                        "clients without a run namespace land in the "
                        "default namespace unchanged")
    p.add_argument("--uds", default=None, metavar="PATH",
                   help="also serve the framed uds:// wire on PATH "
                        "(events + lease ops without a TCP port)")
    p.set_defaults(func=run)


def run(args) -> int:
    init_log()
    # chaos fault plans reach standalone orchestrators via NMZ_CHAOS
    # (no-op unless set; doc/robustness.md "Chaos plane")
    from namazu_tpu import chaos

    chaos.install_from_env()
    cfg = Config.from_file(args.config) if args.config else Config()
    if args.rest_port is not None:
        cfg.set("rest_port", args.rest_port)
    elif int(cfg.get("rest_port", -1)) < 0:
        cfg.set("rest_port", DEFAULT_REST_PORT)
    if args.journal_dir:
        cfg.set("event_journal_dir", args.journal_dir)

    from namazu_tpu.policy.plugins import load_policy_plugins

    # no storage here: relative plugin paths resolve against the
    # config file's directory
    load_policy_plugins(
        cfg, os.path.dirname(os.path.abspath(args.config))
        if args.config else None)
    if args.uds:
        cfg.set("uds_path", args.uds)
    policy = create_policy(cfg.get("explore_policy"))
    policy.load_config(cfg)
    if args.serve:
        from namazu_tpu.tenancy.host import TenantOrchestrator

        orchestrator = TenantOrchestrator(cfg, policy,
                                          collect_trace=False)
    else:
        orchestrator = Orchestrator(cfg, policy, collect_trace=False)
    orchestrator.start()
    rest = orchestrator.hub.endpoint("rest")
    mode = "tenancy host" if args.serve else "orchestrator"
    print(f"{mode} ready (REST port {rest.port}"
          + (f", uds {args.uds}" if args.uds else "")
          + "); Ctrl-C to stop", flush=True)

    stop = threading.Event()
    _signal.signal(_signal.SIGINT, lambda *a: stop.set())
    _signal.signal(_signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    orchestrator.shutdown()
    return 0
