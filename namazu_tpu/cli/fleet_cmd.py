"""``nmz-tpu fleet`` — the fleet-of-fleets placement plane.

``fleet serve`` runs the placement service over a pool of orchestrator
hosts (doc/tenancy.md "Fleet of fleets"); ``fleet status`` renders the
one-surface pool document; ``fleet drain`` gracefully migrates a
host's leases onto its siblings. Point ``nmz-tpu campaign --serve`` at
the pool's ``uds://``/``tcp://`` url exactly as it would point at a
single orchestrator — the pool speaks the same tenancy wire.
"""

from __future__ import annotations

import json
import signal
import threading


def register(sub) -> None:
    p = sub.add_parser("fleet",
                       help="placement plane over a pool of "
                            "orchestrator hosts")
    fsub = p.add_subparsers(dest="fleet_command", required=True)

    srv = fsub.add_parser("serve", help="run the placement service")
    srv.add_argument("--host", action="append", default=[],
                     metavar="NAME=URL", required=False,
                     help="pool member: name=url (repeat per host; "
                          "url is the orchestrator's workload url, "
                          "http://host:port or uds:///path)")
    srv.add_argument("--state-dir", required=True,
                     help="pool state directory (lease records + "
                          "namespace journals; must be on a "
                          "filesystem all hosts share)")
    srv.add_argument("--listen", action="append", default=[],
                     metavar="URL",
                     help="serve the pool wire on uds:///path or "
                          "tcp://host:port (repeatable; default "
                          "uds://<state-dir>/fleet.sock)")
    srv.add_argument("--ttl", type=float, default=15.0,
                     help="default pool-lease TTL seconds (default 15)")
    srv.add_argument("--max-runs-per-host", type=int, default=8,
                     help="slot cap per host (default 8)")
    srv.add_argument("--admission-burn-max", type=float, default=1.0,
                     help="refuse new leases when the pool's worst SLO "
                          "burn reaches this (default 1.0)")
    srv.add_argument("--monitor-interval", type=float, default=0.5,
                     help="seconds between snapshot/migration ticks "
                          "(default 0.5)")
    srv.add_argument("--dead-after", type=float, default=3.0,
                     help="declare a silent host dead after this many "
                          "seconds (default 3)")
    srv.set_defaults(func=run_serve)

    st = fsub.add_parser("status", help="render the pool document")
    st.add_argument("--url", required=True,
                    help="pool wire url (uds:///path or tcp://host:port)")
    st.add_argument("--json", action="store_true",
                    help="raw JSON instead of the table")
    st.set_defaults(func=run_status)

    dr = fsub.add_parser("drain",
                         help="migrate one host's leases onto its "
                              "siblings")
    dr.add_argument("--url", required=True,
                    help="pool wire url (uds:///path or tcp://host:port)")
    dr.add_argument("host", help="pool host name to drain")
    dr.set_defaults(func=run_drain)


def run_serve(args) -> int:
    import os

    from namazu_tpu.fleet import PlacementService
    from namazu_tpu.utils.log import get_logger, init_log

    init_log()
    log = get_logger("fleet")
    if not args.host:
        log.error("no pool members: pass --host name=url at least once")
        return 2
    svc = PlacementService(
        args.state_dir, default_ttl_s=args.ttl,
        max_runs_per_host=args.max_runs_per_host,
        admission_burn_max=args.admission_burn_max,
        monitor_interval_s=args.monitor_interval,
        dead_after_s=args.dead_after)
    for spec in args.host:
        svc.add_host(spec)
    listens = list(args.listen) or [
        "uds://" + os.path.join(os.path.abspath(args.state_dir),
                                "fleet.sock")]
    svc.start()
    try:
        for url in listens:
            if url.startswith("uds://"):
                svc.serve_unix(url[len("uds://"):])
            elif url.startswith("tcp://"):
                hostport = url[len("tcp://"):]
                host, _, port = hostport.rpartition(":")
                svc.serve_tcp(host or "127.0.0.1", int(port or 0))
            else:  # a bare path is a unix socket
                svc.serve_unix(url)
        for url in svc.serve_urls:
            log.info("fleet placement service on %s (%d host(s))", url,
                     len(args.host))
        stop = threading.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: stop.set())
        stop.wait()
    finally:
        svc.shutdown()
    return 0


def _pool_client(url: str):
    from namazu_tpu.fleet import FleetClient

    return FleetClient(url)


def render_pool(pool: dict) -> str:
    """The ``fleet status`` / ``tools top --pool`` table: one view of
    hosts, placements, and the service's counters."""
    lines = []
    hosts = pool.get("hosts") or []
    lines.append(f"pool: {len(hosts)} host(s)  "
                 f"state_dir={pool.get('state_dir', '')}")
    lines.append(f"{'HOST':<12} {'STATE':<9} {'RUNS':>4} {'EV/S':>9} "
                 f"{'PARKED':>7} {'BURN':>6} {'AGE':>6}  URL")
    for h in hosts:
        s = h.get("summary") or {}
        lines.append(
            f"{h.get('name', ''):<12} {h.get('state', ''):<9} "
            f"{s.get('runs', 0):>4} "
            f"{float(s.get('events_per_sec') or 0.0):>9.1f} "
            f"{s.get('parked', 0):>7} "
            f"{float(s.get('max_burn') or 0.0):>6.2f} "
            f"{float(h.get('last_ok_age_s') or 0.0):>6.1f}  "
            f"{h.get('url', '')}")
    leases = pool.get("leases") or []
    lines.append(f"leases: {len(leases)}")
    if leases:
        lines.append(f"  {'RUN':<28} {'HOST':<12} {'STATE':<8} "
                     f"{'MIGR':>4} {'TTL':>6} {'LEFT':>7}")
        for l in sorted(leases, key=lambda x: str(x.get("run"))):
            lines.append(
                f"  {str(l.get('run', '')):<28} "
                f"{str(l.get('host') or '-'):<12} "
                f"{str(l.get('state', '')):<8} "
                f"{l.get('migrations', 0):>4} "
                f"{float(l.get('ttl_s') or 0.0):>6.1f} "
                f"{float(l.get('expires_in_s') or 0.0):>7.2f}")
    counters = pool.get("counters") or {}
    if counters:
        lines.append("counters: " + "  ".join(
            f"{k}={v}" for k, v in sorted(counters.items())))
    return "\n".join(lines)


def run_status(args) -> int:
    client = _pool_client(args.url)
    try:
        pool = client.pool_status()
    finally:
        client.close()
    if args.json:
        print(json.dumps(pool, indent=2, sort_keys=True))
    else:
        print(render_pool(pool))
    return 0


def run_drain(args) -> int:
    client = _pool_client(args.url)
    try:
        doc = client.drain(args.host)
    finally:
        client.close()
    print(f"drained {doc.get('host')}: {doc.get('migrated', 0)} "
          "lease(s) re-placed")
    return 0
