"""``nmz-tpu tools summary|dump-trace|visualize`` — experiment analysis.

Parity: /root/reference/nmz/cli/tools — ``summary`` (per-run pass/fail and
over-average times, summary.go:40-77), ``dump-trace`` (pretty-print one
run's trace, dump_trace.go:60-135), ``visualize`` (unique-trace growth
curve with optional partial-order reduction, visualize.go:81-168).
"""

from __future__ import annotations

import json
import os
import shutil
import sys

from namazu_tpu.storage import load_storage
from namazu_tpu.utils.config import Config


def register(sub) -> None:
    p = sub.add_parser("tools", help="experiment analysis tools")
    tsub = p.add_subparsers(dest="tool", required=True)

    ps = tsub.add_parser("summary", help="per-run results summary")
    ps.add_argument("storage")
    ps.set_defaults(func=summary)

    pd = tsub.add_parser("dump-trace", help="pretty-print one run's trace")
    pd.add_argument("storage")
    pd.add_argument("run_index", type=int)
    pd.set_defaults(func=dump_trace)

    pv = tsub.add_parser("visualize", help="unique-trace growth curve")
    pv.add_argument("storage")
    pv.add_argument("--reduction", action="store_true",
                    help="apply partial-order reduction (compare per-entity "
                         "event subsequences instead of total orders)")
    pv.add_argument("--gnuplot", action="store_true",
                    help="emit gnuplot-ready two-column data only")
    pv.set_defaults(func=visualize)

    pa = tsub.add_parser(
        "analyze",
        help="rank coverage branches by success/failure divergence "
             "(fault localization)",
    )
    pa.add_argument("storage")
    pa.add_argument("--top", type=int, default=20)
    pa.set_defaults(func=analyze)

    pab = tsub.add_parser(
        "ab",
        help="A/B repro-rate measurement: N runs per policy on one "
             "example, searched policy trained on the baseline's "
             "recorded history (the BASELINE.md north-star loop)",
    )
    pab.add_argument("example", help="example dir with configs + materials")
    pab.add_argument("storage", help="storage dir to create (must not exist)")
    pab.add_argument("--runs", type=int, default=10,
                     help="runs per policy (default 10)")
    pab.add_argument("--baseline-config", default="config.toml",
                     help="config file (in EXAMPLE) for phase A")
    pab.add_argument("--search-config", default="config_tpu.toml",
                     help="config file (in EXAMPLE) swapped in for phase B")
    pab.add_argument("--json-out", default="",
                     help="also write the result JSON to this path")
    pab.add_argument("--prime-config", default="config.toml",
                     help="config used for priming runs (with "
                          "--prime-runs)")
    pab.add_argument("--prime-runs", type=int, default=0,
                     help="record N runs under PRIME-CONFIG first, then "
                          "run each phase on an independent CLONE of "
                          "that history (fair search-vs-search "
                          "comparisons: both train on the same recorded "
                          "failures, neither sees the other's runs); "
                          "0 = sequential single-storage A/B")
    pab.set_defaults(func=ab)

    pi = tsub.add_parser(
        "import-reference-trace",
        help="convert a reference-format experiment dir (per-action JSON "
             "pairs + gob results, e.g. the recorded ZOOKEEPER-2212 hunt "
             "shipped under example/zk-found-2212.ryu/example-result.*) "
             "into a native storage",
    )
    pi.add_argument("source", help="reference experiment dir with %%08x runs")
    pi.add_argument("storage", help="storage dir to create (must not exist)")
    pi.set_defaults(func=import_reference_trace)


def import_reference_trace(args) -> int:
    from namazu_tpu.storage.reference_import import import_experiment

    summary = import_experiment(args.source, args.storage)
    print(json.dumps(summary, sort_keys=True))
    return 0


def analyze(args) -> int:
    from namazu_tpu.analyzer import analyze_storage, print_report

    st = load_storage(args.storage)
    ranking = analyze_storage(st, top=args.top)
    if not ranking:
        print("no runs with coverage.json found")
        return 0
    print_report(ranking)
    return 0


def summary(args) -> int:
    st = load_storage(args.storage)
    n = st.nr_stored_histories()
    times, succ = [], 0
    rows = []
    for i in range(n):
        try:
            ok = st.is_successful(i)
            t = st.get_required_time(i)
        except Exception:
            continue
        rows.append((i, ok, t))
        succ += ok
        times.append(t)
    avg = sum(times) / len(times) if times else 0.0
    for i, ok, t in rows:
        flag = " (over average)" if t > avg else ""
        print(f"{i:08x}: {'SUCCESS' if ok else 'FAILURE'} {t:.2f}s{flag}")
    if rows:
        rate = 100.0 * (len(rows) - succ) / len(rows)
        print(f"total: {len(rows)} runs, {succ} successful, "
              f"{len(rows) - succ} failed (repro rate {rate:.1f}%), "
              f"avg {avg:.2f}s")
    else:
        print("no completed runs")
    return 0


def dump_trace(args) -> int:
    st = load_storage(args.storage)
    trace = st.get_stored_history(args.run_index)
    for i, action in enumerate(trace):
        d = action.to_jsonable()
        tt = action.triggered_time
        stamp = f"{tt:.6f}" if tt else "-"
        print(f"{i:6d} {stamp} {json.dumps(d, sort_keys=True)}")
    return 0


def _trace_key(trace, reduction: bool) -> str:
    if reduction:
        # partial-order reduction: two traces are equivalent if every
        # entity observed the same subsequence (parity visualize.go:81-133)
        per = trace.entity_order()
        return json.dumps({k: per[k] for k in sorted(per)})
    return json.dumps([(a.entity_id, a.event_class or a.class_name())
                       for a in trace])


def visualize(args) -> int:
    st = load_storage(args.storage)
    n = st.nr_stored_histories()
    seen = set()
    curve = []
    for i in range(n):
        try:
            trace = st.get_stored_history(i)
        except Exception:
            continue
        seen.add(_trace_key(trace, args.reduction))
        curve.append((i + 1, len(seen)))
    if args.gnuplot:
        for x, y in curve:
            print(f"{x} {y}")
    else:
        for x, y in curve:
            print(f"runs={x} unique_traces={y}")
        if curve:
            print(f"exploration saturation: {curve[-1][1]}/{curve[-1][0]} unique")
    return 0


def _phase_stats(storage, start: int, n: int, wall_s: float) -> dict:
    """Repro stats over runs [start, start+n) of a storage."""
    repros = sum(1 for i in range(start, start + n)
                 if not storage.is_successful(i))
    rate = repros / n if n else 0.0
    per_hour = repros / (wall_s / 3600.0) if wall_s > 0 else 0.0
    return {
        "runs": n,
        "repros": repros,
        "repro_rate": round(rate, 4),
        "wall_s": round(wall_s, 2),
        "repros_per_hour": round(per_hour, 1),
    }


def ab(args) -> int:
    """The north-star loop (BASELINE.md): phase A records N runs under the
    baseline config (the reference's ``for i in $(seq N); do nmz run``,
    SURVEY.md 3.1); phase B swaps in the search config — whose policy
    trains on phase A's recorded history — and runs N more. Reports
    repro-rate and repros/hour per policy and their ratio.

    With ``--prime-runs``, the recorded history is produced up front
    under ``--prime-config`` and each phase runs on its own CLONE of it:
    the right shape for search-vs-search comparisons (e.g. GA vs MCTS),
    where both sides must train on identical failures and neither may
    learn from the other's runs.
    """
    import time as _time

    from namazu_tpu.cli import cli_main

    base_cfg = os.path.join(args.example, args.baseline_config)
    search_cfg = os.path.join(args.example, args.search_config)
    materials = os.path.join(args.example, "materials")
    for path in (base_cfg, search_cfg, materials):
        if not os.path.exists(path):
            print(f"error: {path} not found", file=sys.stderr)
            return 1

    def phase(storage: str, n: int) -> float:
        t0 = _time.monotonic()
        for _ in range(n):
            if cli_main(["run", storage]) != 0:
                raise RuntimeError("run failed (infra error)")
        return _time.monotonic() - t0

    baseline_name = Config.from_file(base_cfg).get("explore_policy")
    search_name = Config.from_file(search_cfg).get("explore_policy")
    if search_name == baseline_name:  # self-vs-self A/B: keep keys distinct
        search_name += "_b"

    if args.prime_runs > 0:
        prime_cfg = os.path.join(args.example, args.prime_config)
        if not os.path.exists(prime_cfg):
            print(f"error: {prime_cfg} not found", file=sys.stderr)
            return 1
        if os.path.exists(args.storage):
            print(f"error: {args.storage} exists; remove it or pick "
                  "another storage dir", file=sys.stderr)
            return 1
        os.makedirs(args.storage)
        prime = os.path.join(args.storage, "prime")
        if cli_main(["init", prime_cfg, materials, prime]) != 0:
            return 1
        phase(prime, args.prime_runs)
        walls = {}
        for key, cfg in (("a", base_cfg), ("b", search_cfg)):
            clone = os.path.join(args.storage, key)
            shutil.copytree(prime, clone)
            shutil.copy(cfg, os.path.join(clone, "config.toml"))
            walls[key] = phase(clone, args.runs)
        res_a = _phase_stats(load_storage(os.path.join(args.storage, "a")),
                             args.prime_runs, args.runs, walls["a"])
        res_b = _phase_stats(load_storage(os.path.join(args.storage, "b")),
                             args.prime_runs, args.runs, walls["b"])
    else:
        if cli_main(["init", base_cfg, materials, args.storage]) != 0:
            return 1
        wall_a = phase(args.storage, args.runs)
        shutil.copy(search_cfg, os.path.join(args.storage, "config.toml"))
        wall_b = phase(args.storage, args.runs)
        st = load_storage(args.storage)
        res_a = _phase_stats(st, 0, args.runs, wall_a)
        res_b = _phase_stats(st, args.runs, args.runs, wall_b)

    ra, rb = res_a["repros_per_hour"], res_b["repros_per_hour"]
    result = {
        "example": os.path.basename(os.path.abspath(args.example)),
        "runs_per_policy": args.runs,
        baseline_name: res_a,
        search_name: res_b,
        # the BASELINE.md target is >= 10x baseline repros/hour
        "repros_per_hour_ratio": round(rb / ra, 2) if ra > 0 else None,
    }
    if args.prime_runs > 0:
        result["primed_runs"] = args.prime_runs
        result["prime_config"] = args.prime_config
    for name, res in ((baseline_name, res_a), (search_name, res_b)):
        print(f"{name:>12}: {res['repros']}/{res['runs']} repros "
              f"({100 * res['repro_rate']:.0f}%), {res['wall_s']}s, "
              f"{res['repros_per_hour']}/h")
    if result["repros_per_hour_ratio"] is not None:
        print(f"ratio: {result['repros_per_hour_ratio']}x repros/hour")
    line = json.dumps(result, sort_keys=True)
    print(line)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    return 0
