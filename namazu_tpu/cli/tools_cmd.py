"""``nmz-tpu tools summary|dump-trace|visualize|report|...`` — analysis.

Parity: /root/reference/nmz/cli/tools — ``summary`` (per-run pass/fail and
over-average times, summary.go:40-77), ``dump-trace`` (pretty-print one
run's trace, dump_trace.go:60-135), ``visualize`` (unique-trace growth
curve with optional partial-order reduction, visualize.go:81-168).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
from typing import Optional

from namazu_tpu.storage import load_storage
from namazu_tpu.utils.config import Config


def register(sub) -> None:
    p = sub.add_parser("tools", help="experiment analysis tools")
    tsub = p.add_subparsers(dest="tool", required=True)

    ps = tsub.add_parser("summary", help="per-run results summary")
    ps.add_argument("storage")
    ps.set_defaults(func=summary)

    pd = tsub.add_parser("dump-trace", help="pretty-print one run's trace")
    pd.add_argument("storage")
    pd.add_argument("run_index", type=int)
    pd.set_defaults(func=dump_trace)

    pv = tsub.add_parser("visualize", help="unique-trace growth curve")
    pv.add_argument("storage")
    pv.add_argument("--reduction", action="store_true",
                    help="apply partial-order reduction (compare per-entity "
                         "event subsequences instead of total orders)")
    pv.add_argument("--gnuplot", action="store_true",
                    help="emit gnuplot-ready two-column data only")
    pv.set_defaults(func=visualize)

    pa = tsub.add_parser(
        "analyze",
        help="rank coverage branches by success/failure divergence "
             "(fault localization)",
    )
    pa.add_argument("storage")
    pa.add_argument("--top", type=int, default=20)
    pa.set_defaults(func=analyze)

    pab = tsub.add_parser(
        "ab",
        help="A/B repro-rate measurement: N runs per policy on one "
             "example, searched policy trained on the baseline's "
             "recorded history (the BASELINE.md north-star loop)",
    )
    pab.add_argument("example", help="example dir with configs + materials")
    pab.add_argument("storage", help="storage dir to create (must not exist)")
    pab.add_argument("--runs", type=int, default=10,
                     help="runs per policy (default 10)")
    pab.add_argument("--baseline-config", default="config.toml",
                     help="config file (in EXAMPLE) for phase A")
    pab.add_argument("--search-config", default="config_tpu.toml",
                     help="config file (in EXAMPLE) swapped in for phase B")
    pab.add_argument("--json-out", default="",
                     help="also write the result JSON to this path")
    pab.add_argument("--prime-config", default="config.toml",
                     help="config used for priming runs (with "
                          "--prime-runs)")
    pab.add_argument("--prime-runs", type=int, default=0,
                     help="record N runs under PRIME-CONFIG first, then "
                          "run each phase on an independent CLONE of "
                          "that history (fair search-vs-search "
                          "comparisons: both train on the same recorded "
                          "failures, neither sees the other's runs); "
                          "0 = sequential single-storage A/B")
    for flag, phase_name in (("--a-param", "A"), ("--b-param", "B")):
        pab.add_argument(flag, action="append", default=[],
                         metavar="KEY=VALUE",
                         help=f"override an explore_policy_param for "
                              f"phase {phase_name}'s config (repeatable; "
                              "VALUE parsed as JSON, else string) — "
                              "ablations without a config file per knob")
    pab.add_argument("--failure-pool", default="",
                     help="shared failure-signature pool dir wired into "
                          "phase B's policy (cross-batch training; "
                          "models/failure_pool.py)")
    pab.set_defaults(func=ab)

    pc = tsub.add_parser(
        "calibrate",
        help="sweep an example's [calibration] timing knobs until the "
             "random-baseline repro rate lands in the target band "
             "(namazu_tpu/calibrate; writes calibration.json beside "
             "the config — `init` copies it, `run` exports the knobs "
             "as NMZ_CALIB_* environment)",
    )
    pc.add_argument("example", help="example dir with a [calibration] "
                                    "table in its config")
    pc.add_argument("--out", default="",
                    help="artifact path (default: "
                         "EXAMPLE/calibration.json)")
    pc.add_argument("--config", default="config.toml",
                    help="config file (in EXAMPLE) to calibrate "
                         "(default config.toml)")
    pc.add_argument("--band", default="",
                    help="target rate band LO,HI (overrides the "
                         "config's; default 0.02,0.10)")
    pc.add_argument("--max-runs", type=int, default=0,
                    help="per-probe run cap (overrides the config's; "
                         "0 = keep)")
    pc.add_argument("--seed", type=int, default=0,
                    help="campaign jitter seed (deterministic retries)")
    pc.add_argument("--workdir", default="",
                    help="where probe storages live (default: a temp "
                         "dir, removed per probe)")
    pc.add_argument("--run-wall-deadline", type=float, default=0.0,
                    help="per-run wall-clock deadline forwarded to the "
                         "probe campaigns (seconds; 0 = none)")
    pc.set_defaults(func=calibrate)

    pv2 = tsub.add_parser(
        "ab-variance",
        help="run the ab measurement N times (independent batches, "
             "optionally sharing a failure-signature pool) and "
             "aggregate the ratio distribution — the floor, not one "
             "lucky draw",
    )
    pv2.add_argument("example")
    pv2.add_argument("storage", help="root dir for per-batch storages "
                                     "(must not exist)")
    pv2.add_argument("--batches", type=int, default=6)
    pv2.add_argument("--runs", type=int, default=20)
    pv2.add_argument("--baseline-config", default="config.toml")
    pv2.add_argument("--search-config", default="config_tpu.toml")
    pv2.add_argument("--a-param", action="append", default=[],
                     metavar="KEY=VALUE")
    pv2.add_argument("--b-param", action="append", default=[],
                     metavar="KEY=VALUE")
    pv2.add_argument("--failure-pool", default="",
                     help="'auto' = STORAGE/pool shared across batches; "
                          "'' = off; else an explicit dir")
    pv2.add_argument("--json-out", default="")
    pv2.set_defaults(func=ab_variance)

    pm = tsub.add_parser(
        "metrics",
        help="dump an observability metrics registry as JSON "
             "(doc/observability.md); a live orchestrator's metrics "
             "need --url — without it the dump is THIS process's own "
             "registry (embedded orchestrators, tests)",
    )
    pm.add_argument("--url", default="",
                    help="scrape a running orchestrator "
                         "(http://127.0.0.1:10080, or uds:///path for "
                         "a same-host fleet without a TCP port); omit "
                         "to dump this process's in-memory registry, "
                         "which for a plain CLI invocation is empty")
    pm.set_defaults(func=metrics_dump)

    ptp = tsub.add_parser(
        "top",
        help="fleet status snapshot (doc/observability.md \"Fleet "
             "telemetry\"): one row per producer process that pushed "
             "telemetry — events/s, queue dwell p99, table-version "
             "skew, backhaul lag, last-seen age — plus the SLO burn "
             "table; --watch refreshes in place",
    )
    ptp.add_argument("--url", default="http://127.0.0.1:10080",
                     help="a fleet aggregator's surface: an "
                          "orchestrator's REST endpoint "
                          "(http://127.0.0.1:10080) or a framed "
                          "collector (uds:///path — a campaign "
                          "supervisor's --telemetry-collector, or an "
                          "orchestrator's uds_path)")
    ptp.add_argument("--pool", action="store_true",
                     help="--url is a fleet placement service "
                          "(nmz-tpu fleet serve) — render the pool "
                          "document (hosts, placements, migration "
                          "counters) instead of /fleet telemetry")
    ptp.add_argument("--watch", action="store_true",
                     help="refresh every INTERVAL seconds until ^C")
    ptp.add_argument("--interval", type=float, default=2.0,
                     help="refresh period with --watch (default 2s)")
    ptp.add_argument("--json", action="store_true",
                     help="print the raw /fleet JSON payload instead "
                          "of the table")
    ptp.set_defaults(func=top)

    ppd = tsub.add_parser(
        "profdiff",
        help="differential profiling (doc/observability.md "
             "\"Profiling\"): align two sampling profiles — files "
             "(nmz-profile-v1 JSON, speedscope JSON, or collapsed "
             "folded text) or live obs endpoints (http:// / uds:// / "
             "tcp://) — and rank frames by self-time share delta; "
             "the #1 entry names what got slower between A and B",
    )
    ppd.add_argument("profile_a",
                     help="baseline profile: a file or a live obs url")
    ppd.add_argument("profile_b",
                     help="candidate profile: a file or a live obs url")
    ppd.add_argument("--format", choices=("text", "md", "json"),
                     default="text", help="output rendering")
    ppd.add_argument("--limit", type=int, default=15,
                     help="frames shown (text/md; default 15)")
    ppd.add_argument("--out", default="",
                     help="write to this file instead of stdout")
    ppd.set_defaults(func=profdiff_cmd)

    pt = tsub.add_parser(
        "trace",
        help="flight-recorder traces (doc/observability.md): list "
             "recorded runs, dump one as NDJSON, export Chrome-trace "
             "JSON for chrome://tracing / ui.perfetto.dev, or diff two "
             "runs' realized dispatch orders",
    )
    ttsub = pt.add_subparsers(dest="trace_tool", required=True)

    def _url_arg(sp):
        sp.add_argument("--url", default="",
                        help="a running orchestrator's REST endpoint "
                             "(e.g. http://127.0.0.1:10080); omit to "
                             "read this process's in-memory recorder "
                             "(embedded orchestrators, tests)")

    ptl = ttsub.add_parser("list", help="recorded-run summaries")
    _url_arg(ptl)
    ptl.set_defaults(func=trace_list)

    ptd = ttsub.add_parser(
        "dump", help="one run's records as NDJSON (diffable: one JSON "
                     "line per event, run-relative timestamps)")
    ptd.add_argument("run_id", nargs="?", default="latest",
                     help="run id (default: latest)")
    _url_arg(ptd)
    ptd.set_defaults(func=trace_dump)

    pte = ttsub.add_parser(
        "export", help="one run as Chrome-trace/Perfetto JSON")
    pte.add_argument("run_id", nargs="?", default="latest",
                     help="run id (default: latest)")
    pte.add_argument("--out", default="",
                     help="write to this file instead of stdout")
    _url_arg(pte)
    pte.set_defaults(func=trace_export)

    ptf = ttsub.add_parser(
        "diff", help="unified diff of two runs' realized dispatch "
                     "orders (empty output = same interleaving)")
    ptf.add_argument("run_a")
    ptf.add_argument("run_b")
    _url_arg(ptf)
    ptf.set_defaults(func=trace_diff)

    pw = tsub.add_parser(
        "why",
        help="causality divergence explanation (doc/observability.md "
             "\"Causality\"): the minimal set of ordering-relation "
             "flips between two recorded runs' dispatch orders, ranked "
             "by fault-localization suspicion, plus each run's "
             "happens-before summary and critical-path attribution — "
             "the answer to \"why does run A reproduce and run B "
             "doesn't\"",
    )
    pw.add_argument("run_a",
                    help="first run: a recorded run id, or a path to "
                         "an NDJSON trace dump (tools trace dump / "
                         "GET /traces/<id>?format=ndjson)")
    pw.add_argument("run_b", help="second run: run id or NDJSON path")
    pw.add_argument("--url", default="",
                    help="a running orchestrator's REST endpoint: ask "
                         "its /causality/<a>/<b> route instead of this "
                         "process's recorder (ignored for file inputs)")
    pw.add_argument("--format", choices=("md", "json"), default="md")
    pw.add_argument("--top", type=int, default=20,
                    help="flips kept in the report (default 20)")
    pw.add_argument("--out", default="",
                    help="write to this file instead of stdout")
    pw.set_defaults(func=why)

    pz = tsub.add_parser(
        "minimize",
        help="auto-minimize a failing run to a reproducer dossier "
             "(triage plane, doc/observability.md \"Triage\"): "
             "delta-debug the run's installed delay table over the "
             "causality plane's ordering flips — candidate subsets are "
             "scored by FREE simulation through the guidance plane, "
             "only the best survivors replay for real — and emit a "
             "self-contained dossier (minimal table + flips + probe "
             "journal + why explanation + DAG slice), keyed by failure "
             "signature",
    )
    pz.add_argument("storage", nargs="?", default="",
                    help="storage dir holding the failing run; with "
                         "--url this is instead a failure SIGNATURE to "
                         "fetch (omit to list the orchestrator's "
                         "dossiers)")
    pz.add_argument("run_index", nargs="?", type=int, default=None,
                    help="failing run index (default: the most recent "
                         "non-quarantined failure)")
    pz.add_argument("--baseline", type=int, default=None,
                    help="passing run index to diff against (default: "
                         "the most recent success, else a synthetic "
                         "zero-delay baseline)")
    pz.add_argument("--url", default="",
                    help="a running orchestrator's REST endpoint: read "
                         "its GET /triage[/<signature>] surface instead "
                         "of minimizing locally")
    pz.add_argument("--knowledge", default="",
                    help="knowledge-service address host:port "
                         "(doc/knowledge.md): pull an existing dossier "
                         "for this failure signature first; push the "
                         "freshly minimized one back for other tenants")
    pz.add_argument("--top", type=int, default=12,
                    help="candidate flips taken from the causality "
                         "diff (default 12)")
    pz.add_argument("--max-probes", type=int, default=4096,
                    help="simulated-probe budget (default 4096)")
    pz.add_argument("--max-replays", type=int, default=4,
                    help="real-replay budget (default 4)")
    pz.add_argument("--replay-deadline", type=float, default=120.0,
                    help="seconds per validation replay (default 120)")
    pz.add_argument("--no-replay", action="store_true",
                    help="skip real-execution validation entirely "
                         "(dossier says validated: false)")
    pz.add_argument("--format", choices=("md", "json"), default="md")
    pz.add_argument("--out", default="",
                    help="write to this file instead of stdout")
    pz.set_defaults(func=minimize)

    pr = tsub.add_parser(
        "report",
        help="experiment analytics report (doc/observability.md): "
             "cross-run exploration coverage, reproduction-rate stats "
             "with a Wilson interval, search-plane convergence + stall "
             "detection, and the analyzer's suspicious-branch ranking — "
             "as Markdown, JSON, or NDJSON",
    )
    pr.add_argument("storage", nargs="?", default="",
                    help="storage dir to analyze (omit with --url)")
    pr.add_argument("--url", default="",
                    help="a running orchestrator's REST endpoint (e.g. "
                         "http://127.0.0.1:10080): fetch its live "
                         "/analytics payload instead of reading a "
                         "storage dir")
    pr.add_argument("--format", choices=("md", "json", "ndjson"),
                    default="md")
    pr.add_argument("--top", type=int, default=20,
                    help="suspicious-branch rows kept (default 20)")
    pr.add_argument("--window", type=int, default=8,
                    help="runs per novelty window (default 8)")
    pr.add_argument("--out", default="",
                    help="write to this file instead of stdout")
    pr.set_defaults(func=report)

    pc = tsub.add_parser(
        "coverage",
        help="relation-coverage dump (guidance plane, doc/search.md): "
             "bitmap occupancy, the coverage growth curve, and the top "
             "uncovered (one-sided) ordering relations ranked by "
             "predicted flip score — the frontier a guided search "
             "mutates toward",
    )
    pc.add_argument("storage", nargs="?", default="",
                    help="storage dir to analyze (omit with --url)")
    pc.add_argument("--url", default="",
                    help="a running orchestrator's REST endpoint: read "
                         "the relation-coverage section of its live "
                         "/analytics payload instead of a storage dir")
    pc.add_argument("--top", type=int, default=12,
                    help="one-sided relations listed (default 12)")
    pc.add_argument("--format", choices=("md", "json"), default="md")
    pc.add_argument("--out", default="",
                    help="write to this file instead of stdout")
    pc.set_defaults(func=coverage)

    pg = tsub.add_parser(
        "ab-guided",
        help="guided-vs-blind A/B acceptance (guidance plane, "
             "doc/search.md): two seeded campaigns of equal run budget "
             "over one deterministic relation-bug workload — guided "
             "must reach >= --min-ratio the blind arm's relation "
             "coverage, dominate its curve, and not regress "
             "time-to-first-failure; exit 1 on any violated criterion",
    )
    pg.add_argument("example", nargs="?", default="",
                    help="example dir (e.g. examples/flaky-init): seed "
                         "the workload's identity space from its "
                         "config; omit for the synthetic default")
    pg.add_argument("--seed", type=int, default=11)
    pg.add_argument("--runs", type=int, default=72,
                    help="runs per arm (default 72)")
    pg.add_argument("--min-ratio", type=float, default=1.25,
                    help="required guided/blind relation-coverage "
                         "ratio (default 1.25)")
    pg.add_argument("--workdir", default="",
                    help="where the two arms' storages land (default: "
                         "a temp dir)")
    pg.add_argument("--out", default="",
                    help="also write the report JSON to this path")
    pg.set_defaults(func=ab_guided)

    pk = tsub.add_parser(
        "knowledge",
        help="global failure-knowledge service stats (doc/knowledge.md): "
             "pool occupancy, tenants, scenario tables, shared-surrogate "
             "training rounds",
    )
    pk.add_argument("addr", help="service address host:port (a sidecar "
                                 "started with --pool-dir)")
    pk.set_defaults(func=knowledge_stats)

    pf = tsub.add_parser(
        "fsck",
        help="storage integrity check (doc/robustness.md): list "
             "quarantined (INCOMPLETE) runs, crash-incomplete runs not "
             "yet marked, and orphan atomic-write temp files; --repair "
             "quarantines the incomplete runs and sweeps the temps. "
             "Pointed at a shared failure-pool dir (doc/knowledge.md) "
             "it checks pool entries instead: stray temps and torn "
             "(unreadable) .npz entries. Pointed at a placement "
             "service's state dir (fleet.json manifest, doc/tenancy.md "
             "\"Fleet of fleets\") it sweeps stale pool-lease records "
             "and orphaned namespace journals, reconciling against the "
             "live service's view when one is reachable",
    )
    pf.add_argument("storage")
    pf.add_argument("--repair", action="store_true",
                    help="quarantine unmarked incomplete runs and remove "
                         "orphan *.tmp files (run only on a quiescent "
                         "storage — an in-flight run looks incomplete)")
    pf.add_argument("--service-url", default="",
                    help="fleet-state fsck only: reconcile lease records "
                         "against this live placement service instead "
                         "of the manifest's recorded serve url")
    pf.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    pf.set_defaults(func=fsck)

    pi = tsub.add_parser(
        "import-reference-trace",
        help="convert a reference-format experiment dir (per-action JSON "
             "pairs + gob results, e.g. the recorded ZOOKEEPER-2212 hunt "
             "shipped under example/zk-found-2212.ryu/example-result.*) "
             "into a native storage",
    )
    pi.add_argument("source", help="reference experiment dir with %%08x runs")
    pi.add_argument("storage", help="storage dir to create (must not exist)")
    pi.set_defaults(func=import_reference_trace)


def metrics_dump(args) -> int:
    """One JSON document: the process-local registry, or a live
    orchestrator's via its REST ``/metrics.json`` route / the framed
    ``metrics`` op on a ``uds://`` surface."""
    if args.url:
        from namazu_tpu.obs import federation

        doc = federation.fetch(args.url, "metrics")
        print(json.dumps(doc, sort_keys=True))
        return 0
    from namazu_tpu import obs

    print(json.dumps(obs.registry_jsonable(), sort_keys=True))
    return 0


def _fmt_cell(value, unit: str = "") -> str:
    if value is None:
        return "-"
    if isinstance(value, bool):
        return "yes" if value else "no"
    if isinstance(value, float):
        text = f"{value:.3f}".rstrip("0").rstrip(".")
        return (text or "0") + unit
    return f"{value}{unit}"


def _fmt_codec(by_codec: dict) -> Optional[str]:
    """The dominant wire codec of one instance (most payload bytes
    moved), from the federated ``nmz_wire_bytes_total{codec}`` ledger;
    a ``+`` suffix marks mixed-codec traffic."""
    if not isinstance(by_codec, dict) or not by_codec:
        return None
    top = max(by_codec, key=by_codec.get)
    return f"{top}+" if len(by_codec) > 1 else top


def _fmt_prof(frame, share) -> Optional[str]:
    """The dominant self-time frame of one instance's sampling profile
    (obs/profiling.py via the federated profile delta), rendered
    ``file.py:func(NN%)`` — the basename keeps the column narrow."""
    if not frame:
        return None
    short = str(frame).rsplit("/", 1)[-1]
    try:
        pct = f"({float(share) * 100:.0f}%)" if share is not None else ""
    except (TypeError, ValueError):
        pct = ""
    return f"{short}{pct}"


def _fmt_hot_stage(stage_p99: dict) -> Optional[str]:
    """The dominant lifecycle segment of one instance — the stage with
    the largest federated p99 from ``nmz_event_stage_seconds``
    (obs/causality.py), rendered ``stage:p99``."""
    if not isinstance(stage_p99, dict) or not stage_p99:
        return None
    stage, p99 = max(stage_p99.items(), key=lambda kv: kv[1])
    return f"{stage}:{_fmt_cell(float(p99), 's')}"


def render_top(payload: dict) -> str:
    """The ``tools top`` table for one /fleet payload."""
    cols = (
        ("job", "JOB", ""), ("instance", "INSTANCE", ""),
        ("events_per_sec", "EV/S", ""), ("events_total", "EVENTS", ""),
        ("queue_dwell_p99_s", "DWELL99", "s"),
        ("dispatch_p99_s", "E2E99", "s"),
        ("hot_stage", "HOTSTAGE", ""),
        ("codec", "CODEC", ""),
        ("backhaul_lag_p99_s", "BACKHL99", "s"),
        ("table_version", "TBLV", ""), ("table_skew", "SKEW", ""),
        # SKEW (a version count) upgraded with its time-domain twin:
        # the measured publish->edge-install propagation p99
        # (nmz_table_propagation_seconds, obs/spans.py)
        ("table_propagation_p99_s", "PROP99", "s"),
        ("edge_parked", "PARKED", ""),
        # distinct failure signatures carrying a triage dossier on this
        # instance (nmz_triage_signatures; doc/observability.md
        # "Triage")
        ("triage_signatures", "SIGS", ""),
        # campaign progress (nmz_campaign_*; doc/observability.md
        # "Calibration & progress"): measured repro rate and the
        # next-repro ETA forecast
        ("repro_rate", "RATE", ""),
        ("eta_next_repro_s", "ETA", "s"),
        # virtual-clock plane (doc/performance.md "Virtual clock"):
        # pace over VIRTUAL elapsed, beside — never instead of — the
        # wall-denominated RATE/ETA the SPRT budgets read
        ("repros_per_hour_virtual", "VRP/H", ""),
        ("vclock_speedup", "VCLK", "x"),
        # dominant self-time frame from the instance's continuous
        # sampling profile (obs/profiling.py; doc/observability.md
        # "Profiling")
        ("prof", "PROF", ""),
        ("last_seen_age_s", "AGE", "s"), ("stale", "STALE", ""),
    )
    rows = [[header for _, header, _ in cols]]
    for inst in payload.get("instances", []):
        inst = dict(inst,
                    hot_stage=_fmt_hot_stage(inst.get("stage_p99_s")),
                    codec=_fmt_codec(inst.get("wire_bytes_by_codec")),
                    prof=_fmt_prof(inst.get("prof_top_frame"),
                                   inst.get("prof_top_share")))
        rows.append([_fmt_cell(inst.get(key), unit)
                     for key, _, unit in cols])
    widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
    lines = ["  ".join(cell.ljust(w) for cell, w in zip(row, widths))
             .rstrip() for row in rows]
    lines.append("")
    lines.append(
        f"{payload.get('instance_count', 0)} instance(s), "
        f"{payload.get('stale_instances', 0)} stale; fleet table "
        f"version {_fmt_cell(payload.get('fleet_table_version'))}")
    # tenancy plane (doc/tenancy.md): one row per (instance, run
    # namespace) — how one orchestrator hosting 8 campaigns reads per
    # tenant. Absent entirely on pre-tenancy fleets.
    run_rows = [(inst.get("instance", ""), run, doc)
                for inst in payload.get("instances", [])
                for run, doc in sorted((inst.get("runs") or {}).items())]
    if run_rows:
        lines.append("")
        rtab = [["RUN", "INSTANCE", "EV/S", "EVENTS", "PARKED"]]
        for instance, run, doc in run_rows:
            rtab.append([run, instance,
                         _fmt_cell(doc.get("events_per_sec")),
                         _fmt_cell(doc.get("events_total")),
                         _fmt_cell(doc.get("parked"))])
        rwidths = [max(len(r[i]) for r in rtab) for i in range(5)]
        lines.extend("  ".join(cell.ljust(w) for cell, w
                               in zip(row, rwidths)).rstrip()
                     for row in rtab)
    objectives = (payload.get("slo") or {}).get("objectives") or []
    if objectives:
        lines.append("")
        lines.append("SLO" + " " * 17 + "BURN    BREACHED  BREACHES")
        for row in objectives:
            lines.append(f"{str(row.get('name', '')):<20}"
                         f"{_fmt_cell(row.get('burn')):<8}"
                         f"{_fmt_cell(row.get('breached', False)):<10}"
                         f"{_fmt_cell(row.get('breaches', 0))}")
    return "\n".join(lines) + "\n"


def profdiff_cmd(args) -> int:
    """Differential profiling (obs/profdiff.py): load two profiles
    from files or live obs endpoints and rank frames by self-time
    share delta."""
    from namazu_tpu.obs import profdiff

    try:
        a = profdiff.load_profile(args.profile_a)
        b = profdiff.load_profile(args.profile_b)
    except (OSError, ValueError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    d = profdiff.diff(a, b)
    if args.format == "json":
        text = json.dumps(d, sort_keys=True) + "\n"
    elif args.format == "md":
        text = profdiff.render_md(d, limit=args.limit)
    else:
        text = profdiff.render_text(d, limit=args.limit)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def top(args) -> int:
    """Fleet snapshot table over a live aggregator's /fleet payload
    (REST or uds, obs/federation.py); --watch redraws until ^C.
    With --pool the url is a placement service (fleet/service.py) and
    the table is the pool document instead."""
    import time as _time

    from namazu_tpu.obs import federation

    # programmatic callers (tests, scripts) build bare Namespaces that
    # predate the flag
    pool = getattr(args, "pool", False)

    def _fetch_pool():
        from namazu_tpu.fleet import FleetClient
        from namazu_tpu.tenancy.client import TenancyWireError

        client = FleetClient(args.url)
        try:
            return client.pool_status()
        except TenancyWireError as e:
            # fold into the watch loop's retryable class
            raise RuntimeError(str(e)) from e
        finally:
            client.close()

    while True:
        try:
            try:
                if pool:
                    payload = _fetch_pool()
                else:
                    payload = federation.fetch(args.url, "fleet")
            except (OSError, RuntimeError, ValueError):
                if not args.watch:
                    raise
                # a watch session must survive transient unreachability
                # (a run child cycling, the collector restarting):
                # show the gap, keep polling
                sys.stdout.write(
                    f"\x1b[2J\x1b[H{args.url}: fleet unreachable, "
                    "retrying...\n")
                sys.stdout.flush()
                _time.sleep(max(0.2, args.interval))
                continue
            if args.json:
                text = json.dumps(payload, sort_keys=True) + "\n"
            elif pool:
                from namazu_tpu.cli.fleet_cmd import render_pool

                text = render_pool(payload) + "\n"
            else:
                text = render_top(payload)
            if not args.watch:
                sys.stdout.write(text)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + text)
            sys.stdout.flush()
            _time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            # ^C mid-fetch (slow collector) must exit as cleanly as
            # ^C mid-sleep
            if args.watch:
                return 0
            raise


def _http_get(url: str, timeout: float = 10.0) -> bytes:
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read()
    except urllib.error.HTTPError as e:
        # surface the server's error body (e.g. "no recorded run X")
        # instead of a raw traceback — parity with the local path's
        # friendly _local_run_or_die message
        body = e.read().decode(errors="replace")
        try:
            msg = json.loads(body).get("error", body)
        except ValueError:
            msg = body
        raise SystemExit(f"error: {url}: HTTP {e.code}: {msg}") from None


def _local_run_or_die(run_id: str):
    from namazu_tpu import obs

    run = obs.trace_run(run_id)
    if run is None:
        known = [s["run_id"] for s in obs.trace_summaries()]
        raise SystemExit(
            f"no recorded run {run_id!r} in this process's recorder "
            f"(known: {known}); a live orchestrator's traces need --url")
    return run


def trace_list(args) -> int:
    if args.url:
        doc = json.loads(_http_get(args.url.rstrip("/") + "/traces"))
    else:
        from namazu_tpu import obs

        doc = {"runs": obs.trace_summaries()}
    print(json.dumps(doc, sort_keys=True))
    return 0


def trace_dump(args) -> int:
    if args.url:
        text = _http_get(
            args.url.rstrip("/")
            + f"/traces/{args.run_id}?format=ndjson").decode()
    else:
        from namazu_tpu.obs import export

        text = export.to_ndjson(_local_run_or_die(args.run_id))
    sys.stdout.write(text)
    return 0


def trace_export(args) -> int:
    if args.url:
        text = _http_get(
            args.url.rstrip("/") + f"/traces/{args.run_id}").decode()
    else:
        from namazu_tpu.obs import export

        text = json.dumps(
            export.chrome_trace(_local_run_or_die(args.run_id)),
            sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out} (load it in chrome://tracing or "
              "https://ui.perfetto.dev)")
    else:
        print(text)
    return 0


def trace_diff(args) -> int:
    from namazu_tpu.obs import export

    if args.url:
        base = args.url.rstrip("/")
        orders = [
            export.order_lines_from_docs([
                json.loads(line) for line in _http_get(
                    f"{base}/traces/{rid}?format=ndjson"
                ).decode().splitlines() if line.strip()])
            for rid in (args.run_a, args.run_b)
        ]
        diff = export.diff_order(orders[0], orders[1],
                                 args.run_a, args.run_b)
    else:
        diff = export.diff_runs(_local_run_or_die(args.run_a),
                                _local_run_or_die(args.run_b))
    if diff:
        print(diff)
        return 1  # like diff(1): nonzero when the orders differ
    print("runs executed the same dispatch order")
    return 0


def _why_docs(spec: str, url: str):
    """Resolve one ``tools why`` input to ``(record_docs, label)``:
    an NDJSON dump file on disk, a run id on a live orchestrator
    (--url), or a run id in this process's recorder."""
    from namazu_tpu.obs import causality

    if os.path.exists(spec):
        with open(spec) as f:
            records, _, run_id = causality.split_ndjson(f.read())
        return records, run_id or os.path.basename(spec)
    if url:
        text = _http_get(
            url.rstrip("/") + f"/traces/{spec}?format=ndjson").decode()
        records, _, run_id = causality.split_ndjson(text)
        return records, run_id or spec
    records, _, run_id = causality.docs_of_run(_local_run_or_die(spec))
    return records, run_id


def why(args) -> int:
    """Causality divergence explanation between two runs
    (obs/causality.py): ordering-relation flips + per-run
    happens-before and critical-path summaries."""
    from namazu_tpu.obs import causality

    both_ids = not (os.path.exists(args.run_a)
                    or os.path.exists(args.run_b))
    if args.url and both_ids:
        # the server computes (and folds in its registered storage's
        # fault-localization ranking, which this process can't see)
        payload = json.loads(_http_get(
            args.url.rstrip("/")
            + f"/causality/{args.run_a}/{args.run_b}?top={args.top}"))
    else:
        docs_a, label_a = _why_docs(args.run_a, args.url)
        docs_b, label_b = _why_docs(args.run_b, args.url)
        payload = causality.why_payload(docs_a, docs_b,
                                        label_a, label_b,
                                        top=args.top)
    if args.format == "json":
        text = json.dumps(payload, sort_keys=True) + "\n"
    else:
        # the closing Perfetto pointer names `tools trace export
        # <run_id>`, which only works when THIS process's recorder
        # holds the runs — not for --url-fetched payloads or file
        # dumps, where the pointer would dangle
        from namazu_tpu import obs

        local_dump = both_ids and not args.url \
            and obs.trace_run(args.run_a) is not None \
            and obs.trace_run(args.run_b) is not None
        text = causality.render_why_md(payload, perfetto=local_dump)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


def _emit(text: str, out: str) -> None:
    if out:
        with open(out, "w") as f:
            f.write(text)
        print(f"wrote {out}")
    else:
        sys.stdout.write(text)


def minimize(args) -> int:
    """Auto-minimized reproducer for a failing run (triage plane,
    namazu_tpu/triage): knowledge-first when a signature is already
    dossier'd, locally delta-debugged otherwise."""
    from namazu_tpu import triage

    if args.url:
        base = args.url.rstrip("/")
        if not args.storage:
            doc = json.loads(_http_get(f"{base}/triage"))
            print(json.dumps(doc, sort_keys=True))
            return 0
        doc = json.loads(_http_get(f"{base}/triage/{args.storage}"))
        dossier = doc.get("dossier") or doc
        text = (json.dumps(dossier, sort_keys=True) + "\n"
                if args.format == "json"
                else triage.render_dossier_md(dossier))
        _emit(text, args.out)
        return 0
    if not args.storage:
        raise SystemExit("error: minimize needs a storage dir "
                         "(or --url)")

    client = None
    if args.knowledge:
        from namazu_tpu.knowledge import shared_client

        client = shared_client(args.knowledge, tenant="tools-minimize")
        # knowledge-first: a sibling campaign may already have paid the
        # replays for this exact failure signature
        try:
            sig = triage.failure_signature(args.storage, args.run_index)
        except triage.MinimizeError as e:
            raise SystemExit(f"error: {e}") from None
        pulled = client.triage_pull(sig)
        if pulled is not None:
            print(f"# dossier for {sig} served from the knowledge "
                  "pool (no local minimization)", file=sys.stderr)
            text = (json.dumps(pulled, sort_keys=True) + "\n"
                    if args.format == "json"
                    else triage.render_dossier_md(pulled))
            _emit(text, args.out)
            return 0

    budget = triage.MinimizeBudget(
        max_probes=args.max_probes,
        max_replays=0 if args.no_replay else args.max_replays,
        replay_deadline_s=args.replay_deadline)
    try:
        dossier = triage.minimize_run(
            args.storage, run_index=args.run_index,
            baseline_index=args.baseline, top=args.top, budget=budget)
    except triage.MinimizeError as e:
        raise SystemExit(f"error: {e}") from None
    if client is not None:
        # best-effort like every knowledge op: an outage warns once
        # inside the client and the dossier still prints
        client.triage_push(dossier)
    text = (json.dumps(dossier, sort_keys=True) + "\n"
            if args.format == "json"
            else triage.render_dossier_md(dossier))
    _emit(text, args.out)
    return 0 if dossier.get("validated") or args.no_replay else 2


def report(args) -> int:
    """Experiment analytics report — local storage or a live
    orchestrator's /analytics (same payload either way; the local path
    additionally folds in THIS process's flight-recorder runs, which for
    a plain CLI invocation are none)."""
    from namazu_tpu.obs import analytics, recorder
    from namazu_tpu.obs import report as report_mod

    if args.url:
        payload = json.loads(_http_get(
            args.url.rstrip("/")
            + f"/analytics?top={args.top}&window={args.window}"))
    elif args.storage:
        st = load_storage(args.storage)
        try:
            payload = analytics.compute_payload(
                storage=st, recorder_runs=recorder.recorder().runs(),
                top=args.top, window=args.window)
        finally:
            st.close()
    else:
        raise SystemExit("error: give a storage dir or --url")
    if args.format == "json":
        text = json.dumps(payload, sort_keys=True) + "\n"
    elif args.format == "ndjson":
        text = report_mod.render_ndjson(payload)
    else:
        text = report_mod.render_markdown(payload)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


def calibrate(args) -> int:
    """Calibration sweep over one example (namazu_tpu/calibrate): land
    the random-baseline repro rate in the target band by bisecting the
    declared knob axis, each probe a short SPRT-early-stopped campaign.
    Exit 0 only when an in-band point landed; the artifact (with the
    full probe journal either way) is written beside the config."""
    from namazu_tpu.calibrate.harness import (
        CalibrationError,
        calibrate_example,
    )

    band = None
    if args.band:
        try:
            lo, hi = (float(x) for x in args.band.split(","))
            band = (lo, hi)
        except ValueError:
            print(f"error: bad --band {args.band!r} (want LO,HI)",
                  file=sys.stderr)
            return 2
    try:
        doc = calibrate_example(
            args.example,
            out_path=args.out,
            config_name=args.config,
            workdir=args.workdir or None,
            seed=args.seed,
            band=band,
            max_runs=args.max_runs or None,
            run_wall_deadline_s=args.run_wall_deadline)
    except CalibrationError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    out = args.out or os.path.join(args.example, "calibration.json")
    print(json.dumps({k: doc[k] for k in (
        "status", "knobs", "rate", "rate_ci95", "runs_spent",
        "fixed_n_equivalent", "runs_saved_pct")}, sort_keys=True))
    print(f"wrote {out}")
    if doc["status"] != "calibrated":
        print("error: no in-band knob point found (see the probe "
              "journal in the artifact)", file=sys.stderr)
        return 1
    return 0


def _looks_like_pool_dir(path: str) -> bool:
    """A shared failure-pool dir is flat ``<digest>.npz`` files with no
    storage skeleton — no ``config.json``/``storage.json`` (every
    initialized storage has those). A FRESH pool counts too: empty, or
    holding only the knowledge service's ``_state`` subdir — fsck on a
    just-started service must report 0 entries, not crash on
    load_storage."""
    if not os.path.isdir(path) \
            or os.path.exists(os.path.join(path, "config.json")) \
            or os.path.exists(os.path.join(path, "storage.json")):
        return False
    names = os.listdir(path)
    if any(n.endswith((".npz", ".tmp")) for n in names):
        return True
    return not names or names == ["_state"]


def _fsck_pool(args) -> int:
    from namazu_tpu.models.failure_pool import pool_fsck

    report = pool_fsck(args.storage, repair=args.repair)
    findings = (len(report["tmp_artifacts"])
                + len(report["unreadable_entries"]))
    if args.json:
        print(json.dumps(report, sort_keys=True))
        return 1 if findings else 0
    print(f"{report['pool_dir']}: {report['entries']} pool entr(ies) "
          "readable")
    for name in report["tmp_artifacts"]:
        print(f"  stray temp: {name}")
    for name in report["unreadable_entries"]:
        print(f"  unreadable entry: {name}")
    if args.repair and report["repaired"]:
        print(f"repaired: {len(report['repaired'])} item(s) swept/"
              "quarantined")
    elif findings:
        print("rerun with --repair to sweep stray temps and quarantine "
              "torn entries")
    return 1 if findings else 0


def _fsck_fleet(args) -> int:
    from namazu_tpu.fleet.fsck import fsck_pool_state

    report = fsck_pool_state(args.storage, repair=args.repair,
                             service_url=getattr(args, "service_url", ""))
    findings = (len(report["stale_leases"])
                + len(report["orphan_journals"])
                + len(report["unreadable_records"]))
    if args.json:
        print(json.dumps(report, sort_keys=True))
        return 1 if findings else 0
    print(f"{report['state_dir']}: {report['lease_records']} lease "
          f"record(s), {len(report['live_leases'])} live")
    if not report["manifest_ok"]:
        print("  manifest unreadable (fleet.json)")
    for rec in report["stale_leases"]:
        print(f"  stale lease: {rec['lease_id']} run={rec['run']} "
              f"expired {rec['expired_ago_s']}s ago")
    for name in report["unreadable_records"]:
        print(f"  unreadable record: {name}")
    for name in report["orphan_journals"]:
        print(f"  orphan journal (empty): {name}")
    for rec in report["recoverable_journals"]:
        print(f"  recoverable journal: {rec['journal']} holds "
              f"{rec['unreleased']} unreleased event(s) — kept; "
              "re-lease the run over it to recover")
    if args.repair and report["repaired"]:
        print(f"repaired: {len(report['repaired'])} item(s) swept")
    elif findings:
        print("rerun with --repair to sweep stale records and orphan "
              "journals")
    return 1 if findings else 0


def fsck(args) -> int:
    """Integrity report over a storage's run dirs. Exit 1 only for
    UNHANDLED states — unmarked incomplete dirs, missing dirs, stray
    atomic-write temps (found-and-repaired still exits 1 so scripts
    notice the storage needed repair). Already-quarantined runs are
    reported but are a handled state (a supervised abort marks its own
    dir; doc/robustness.md), so they alone exit 0.

    A shared failure-pool dir (no storage skeleton) gets the pool
    checks instead — the knowledge plane's pool is part of the same
    durable state a campaign depends on (doc/knowledge.md). A fleet
    placement service's state dir (fleet.json manifest) gets the pool-
    lease/journal sweep (fleet/fsck.py)."""
    from namazu_tpu.fleet.fsck import looks_like_fleet_dir

    if looks_like_fleet_dir(args.storage):
        return _fsck_fleet(args)
    if _looks_like_pool_dir(args.storage):
        return _fsck_pool(args)
    st = load_storage(args.storage)
    try:
        if not hasattr(st, "fsck"):
            print(f"error: storage backend {type(st).__name__} has no "
                  "fsck support", file=sys.stderr)
            return 2
        report = st.fsck(repair=args.repair)
    finally:
        st.close()
    findings = (len(report["incomplete_unmarked"])
                + len(report.get("repaired_runs", []))
                + len(report["missing_dirs"])
                + len(report["tmp_artifacts"]))
    if args.json:
        print(json.dumps(report, sort_keys=True))
        return 1 if findings else 0
    print(f"{report['dir']}: {report['next_run']} run dir(s) allocated, "
          f"{report['complete']} complete, "
          f"{len(report['quarantined'])} quarantined")
    for i in report["quarantined"]:
        print(f"  quarantined: {i:08x} (INCOMPLETE marker)")
    for i in report["incomplete_unmarked"]:
        print(f"  incomplete (unmarked): {i:08x} — no result recorded")
    for i in report["missing_dirs"]:
        print(f"  missing dir: {i:08x}")
    for path in report["tmp_artifacts"]:
        print(f"  stray temp: {path}")
    if args.repair:
        print("repaired: incomplete runs quarantined, stray temps removed")
    elif findings:
        print("rerun with --repair to quarantine incomplete runs and "
              "sweep stray temps")
    return 1 if findings else 0


def _coverage_md(doc: dict) -> str:
    """Markdown face of a coverage dump."""
    from namazu_tpu.obs.report import sparkline

    stats = doc.get("stats") or {}
    lines = [
        "# Relation coverage",
        "",
        f"- source: `{doc.get('source', '')}`",
        f"- covered: {stats.get('covered_bits', 0)} / "
        f"{stats.get('width', 0)} bits "
        f"(occupancy {stats.get('occupancy', 0)}) over "
        f"{stats.get('runs_observed', 0)} run(s)",
        f"- growth: `{sparkline(stats.get('curve', []))}` "
        f"{stats.get('curve', [])}",
        f"- directed pairs tracked: "
        f"{_fmt_cell(stats.get('directed_pairs'))} "
        f"(overflow {_fmt_cell(stats.get('pair_overflow'))})",
    ]
    if "relation_saturated" in doc:
        # the aggregate verdicts the --url mode exists to surface
        lines.append(
            f"- relation saturated: "
            f"{_fmt_cell(doc.get('relation_saturated'))} "
            f"(open frontier: "
            f"{_fmt_cell(doc.get('relation_frontier_bits'))} "
            "one-sided relation bits)")
    lines.append("")
    rows = doc.get("one_sided_top") or []
    if rows:
        lines += ["## Top uncovered relations (by predicted flip "
                  "score)", "",
                  "| first | then (flip uncovered) | seen | min gap "
                  "| flip score |",
                  "|---|---|---:|---:|---:|"]
        for r in rows:
            lines.append(f"| `{r['first']}` | `{r['then']}` "
                         f"| {r['count']} | {r['min_gap']} "
                         f"| {r['flip_score']} |")
    elif "one_sided_top" in doc:
        lines.append("- no one-sided relations (every observed "
                     "ordering has had its flip exercised)")
    else:
        lines.append("- one-sided relation identities are not "
                     "available over --url (the /analytics payload "
                     "carries curve aggregates only); point this tool "
                     "at the storage dir for the full frontier")
    lines.append("")
    return "\n".join(lines)


def coverage(args) -> int:
    """Relation-coverage dump (guidance plane): the campaign's covered
    bitmap, growth curve, and one-sided frontier — from a storage dir
    (full detail) or a live orchestrator's /analytics (aggregates)."""
    from namazu_tpu.obs import analytics as an

    if args.url:
        payload = json.loads(_http_get(
            args.url.rstrip("/") + "/analytics"))
        cov = payload.get("coverage") or {}
        doc = {
            "schema": "nmz-coverage-v1",
            "source": args.url,
            "stats": {
                "covered_bits": cov.get("relation_bits", 0),
                "width": cov.get("relation_width", 0),
                "occupancy": cov.get("relation_coverage", 0.0),
                "runs_observed": cov.get("runs", 0),
                "curve": cov.get("relation_curve", []),
                "directed_pairs": None,
                "pair_overflow": None,
            },
            "relation_saturated": cov.get("relation_saturated"),
            "relation_frontier_bits": cov.get("relation_frontier_bits"),
        }
    elif args.storage:
        from namazu_tpu.guidance import (
            CoverageMap,
            bucket_sequence_from_trace,
        )

        st = load_storage(args.storage)
        try:
            cmap = CoverageMap(H=an.RELATION_H, width=an.RELATION_WIDTH,
                               window=an.RELATION_WINDOW)
            is_quarantined = getattr(st, "is_quarantined", None)
            for i in range(st.nr_stored_histories()):
                if is_quarantined is not None and is_quarantined(i):
                    continue
                try:
                    trace = st.get_stored_history(i)
                except Exception:
                    continue
                cmap.observe(
                    bucket_sequence_from_trace(trace, an.RELATION_H))
        finally:
            st.close()
        doc = {
            "schema": "nmz-coverage-v1",
            "source": os.path.abspath(args.storage),
            "stats": cmap.stats(),
            "one_sided_top": cmap.one_sided(args.top),
        }
    else:
        raise SystemExit("error: give a storage dir or --url")
    if args.format == "json":
        text = json.dumps(doc, sort_keys=True) + "\n"
    else:
        text = _coverage_md(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


def ab_guided(args) -> int:
    """The guidance plane's A/B acceptance gate (guidance/ab.py):
    prints the per-arm summary + report JSON; exit 1 when any
    acceptance criterion fails — CI-gateable."""
    import tempfile

    from namazu_tpu.guidance.ab import run_ab

    workdir = args.workdir or tempfile.mkdtemp(prefix="nmz-ab-guided-")
    try:
        rep = run_ab(workdir, seed=args.seed, runs=args.runs,
                     min_ratio=args.min_ratio, example=args.example)
    except ValueError as e:  # e.g. a typo'd example path — loud, not
        print(f"error: {e}", file=sys.stderr)  # a silent synthetic run
        return 2
    for name in ("blind", "guided"):
        arm = rep["arms"][name]
        ttff = arm["time_to_first_failure_run"]
        print(f"{name:>7}: {arm['relation_bits']} relation bits, "
              f"{arm['unique_digests']} digests, "
              f"{arm['repros']} repro(s), "
              f"ttff {'-' if ttff is None else f'run {ttff}'}")
    print(f"coverage ratio {rep['coverage_ratio']}x "
          f"(need >= {rep['min_ratio']}): "
          f"{'OK' if rep['coverage_ratio_ok'] else 'FAIL'}; "
          f"curve dominance {rep['curve_dominance']}: "
          f"{'OK' if rep['curve_dominance_ok'] else 'FAIL'}; "
          f"ttff: {'OK' if rep['ttff_ok'] else 'FAIL'}")
    line = json.dumps(rep, sort_keys=True)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if rep["ok"] else 1


def knowledge_stats(args) -> int:
    """One ``stats`` round trip against a knowledge-hosting sidecar;
    prints the JSON payload (the same document obs/analytics.py folds
    into its payload when a knowledge address is registered)."""
    from namazu_tpu.knowledge import KnowledgeClient

    client = KnowledgeClient(args.addr, tenant="tools")
    try:
        stats = client.stats()
    finally:
        client.close()
    if stats is None:
        print(f"error: knowledge service {args.addr} unreachable or "
              "not configured (start a sidecar with --pool-dir)",
              file=sys.stderr)
        return 1
    print(json.dumps(stats, sort_keys=True, indent=2))
    return 0


def import_reference_trace(args) -> int:
    from namazu_tpu.storage.reference_import import import_experiment

    summary = import_experiment(args.source, args.storage)
    print(json.dumps(summary, sort_keys=True))
    return 0


def analyze(args) -> int:
    from namazu_tpu.analyzer import analyze_storage, print_report

    st = load_storage(args.storage)
    ranking = analyze_storage(st, top=args.top)
    if not ranking:
        print("no runs with coverage.json found")
        return 0
    print_report(ranking)
    return 0


def summary(args) -> int:
    st = load_storage(args.storage)
    n = st.nr_stored_histories()
    times, succ = [], 0
    rows = []
    for i in range(n):
        try:
            ok = st.is_successful(i)
            t = st.get_required_time(i)
        except Exception:
            continue
        rows.append((i, ok, t))
        succ += ok
        times.append(t)
    avg = sum(times) / len(times) if times else 0.0
    for i, ok, t in rows:
        flag = " (over average)" if t > avg else ""
        print(f"{i:08x}: {'SUCCESS' if ok else 'FAILURE'} {t:.2f}s{flag}")
    if rows:
        rate = 100.0 * (len(rows) - succ) / len(rows)
        print(f"total: {len(rows)} runs, {succ} successful, "
              f"{len(rows) - succ} failed (repro rate {rate:.1f}%), "
              f"avg {avg:.2f}s")
    else:
        print("no completed runs")
    return 0


def dump_trace(args) -> int:
    st = load_storage(args.storage)
    trace = st.get_stored_history(args.run_index)
    for i, action in enumerate(trace):
        d = action.to_jsonable()
        tt = action.triggered_time
        stamp = f"{tt:.6f}" if tt else "-"
        print(f"{i:6d} {stamp} {json.dumps(d, sort_keys=True)}")
    return 0


def _trace_key(trace, reduction: bool) -> str:
    if reduction:
        # partial-order reduction: two traces are equivalent if every
        # entity observed the same subsequence (parity visualize.go:81-133)
        per = trace.entity_order()
        return json.dumps({k: per[k] for k in sorted(per)})
    return json.dumps([(a.entity_id, a.event_class or a.class_name())
                       for a in trace])


def visualize(args) -> int:
    st = load_storage(args.storage)
    n = st.nr_stored_histories()
    seen = set()
    curve = []
    for i in range(n):
        try:
            trace = st.get_stored_history(i)
        except Exception:
            continue
        seen.add(_trace_key(trace, args.reduction))
        curve.append((i + 1, len(seen)))
    if args.gnuplot:
        for x, y in curve:
            print(f"{x} {y}")
    else:
        for x, y in curve:
            print(f"runs={x} unique_traces={y}")
        if curve:
            print(f"exploration saturation: {curve[-1][1]}/{curve[-1][0]} unique")
    return 0


def _parse_params(pairs) -> list:
    """["k=v", ...] -> [(key, value)] with JSON-typed values."""
    out = []
    for item in pairs:
        key, sep, raw = item.partition("=")
        if not sep or not key:
            raise SystemExit(f"bad --*-param {item!r} (want KEY=VALUE)")
        try:
            val = json.loads(raw)
        except ValueError:
            val = raw
        out.append((key, val))
    return out


def _install_phase_config(cfg_file: str, storage: str, params) -> None:
    """Make ``cfg_file`` the storage's active config, applying
    explore_policy_param overrides.

    Without overrides this is the documented copy-as-config.toml flow.
    With overrides the merged config is written to the storage's
    config.json (and any config.toml removed) — ``run`` prefers
    config.toml but falls back to config.json (cli/run_cmd.py:38), and
    JSON is the one format the stdlib can *write*."""
    dst_toml = os.path.join(storage, "config.toml")
    if not params:
        shutil.copy(cfg_file, dst_toml)
        return
    cfg = Config.from_file(cfg_file)
    for key, val in params:
        cfg.set(f"explore_policy_param.{key}", val)
    if os.path.exists(dst_toml):
        os.remove(dst_toml)
    cfg.dump_json(os.path.join(storage, "config.json"))


def _phase_stats(storage, start: int, n: int, wall_s: float) -> dict:
    """Repro stats over runs [start, start+n) of a storage."""
    repros = sum(1 for i in range(start, start + n)
                 if not storage.is_successful(i))
    rate = repros / n if n else 0.0
    per_hour = repros / (wall_s / 3600.0) if wall_s > 0 else 0.0
    return {
        "runs": n,
        "repros": repros,
        "repro_rate": round(rate, 4),
        "wall_s": round(wall_s, 2),
        "repros_per_hour": round(per_hour, 1),
    }


def ab(args) -> int:
    """The north-star loop (BASELINE.md): phase A records N runs under the
    baseline config (the reference's ``for i in $(seq N); do nmz run``,
    SURVEY.md 3.1); phase B swaps in the search config — whose policy
    trains on phase A's recorded history — and runs N more. Reports
    repro-rate and repros/hour per policy and their ratio.

    With ``--prime-runs``, the recorded history is produced up front
    under ``--prime-config`` and each phase runs on its own CLONE of it:
    the right shape for search-vs-search comparisons (e.g. GA vs MCTS),
    where both sides must train on identical failures and neither may
    learn from the other's runs.
    """
    import time as _time

    from namazu_tpu.cli import cli_main

    base_cfg = os.path.join(args.example, args.baseline_config)
    search_cfg = os.path.join(args.example, args.search_config)
    materials = os.path.join(args.example, "materials")
    for path in (base_cfg, search_cfg, materials):
        if not os.path.exists(path):
            print(f"error: {path} not found", file=sys.stderr)
            return 1

    def phase(storage: str, n: int) -> float:
        t0 = _time.monotonic()
        for _ in range(n):
            if cli_main(["run", storage]) != 0:
                raise RuntimeError("run failed (infra error)")
        return _time.monotonic() - t0

    baseline_name = Config.from_file(base_cfg).get("explore_policy")
    search_name = Config.from_file(search_cfg).get("explore_policy")
    if search_name == baseline_name:  # self-vs-self A/B: keep keys distinct
        search_name += "_b"

    a_params = _parse_params(getattr(args, "a_param", []))
    b_params = _parse_params(getattr(args, "b_param", []))
    if getattr(args, "failure_pool", ""):
        b_params.append(("failure_pool",
                         os.path.abspath(args.failure_pool)))

    if args.prime_runs > 0:
        prime_cfg = os.path.join(args.example, args.prime_config)
        if not os.path.exists(prime_cfg):
            print(f"error: {prime_cfg} not found", file=sys.stderr)
            return 1
        if os.path.exists(args.storage):
            print(f"error: {args.storage} exists; remove it or pick "
                  "another storage dir", file=sys.stderr)
            return 1
        os.makedirs(args.storage)
        prime = os.path.join(args.storage, "prime")
        if cli_main(["init", prime_cfg, materials, prime]) != 0:
            return 1
        phase(prime, args.prime_runs)
        walls = {}
        for key, cfg, params in (("a", base_cfg, a_params),
                                 ("b", search_cfg, b_params)):
            clone = os.path.join(args.storage, key)
            shutil.copytree(prime, clone)
            _install_phase_config(cfg, clone, params)
            walls[key] = phase(clone, args.runs)
        res_a = _phase_stats(load_storage(os.path.join(args.storage, "a")),
                             args.prime_runs, args.runs, walls["a"])
        res_b = _phase_stats(load_storage(os.path.join(args.storage, "b")),
                             args.prime_runs, args.runs, walls["b"])
    else:
        if cli_main(["init", base_cfg, materials, args.storage]) != 0:
            return 1
        if a_params:
            _install_phase_config(base_cfg, args.storage, a_params)
        wall_a = phase(args.storage, args.runs)
        _install_phase_config(search_cfg, args.storage, b_params)
        wall_b = phase(args.storage, args.runs)
        st = load_storage(args.storage)
        res_a = _phase_stats(st, 0, args.runs, wall_a)
        res_b = _phase_stats(st, args.runs, args.runs, wall_b)

    ra, rb = res_a["repros_per_hour"], res_b["repros_per_hour"]
    result = {
        "example": os.path.basename(os.path.abspath(args.example)),
        "runs_per_policy": args.runs,
        baseline_name: res_a,
        search_name: res_b,
        # the BASELINE.md target is >= 10x baseline repros/hour
        "repros_per_hour_ratio": round(rb / ra, 2) if ra > 0 else None,
    }
    if args.prime_runs > 0:
        result["primed_runs"] = args.prime_runs
        result["prime_config"] = args.prime_config
    if a_params:
        result["a_params"] = dict(a_params)
    if b_params:
        result["b_params"] = dict(b_params)
    for name, res in ((baseline_name, res_a), (search_name, res_b)):
        print(f"{name:>12}: {res['repros']}/{res['runs']} repros "
              f"({100 * res['repro_rate']:.0f}%), {res['wall_s']}s, "
              f"{res['repros_per_hour']}/h")
    if result["repros_per_hour_ratio"] is not None:
        print(f"ratio: {result['repros_per_hour_ratio']}x repros/hour")
    line = json.dumps(result, sort_keys=True)
    print(line)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    return 0


def ab_variance(args) -> int:
    """N independent ab batches; report the ratio DISTRIBUTION (min =
    the floor the round is judged on, VERDICT r4 weak #2), optionally
    with a shared failure-signature pool so later batches train on every
    earlier batch's failures, not just their own phase A's."""
    import argparse

    if os.path.exists(args.storage):
        print(f"error: {args.storage} exists; remove it or pick another "
              "root", file=sys.stderr)
        return 1
    os.makedirs(args.storage)
    pool = args.failure_pool
    if pool == "auto":
        pool = os.path.join(args.storage, "pool")
    batches = []
    for i in range(args.batches):
        bdir = os.path.join(args.storage, f"batch{i:02d}")
        out = os.path.join(args.storage, f"batch{i:02d}.json")
        ns = argparse.Namespace(
            example=args.example, storage=bdir, runs=args.runs,
            baseline_config=args.baseline_config,
            search_config=args.search_config,
            prime_config=args.baseline_config, prime_runs=0,
            a_param=list(args.a_param), b_param=list(args.b_param),
            failure_pool=pool, json_out=out,
        )
        print(f"== batch {i + 1}/{args.batches} ==")
        rc = ab(ns)
        if rc != 0:
            return rc
        with open(out) as f:
            batches.append(json.load(f))
    import statistics

    ratios = [b["repros_per_hour_ratio"] for b in batches]
    finite = sorted(r for r in ratios if r is not None)
    med = statistics.median(finite) if finite else None
    result = {
        "example": os.path.basename(os.path.abspath(args.example)),
        "batches": args.batches,
        "runs_per_policy": args.runs,
        "failure_pool": bool(pool),
        "ratios": ratios,
        # None ratio = phase A recorded zero repros (denominator 0):
        # the searched side found bugs random never did — a floor of
        # +inf, reported separately rather than folded into min
        "ratio_min": finite[0] if finite else None,
        "ratio_median": med,
        "ratio_max": finite[-1] if finite else None,
        "baseline_zero_repro_batches": sum(1 for r in ratios
                                           if r is None),
        "per_batch": batches,
    }
    line = json.dumps(result, sort_keys=True)
    print(line)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    return 0
