"""``nmz-tpu tools summary|dump-trace|visualize`` — experiment analysis.

Parity: /root/reference/nmz/cli/tools — ``summary`` (per-run pass/fail and
over-average times, summary.go:40-77), ``dump-trace`` (pretty-print one
run's trace, dump_trace.go:60-135), ``visualize`` (unique-trace growth
curve with optional partial-order reduction, visualize.go:81-168).
"""

from __future__ import annotations

import json

from namazu_tpu.storage import load_storage


def register(sub) -> None:
    p = sub.add_parser("tools", help="experiment analysis tools")
    tsub = p.add_subparsers(dest="tool", required=True)

    ps = tsub.add_parser("summary", help="per-run results summary")
    ps.add_argument("storage")
    ps.set_defaults(func=summary)

    pd = tsub.add_parser("dump-trace", help="pretty-print one run's trace")
    pd.add_argument("storage")
    pd.add_argument("run_index", type=int)
    pd.set_defaults(func=dump_trace)

    pv = tsub.add_parser("visualize", help="unique-trace growth curve")
    pv.add_argument("storage")
    pv.add_argument("--reduction", action="store_true",
                    help="apply partial-order reduction (compare per-entity "
                         "event subsequences instead of total orders)")
    pv.add_argument("--gnuplot", action="store_true",
                    help="emit gnuplot-ready two-column data only")
    pv.set_defaults(func=visualize)

    pa = tsub.add_parser(
        "analyze",
        help="rank coverage branches by success/failure divergence "
             "(fault localization)",
    )
    pa.add_argument("storage")
    pa.add_argument("--top", type=int, default=20)
    pa.set_defaults(func=analyze)


def analyze(args) -> int:
    from namazu_tpu.analyzer import analyze_storage, print_report

    st = load_storage(args.storage)
    ranking = analyze_storage(st, top=args.top)
    if not ranking:
        print("no runs with coverage.json found")
        return 0
    print_report(ranking)
    return 0


def summary(args) -> int:
    st = load_storage(args.storage)
    n = st.nr_stored_histories()
    times, succ = [], 0
    rows = []
    for i in range(n):
        try:
            ok = st.is_successful(i)
            t = st.get_required_time(i)
        except Exception:
            continue
        rows.append((i, ok, t))
        succ += ok
        times.append(t)
    avg = sum(times) / len(times) if times else 0.0
    for i, ok, t in rows:
        flag = " (over average)" if t > avg else ""
        print(f"{i:08x}: {'SUCCESS' if ok else 'FAILURE'} {t:.2f}s{flag}")
    if rows:
        rate = 100.0 * (len(rows) - succ) / len(rows)
        print(f"total: {len(rows)} runs, {succ} successful, "
              f"{len(rows) - succ} failed (repro rate {rate:.1f}%), "
              f"avg {avg:.2f}s")
    else:
        print("no completed runs")
    return 0


def dump_trace(args) -> int:
    st = load_storage(args.storage)
    trace = st.get_stored_history(args.run_index)
    for i, action in enumerate(trace):
        d = action.to_jsonable()
        tt = action.triggered_time
        stamp = f"{tt:.6f}" if tt else "-"
        print(f"{i:6d} {stamp} {json.dumps(d, sort_keys=True)}")
    return 0


def _trace_key(trace, reduction: bool) -> str:
    if reduction:
        # partial-order reduction: two traces are equivalent if every
        # entity observed the same subsequence (parity visualize.go:81-133)
        per = trace.entity_order()
        return json.dumps({k: per[k] for k in sorted(per)})
    return json.dumps([(a.entity_id, a.event_class or a.class_name())
                       for a in trace])


def visualize(args) -> int:
    st = load_storage(args.storage)
    n = st.nr_stored_histories()
    seen = set()
    curve = []
    for i in range(n):
        try:
            trace = st.get_stored_history(i)
        except Exception:
            continue
        seen.add(_trace_key(trace, args.reduction))
        curve.append((i + 1, len(seen)))
    if args.gnuplot:
        for x, y in curve:
            print(f"{x} {y}")
    else:
        for x, y in curve:
            print(f"runs={x} unique_traces={y}")
        if curve:
            print(f"exploration saturation: {curve[-1][1]}/{curve[-1][0]} unique")
    return 0
