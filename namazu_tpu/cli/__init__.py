"""Command-line interface and experiment driver.

Parity: /root/reference/nmz/cli (main.go:35-52) — subcommands ``init``,
``run``, ``orchestrator``, ``inspectors``, ``tools``. Invoke as
``python -m namazu_tpu.cli <subcommand> ...`` (or the ``nmz-tpu`` console
script when installed).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    from namazu_tpu.cli import (
        campaign_cmd,
        chaos_cmd,
        container_cmd,
        fleet_cmd,
        init_cmd,
        inspectors_cmd,
        orchestrator_cmd,
        run_cmd,
        sidecar_cmd,
        tools_cmd,
    )

    parser = argparse.ArgumentParser(
        prog="nmz-tpu",
        description="TPU-native programmable fuzzy scheduler for distributed systems",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    init_cmd.register(sub)
    run_cmd.register(sub)
    campaign_cmd.register(sub)
    orchestrator_cmd.register(sub)
    inspectors_cmd.register(sub)
    tools_cmd.register(sub)
    container_cmd.register(sub)
    sidecar_cmd.register(sub)
    chaos_cmd.register(sub)
    fleet_cmd.register(sub)
    return parser


def cli_main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    return args.func(args) or 0


def main() -> None:  # console-script entry point
    sys.exit(cli_main())
