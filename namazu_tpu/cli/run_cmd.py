"""``nmz-tpu run <storage_dir>`` — run one experiment.

Parity: /root/reference/nmz/cli/run.go:171-248 (call stack SURVEY.md 3.1):
allocate a run dir, start the orchestrator, run the experiment's ``run``
script (which boots the testee + inspectors), shut down, judge with the
``validate`` script (exit status = oracle), record trace + result, clean.

Driven N times by the user (``for i in $(seq 1 100); do nmz-tpu run d; done``)
— this loop is the repro-rate metric loop of BASELINE.md.
"""

from __future__ import annotations

import os
import sys
import time

from namazu_tpu.orchestrator import Orchestrator
from namazu_tpu.policy import create_policy
from namazu_tpu.storage import load_storage
from namazu_tpu.utils.cmd import CmdFactory
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import init_log


def register(sub) -> None:
    p = sub.add_parser("run", help="run one experiment from a storage dir")
    p.add_argument("storage", help="storage directory created by init")
    p.set_defaults(func=run)


def run(args) -> int:
    storage_dir = args.storage
    # a user-editable config.toml wins over the init-time config.json
    # snapshot, so swapping the policy between runs of one storage works
    # (reference parity: run.go:55 reads storageDir/config.toml directly —
    # e.g. record history under `random`, then re-run under `tpu_search`)
    cfg_path = os.path.join(storage_dir, "config.toml")
    if not os.path.exists(cfg_path):
        cfg_path = os.path.join(storage_dir, "config.json")
    # config.json is only ever written by init, so its absence (even with
    # a config.toml present, e.g. `run` pointed at an example source dir)
    # means this is not an initialized storage
    if not os.path.exists(os.path.join(storage_dir, "config.json")):
        print(f"error: {storage_dir} is not initialized (no config.json; "
              "run `init` first — an edited config.toml wins over it "
              "afterwards)", file=sys.stderr)
        return 1
    cfg = Config.from_file(cfg_path)

    storage = load_storage(storage_dir)
    working_dir = storage.create_new_working_dir()
    materials_dir = os.path.join(storage_dir, "materials")
    # correlate this run's log lines, metrics, and flight-recorder trace
    # (GET /traces/<run_id>) with the on-disk run dir via one key
    if not cfg.is_set("run_id"):
        cfg.set("run_id", os.path.basename(os.path.normpath(working_dir)))
    init_log(os.path.join(working_dir, "nmz.log"))
    factory = CmdFactory(working_dir=working_dir, materials_dir=materials_dir)

    from namazu_tpu.policy.plugins import load_policy_plugins

    load_policy_plugins(cfg, materials_dir)
    policy = create_policy(cfg.get("explore_policy"))
    policy.load_config(cfg)
    policy.set_history_storage(storage)

    # the live GET /analytics route aggregates over this storage (the
    # same dir `tools report` reads offline — one payload, two surfaces)
    from namazu_tpu import obs

    obs.set_analytics_storage(os.path.abspath(storage_dir))

    orchestrator = Orchestrator(cfg, policy, collect_trace=True)
    orchestrator.start()

    successful = False
    start = time.monotonic()
    try:
        run_script = cfg.get("run")
        if not run_script:
            print("error: config has no 'run' script", file=sys.stderr)
            return 1
        res = factory.run(run_script)
        if res.returncode != 0:
            # infra failure, not an experiment outcome: abort without
            # recording so it cannot pollute repro-rate stats or the
            # search plane's failure archive (parity: cli/run.go aborts
            # when the run command errors)
            print(f"error: run script exited {res.returncode}; "
                  "not recording this run", file=sys.stderr)
            return 1
    finally:
        trace = orchestrator.shutdown()

    validate_script = cfg.get("validate")
    if validate_script:
        successful = factory.run(validate_script).returncode == 0
    required_time = time.monotonic() - start

    from namazu_tpu.signal.base import HINT_SPACE

    storage.record_new_trace(trace)
    # stamp the replay-hint format version: a future format bump must be
    # able to tell (and skip) histories whose recorded event_hint strings
    # hash into a different bucket space (policy/tpu.py _ingest_history)
    storage.record_result(successful, required_time,
                          metadata={"hint_space": HINT_SPACE})
    storage.close()

    clean_script = cfg.get("clean")
    if clean_script:
        factory.run(clean_script)

    print(f"run finished: successful={successful} "
          f"time={required_time:.2f}s trace={len(trace)} actions "
          f"workdir={working_dir}")
    return 0
