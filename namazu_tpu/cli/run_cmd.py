"""``nmz-tpu run <storage_dir>`` — run one experiment.

Parity: /root/reference/nmz/cli/run.go:171-248 (call stack SURVEY.md 3.1):
allocate a run dir, start the orchestrator, run the experiment's ``run``
script (which boots the testee + inspectors), shut down, judge with the
``validate`` script (exit status = oracle), record trace + result, clean.

Driven N times by the user (``for i in $(seq 1 100); do nmz-tpu run d; done``)
— this loop is the repro-rate metric loop of BASELINE.md.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional

from namazu_tpu.orchestrator import Orchestrator
from namazu_tpu.policy import create_policy
from namazu_tpu.storage import load_storage
from namazu_tpu.utils.cmd import CmdFactory
from namazu_tpu.utils.config import Config
from namazu_tpu.utils.log import init_log

#: exit statuses the campaign supervisor classifies on (doc/robustness.md)
EXIT_OK = 0
EXIT_INFRA = 1
EXIT_TIMEOUT = 124  # a phase deadline expired (same convention as timeout(1))


def register(sub) -> None:
    p = sub.add_parser("run", help="run one experiment from a storage dir")
    p.add_argument("storage", help="storage directory created by init")
    for phase in ("run", "validate", "clean"):
        p.add_argument(
            f"--{phase}-deadline", type=float, default=None, metavar="S",
            help=f"deadline for the {phase} script (seconds; its whole "
                 f"process group is killed on expiry); default: the "
                 f"config's {phase}_deadline_s, 0 = none")
    p.add_argument(
        "--journal", action="store_true",
        help="write the crash-recovery event journal into the run's "
             "working dir (doc/robustness.md): a killed orchestrator's "
             "parked events survive and a restart over the same dir "
             "resumes them. Also enabled by event_journal = true in "
             "the config")
    p.add_argument(
        "--knowledge", default="", metavar="HOST:PORT",
        help="global failure-knowledge service address (a sidecar "
             "started with --pool-dir, doc/knowledge.md): cold runs "
             "warm-start from the fleet's pooled failures, failures "
             "stream back; an outage degrades to local-only search. "
             "Overrides the config's explore_policy_param.knowledge")
    p.add_argument(
        "--virtual-clock", action="store_true",
        help="run under a discrete-event virtual clock "
             "(doc/performance.md \"Virtual clock\"): scheduled delays "
             "fast-forward instead of real-sleeping whenever every "
             "waiter is parked, and experiment children get the "
             "LD_PRELOAD clock interposer so their sleeps/poll "
             "timeouts park too. Repro results are unchanged at "
             "delay-scale 1; wall time shrinks by the scenario's idle "
             "fraction. Also enabled by virtual_clock = true in the "
             "config")
    p.add_argument(
        "--telemetry-url", default="", metavar="URL",
        help="push this process's metrics to a fleet aggregator "
             "(doc/observability.md \"Fleet telemetry\"): an "
             "orchestrator's REST endpoint (http://...) or a campaign "
             "supervisor's collector (uds:///path). Defaults to "
             "$NMZ_TELEMETRY_URL (a campaign supervisor exports it to "
             "its run children); overrides the config's telemetry_url")
    p.set_defaults(func=run)


def _deadline(cli_value: Optional[float], cfg: Config, key: str
              ) -> Optional[float]:
    v = cli_value if cli_value is not None else float(cfg.get(key, 0) or 0)
    return v if v and v > 0 else None


def run(args) -> int:
    storage_dir = args.storage
    # a user-editable config.toml wins over the init-time config.json
    # snapshot, so swapping the policy between runs of one storage works
    # (reference parity: run.go:55 reads storageDir/config.toml directly —
    # e.g. record history under `random`, then re-run under `tpu_search`)
    cfg_path = os.path.join(storage_dir, "config.toml")
    if not os.path.exists(cfg_path):
        cfg_path = os.path.join(storage_dir, "config.json")
    # config.json is only ever written by init, so its absence (even with
    # a config.toml present, e.g. `run` pointed at an example source dir)
    # means this is not an initialized storage
    if not os.path.exists(os.path.join(storage_dir, "config.json")):
        print(f"error: {storage_dir} is not initialized (no config.json; "
              "run `init` first — an edited config.toml wins over it "
              "afterwards)", file=sys.stderr)
        return 1
    cfg = Config.from_file(cfg_path)
    # chaos plane (doc/robustness.md): fault plans reach child `run`
    # processes (campaign slots, kill-tests) via NMZ_CHAOS; no-op unless
    # set, and an explicitly installed plan wins
    from namazu_tpu import chaos

    chaos.install_from_env()
    if args.knowledge:
        # CLI wins over the config snapshot (same precedence as the
        # deadline flags): `campaign --knowledge` forwards this to every
        # child without editing the storage's config
        cfg.set("explore_policy_param.knowledge", args.knowledge)

    storage = load_storage(storage_dir)
    working_dir = storage.create_new_working_dir()
    materials_dir = os.path.join(storage_dir, "materials")
    # correlate this run's log lines, metrics, and flight-recorder trace
    # (GET /traces/<run_id>) with the on-disk run dir via one key
    if not cfg.is_set("run_id"):
        cfg.set("run_id", os.path.basename(os.path.normpath(working_dir)))
    init_log(os.path.join(working_dir, "nmz.log"))
    if args.journal or bool(cfg.get("event_journal")):
        # the journal lives in the run's own dir: recovery is per-run,
        # and fsck/quarantine semantics over the storage stay untouched
        cfg.set("event_journal_dir", working_dir)
    factory = CmdFactory(working_dir=working_dir, materials_dir=materials_dir)
    # calibration plane (namazu_tpu/calibrate): a committed
    # calibration.json in the storage (copied by init from the example
    # dir) exports its knob values as NMZ_CALIB_<NAME> to every
    # experiment script — calibrated timing is provenance the scripts
    # read from the environment, never an edited source constant.
    # Explicit environment (a calibration probe's candidate values,
    # exported by the campaign supervisor) wins over the artifact.
    from namazu_tpu.calibrate import artifact as _calib_artifact

    calib = _calib_artifact.load_calibration(storage_dir)
    if calib is not None:
        env_knobs = _calib_artifact.knob_env(calib)
        factory.extra_env.update(
            {k: v for k, v in env_knobs.items() if k not in os.environ})
    # record the run script's process group while a phase is in flight:
    # if THIS process is SIGKILLed mid-run (the orchestrator crash the
    # chaos plane injects), the campaign supervisor sweeps the group so
    # testee processes cannot orphan into the next slot
    factory.pgid_file = os.path.join(working_dir, "phase.pgid")

    # virtual clock (doc/performance.md "Virtual clock"): installed
    # BEFORE the policy/orchestrator exist so every ScheduledQueue,
    # liveness stamp, and lease TTL constructed below reads the virtual
    # source; children inherit the epoch page + interposer via the env
    vclock_handle = None
    vclock_summary = None
    if getattr(args, "virtual_clock", False) or bool(
            cfg.get("virtual_clock")):
        from namazu_tpu import vclock

        vclock_handle = vclock.activate(working_dir, cfg)
        factory.extra_env.update(vclock_handle.child_env())

    from namazu_tpu.policy.plugins import load_policy_plugins

    load_policy_plugins(cfg, materials_dir)
    policy = create_policy(cfg.get("explore_policy"))
    policy.load_config(cfg)
    policy.set_history_storage(storage)

    # the live GET /analytics route aggregates over this storage (the
    # same dir `tools report` reads offline — one payload, two surfaces)
    from namazu_tpu import obs

    obs.set_analytics_storage(os.path.abspath(storage_dir))
    if args.knowledge:
        # fold the fleet's pool/tenant stats into GET /analytics
        obs.set_knowledge_address(args.knowledge)
    # fleet telemetry: claim this process's producer identity as a
    # campaign `run` child BEFORE the orchestrator's own idempotent
    # ensure_self_relay can name it "orchestrator"; precedence CLI >
    # $NMZ_TELEMETRY_URL (the campaign supervisor's export) > config
    if args.telemetry_url:
        cfg.set("telemetry_url", args.telemetry_url)
    obs.configure_from_config(cfg)  # honor telemetry_enabled = false
    obs.federation.ensure_self_relay(
        "run",
        push_url=(args.telemetry_url
                  or os.environ.get("NMZ_TELEMETRY_URL", "")
                  or str(cfg.get("telemetry_url", "") or "")),
        interval_s=float(cfg.get("telemetry_interval_s", 2.0) or 2.0))
    # continuous profiling (doc/observability.md "Profiling"): same
    # claim-before-the-orchestrator rule as the relay above, so the
    # profile rides this child's telemetry as job "run"
    obs.profiling.ensure_profiler("run", cfg=cfg)

    run_deadline = _deadline(args.run_deadline, cfg, "run_deadline_s")
    validate_deadline = _deadline(args.validate_deadline, cfg,
                                  "validate_deadline_s")
    clean_deadline = _deadline(args.clean_deadline, cfg, "clean_deadline_s")

    orchestrator = Orchestrator(cfg, policy, collect_trace=True)
    orchestrator.start()

    successful = False
    recorded = False
    start = time.monotonic()
    # the clean script runs in the OUTER finally no matter how the run
    # ends — a failed validate, a deadline kill, or a Ctrl-C after the
    # run script must not leak testee state (ports, scratch files,
    # half-dead processes) into the next run of the campaign loop
    try:
        try:
            run_script = cfg.get("run")
            if not run_script:
                print("error: config has no 'run' script", file=sys.stderr)
                return EXIT_INFRA
            try:
                res = factory.run(run_script, deadline=run_deadline)
            except subprocess.TimeoutExpired:
                print(f"error: run script exceeded its {run_deadline:.1f}s "
                      "deadline; killed its process group; not recording "
                      "this run", file=sys.stderr)
                return EXIT_TIMEOUT
            if res.returncode != 0:
                # infra failure, not an experiment outcome: abort without
                # recording so it cannot pollute repro-rate stats or the
                # search plane's failure archive (parity: cli/run.go aborts
                # when the run command errors)
                print(f"error: run script exited {res.returncode}; "
                      "not recording this run", file=sys.stderr)
                return EXIT_INFRA
        finally:
            trace = orchestrator.shutdown()
            # stop fast-forwarding before validate/clean: the oracle
            # runs at wall rate, and the restored default TimeSource
            # must not leak a jumped clock into the next in-process run
            if vclock_handle is not None:
                vclock_summary = vclock_handle.finish()

        validate_script = cfg.get("validate")
        if validate_script:
            try:
                successful = factory.run(
                    validate_script,
                    deadline=validate_deadline).returncode == 0
            except subprocess.TimeoutExpired:
                print("error: validate script exceeded its "
                      f"{validate_deadline:.1f}s deadline; killed its "
                      "process group; not recording this run",
                      file=sys.stderr)
                return EXIT_TIMEOUT
        required_time = time.monotonic() - start

        from namazu_tpu.signal.base import HINT_SPACE

        storage.record_new_trace(trace)
        # stamp the replay-hint format version: a future format bump must
        # be able to tell (and skip) histories whose recorded event_hint
        # strings hash into a different bucket space (policy/tpu.py
        # _ingest_history)
        metadata = {"hint_space": HINT_SPACE}
        if vclock_summary is not None:
            # required_time (and every rate derived from it) stays
            # wall-denominated — SPRT budgets and calibration artifacts
            # must keep comparing like with like; the virtual elapsed
            # rides as separate metadata for the virtual-rate surfaces
            metadata["virtual_time_s"] = vclock_summary[
                "virtual_elapsed_s"]
            metadata["wall_time_s"] = vclock_summary["wall_elapsed_s"]
            metadata["vclock_speedup"] = vclock_summary["speedup_ratio"]
            metadata["vclock_pinned_s"] = vclock_summary["pinned_s"]
        storage.record_result(successful, required_time,
                              metadata=metadata)
        recorded = True

        extra = ""
        if vclock_summary is not None:
            extra = (f" virtual={vclock_summary['virtual_elapsed_s']:.2f}s"
                     f" speedup={vclock_summary['speedup_ratio']}x")
        print(f"run finished: successful={successful} "
              f"time={required_time:.2f}s{extra} trace={len(trace)} "
              f"actions workdir={working_dir}")
        return EXIT_OK
    finally:
        # abort paths (deadline kill, infra failure, Ctrl-C) must also
        # restore the wall TimeSource; finish() is idempotent
        if vclock_handle is not None:
            vclock_handle.finish()
        if not recorded:
            # deliberate abort (infra failure / deadline / interrupt):
            # mark the allocated run dir so fsck can tell it from a
            # crash and analytics never mistakes it for data
            try:
                storage.quarantine_current_run(
                    "run aborted before a result was recorded")
            except Exception as e:
                print(f"warning: could not mark aborted run: {e}",
                      file=sys.stderr)
        # crash-safe close: a storage backend flushing remote state
        # (mongodb) must not turn a recorded run into a failed exit
        try:
            storage.close()
        except Exception as e:
            print(f"warning: storage close failed: {e}", file=sys.stderr)
        clean_script = cfg.get("clean")
        if clean_script:
            try:
                factory.run(clean_script, deadline=clean_deadline)
            except subprocess.TimeoutExpired:
                print("warning: clean script exceeded its "
                      f"{clean_deadline:.1f}s deadline; killed its "
                      "process group", file=sys.stderr)
            except Exception as e:
                print(f"warning: clean script failed: {e}", file=sys.stderr)
