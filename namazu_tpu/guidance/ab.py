"""Seeded guided-vs-blind A/B: the guidance plane's acceptance driver.

``nmz-tpu tools ab-guided`` (and the tier-1 "Guidance A/B smoke") runs
two campaigns of equal run budget over ONE deterministic workload —
the same event schedule, the same per-run arrival jitter, the same
mutation kernel — differing ONLY in what guides them:

* **blind** — the pre-guidance loop: mutate uniformly-chosen delay
  buckets, keep a candidate when its realized interleaving has a new
  ``trace_digest`` (digest novelty, the old coverage currency);
* **guided** — the causality-guided loop: mutation buckets sampled
  from the CoverageMap's bias (one-sided relations first), candidates
  chosen by predicted relation-coverage gain, every executed run
  observed back into the map (observe -> score -> mutate, closed).

Both arms' runs are recorded into REAL storages (actions with hints,
arrivals, and realized release stamps), so the acceptance claims are
checked on the same surfaces operators use: the arms' relation-
coverage curves come straight out of ``obs/analytics.py`` — the exact
``GET /analytics`` payload — not from driver-private accounting.

The workload's oracle is a relation bug: it "reproduces" exactly when
one specific ordering relation flips against its arrival order — the
regime PCT-style ordering-aware search exists for. The acceptance
criteria (doc/search.md):

* the guided arm reaches >= ``min_ratio`` (default 1.25x) the blind
  arm's relation coverage at equal run budget;
* the guided arm's time-to-first-failure is no worse;
* the guided arm's relation-coverage curve DOMINATES the blind arm's
  (cumulative coverage >= the blind arm's at >= 95% of run indices —
  a whole-curve statistic, robust where any single saturation index
  is run-to-run noise).

Everything derives from the seed — a red run is a deterministic repro.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from namazu_tpu.guidance.coverage import CoverageMap
from namazu_tpu.guidance.signature import hint_bucket
from namazu_tpu.utils.log import get_logger

log = get_logger("guidance.ab")

#: workload shape: EVENTS slots round-robin over ENTITIES entities and
#: IDENTITIES distinct hints; arrivals GAP_S apart with JITTER_S of
#: seeded per-run noise. Delay tables live in [0, MAX_DELAY_S].
ENTITIES = 2
IDENTITIES = 12
GAP_S = 0.010
JITTER_S = 0.002
MAX_DELAY_S = 0.100
#: mutation kernel (shared verbatim by both arms)
MUTATE_BUCKETS = 3
MUTATE_SIGMA = 0.025
#: candidate fan per run slot (the blind arm gets the same number of
#: DRAWS but no simulator to rank them with — it executes its first
#: digest-novel candidate, the pre-guidance acceptance rule)
CANDIDATES = 6
#: guided mutation-bias peak (CoverageMap.mutation_bias max_boost) —
#: the hottest one-sided bucket mutates this many times as often
BIAS_BOOST = 8.0


def _schedule(events: int) -> List[Tuple[str, str]]:
    return [(f"e{i % ENTITIES}", f"k{i % IDENTITIES:02d}")
            for i in range(events)]


def _arrivals(rng: np.random.Generator, events: int) -> np.ndarray:
    base = np.arange(events, dtype=np.float64) * GAP_S
    return base + rng.uniform(0.0, JITTER_S, size=events)


def _oracle_pair(schedule, H: int) -> Tuple[int, int, float,
                                            Tuple[str, str]]:
    """The workload's planted relation bug: pick two identities whose
    first occurrences arrive ~6 slots apart and hash to distinct
    buckets (so a delay table CAN separate them) — the bug fires when
    the later identity's first event is dispatched before the earlier
    one's (a genuine ordering flip a blind delay walk rarely
    produces). Returns the two identities' first SCHEDULE SLOTS (the
    oracle checks those exact events' dispatch ranks — keying on
    buckets would let an unrelated colliding identity satisfy it),
    the natural arrival gap, and the hints for the report."""
    first_pos: Dict[str, int] = {}
    for i, (_e, hint) in enumerate(schedule):
        first_pos.setdefault(hint, i)
    hints = sorted(first_pos, key=lambda h: first_pos[h])
    a = hints[1]
    b = hints[min(len(hints) - 1, 7)]
    if hint_bucket(a, H) == hint_bucket(b, H):
        # same-bucket pair: a delay table cannot separate them — slide
        for h in hints[2:]:
            if hint_bucket(h, H) != hint_bucket(a, H) \
                    and first_pos[h] > first_pos[a]:
                b = h
                break
    gap = (first_pos[b] - first_pos[a]) * GAP_S
    return first_pos[a], first_pos[b], gap, (a, b)


class _Arm:
    """One campaign arm: current table + per-run realization loop."""

    def __init__(self, name: str, H: int, width: int,
                 window: int) -> None:
        self.name = name
        self.table = np.zeros((H,), np.float32)
        self.H = H
        # the MEASUREMENT map: both arms are scored in this space; only
        # the guided arm also READS it (bias + gain)
        self.coverage = CoverageMap(H=H, width=width, window=window)
        self.seen_digests: set = set()
        self.bits_curve: List[int] = []
        self.repro_runs: List[int] = []
        self.runs = 0

    def realize(self, buckets: np.ndarray,
                arrivals: np.ndarray,
                table: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(dispatch order permutation, realized times) under the
        delay-mode release rule."""
        times = arrivals + table[buckets]
        order = np.argsort(times, kind="stable")
        return order, times


def _mutate(table: np.ndarray, picks: np.ndarray,
            noise: np.ndarray) -> np.ndarray:
    out = table.copy()
    out[picks] = np.clip(out[picks] + noise, 0.0, MAX_DELAY_S)
    return out


def run_ab(workdir: str, seed: int = 7, runs: int = 72,
           events: int = 24, H: int = 64, width: int = 2048,
           window: int = 8, min_ratio: float = 1.25,
           example: str = "") -> Dict[str, Any]:
    """Run the guided-vs-blind pair; returns the acceptance report.

    ``example`` (optional, e.g. ``examples/flaky-init``) seeds the
    workload identity space from the example's config — the A/B then
    measures guidance over that experiment's hint population instead
    of the synthetic default."""
    schedule = _schedule(events)
    if example:
        from_example = _example_schedule(example, events)
        if from_example is None:
            # loud, not a silent synthetic fallback: a typo'd example
            # path must not green-light as if it validated the example
            raise ValueError(
                f"example {example!r} has no loadable config.toml")
        schedule = from_example
    buckets = np.asarray([hint_bucket(h, H) for _e, h in schedule],
                         np.int64)
    entities = [e for e, _h in schedule]
    hints = [h for _e, h in schedule]
    slot_a, slot_b, gap, oracle_hints = _oracle_pair(schedule, H)

    arms = {
        "blind": _Arm("blind", H, width, window),
        "guided": _Arm("guided", H, width, window),
    }
    for arm in arms.values():
        st = _new_storage(os.path.join(workdir, arm.name))
        for r in range(runs):
            # identical per-run arrival realization for both arms:
            # the rng is keyed by (seed, run), not by arm
            arr_rng = np.random.default_rng([seed, r])
            arrivals = _arrivals(arr_rng, len(schedule))
            mut_rng = np.random.default_rng(
                [seed, r, 1 if arm.name == "guided" else 0])
            candidate = _next_candidate(arm, buckets, arrivals, mut_rng)
            order, times = arm.realize(buckets, arrivals, candidate)
            seq = buckets[order]
            # oracle: did the planted relation flip? Checked on the
            # two chosen identities' EXACT schedule slots (their
            # dispatch ranks), immune to other identities sharing a
            # bucket with them
            rank = np.empty((len(order),), np.int64)
            rank[order] = np.arange(len(order))
            reproduced = bool(rank[slot_b] < rank[slot_a])
            arm.table = candidate
            arm.seen_digests.add(tuple(int(b) for b in seq))
            arm.coverage.observe(seq)
            arm.bits_curve.append(arm.coverage.covered())
            arm.runs += 1
            if reproduced:
                arm.repro_runs.append(r)
            _record_run(st, entities, hints, arrivals, times,
                        ok=not reproduced)
        st.close()

    report = _report(arms, workdir, runs, min_ratio, seed,
                     oracle={"early": oracle_hints[0],
                             "late": oracle_hints[1],
                             "arrival_gap_s": round(gap, 4)})
    return report


def _next_candidate(arm: _Arm, buckets: np.ndarray,
                    arrivals: np.ndarray,
                    rng: np.random.Generator) -> np.ndarray:
    """One run slot's executed table. Both arms draw CANDIDATES times
    from the SAME kernel; they differ in bucket choice (uniform vs
    bias-weighted) and in the acceptance rule (first digest-novel vs
    best predicted relation gain)."""
    H = arm.H
    if arm.name == "blind":
        # pre-guidance loop: execute the first candidate whose
        # simulated interleaving has a new digest (else the last draw)
        chosen = arm.table
        for _ in range(CANDIDATES):
            picks = rng.integers(0, H, size=MUTATE_BUCKETS)
            noise = rng.normal(0.0, MUTATE_SIGMA, size=MUTATE_BUCKETS)
            cand = _mutate(arm.table, picks, noise)
            order, _ = arm.realize(buckets, arrivals, cand)
            chosen = cand
            if tuple(int(b) for b in buckets[order]) \
                    not in arm.seen_digests:
                break
        return chosen
    # guided loop: bias-weighted bucket choice, gain-ranked acceptance
    bias = arm.coverage.mutation_bias(max_boost=BIAS_BOOST)
    p = bias / bias.sum()
    best, best_gain = arm.table, -1.0
    for _ in range(CANDIDATES):
        picks = rng.choice(H, size=MUTATE_BUCKETS, p=p)
        noise = rng.normal(0.0, MUTATE_SIGMA, size=MUTATE_BUCKETS)
        cand = _mutate(arm.table, picks, noise)
        order, _ = arm.realize(buckets, arrivals, cand)
        gain = arm.coverage.predicted_gain(buckets[order])
        if gain > best_gain:
            best, best_gain = cand, gain
    return best


# -- real-surface recording + the report -----------------------------------

def _new_storage(path: str):
    from namazu_tpu.storage import new_storage

    st = new_storage("naive", path)
    st.create()
    return st


def _record_run(st, entities, hints, arrivals, times, ok: bool) -> None:
    """One simulated run recorded the way a real run is: actions with
    hints, arrivals, realized release stamps — so analytics computes
    the arm's curves from the same storage surface a live campaign
    produces.

    Actions are appended in PROGRAM order (the workload's fixed event
    schedule), with the realized ordering carried by the release
    stamps. The ``trace_digest`` is deliberately timing-invariant over
    the appended hint/entity sequence (PR 1: it counts failure MODES),
    so on the A/B artifact the digest curve saturates immediately —
    the mode space of a fixed program is one mode — while the relation
    curve keeps growing with every newly realized ordering. That is
    the decoupling the guidance plane exists to expose: digest
    coverage reads "done" exactly where ordering exploration has
    barely started."""
    from namazu_tpu.signal import PacketEvent
    from namazu_tpu.signal.action import EventAcceptanceAction
    from namazu_tpu.utils.trace import SingleTrace

    st.create_new_working_dir()
    trace = SingleTrace()
    base = 1000.0
    for i in range(len(hints)):
        ev = PacketEvent.create(entities[i], entities[i], "peer",
                                hint=hints[i])
        a = EventAcceptanceAction.for_event(ev)
        a.event_arrived = base + float(arrivals[i])
        a.triggered_time = base + float(times[i])
        trace.append(a)
    st.record_new_trace(trace)
    st.record_result(ok, GAP_S * len(hints))


def _curve_last_growth(curve: List[int]) -> int:
    """Index of the last run that grew the curve (-1 for an empty or
    flat curve) — "saturates later" = a larger value."""
    last = -1
    prev = 0
    for i, v in enumerate(curve):
        if v > prev:
            last = i
        prev = v
    return last


def _analytics_payload(storage_dir: str) -> Optional[Dict[str, Any]]:
    from namazu_tpu.obs import analytics
    from namazu_tpu.storage import load_storage

    st = load_storage(storage_dir)
    try:
        return analytics.compute_payload(storage=st, publish=False)
    finally:
        st.close()


def _report(arms, workdir, runs, min_ratio, seed, oracle) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "schema": "nmz-guidance-ab-v1",
        "seed": seed,
        "runs_per_arm": runs,
        "min_ratio": min_ratio,
        "oracle": oracle,
        "arms": {},
    }
    for name, arm in arms.items():
        payload = _analytics_payload(os.path.join(workdir, name))
        cov = (payload or {}).get("coverage", {})
        out["arms"][name] = {
            "relation_bits": arm.coverage.covered(),
            "relation_occupancy": round(arm.coverage.occupancy(), 4),
            "bits_curve": arm.bits_curve,
            "curve_last_growth_run": _curve_last_growth(arm.bits_curve),
            "one_sided": arm.coverage.one_sided_count(),
            "unique_digests": len(arm.seen_digests),
            "repros": len(arm.repro_runs),
            "time_to_first_failure_run": (arm.repro_runs[0]
                                          if arm.repro_runs else None),
            "analytics_coverage": {
                k: cov.get(k)
                for k in ("unique_interleavings", "saturated",
                          "relation_bits", "relation_curve",
                          "relation_saturated",
                          "relation_frontier_bits",
                          "digests_saturated_relations_growing")
            },
        }
    blind, guided = out["arms"]["blind"], out["arms"]["guided"]
    ratio = (guided["relation_bits"] / blind["relation_bits"]
             if blind["relation_bits"] else float("inf"))
    ttff_b = blind["time_to_first_failure_run"]
    ttff_g = guided["time_to_first_failure_run"]
    # "no worse": found at least as early, or the blind arm never found
    # it at all (None sorts as worst)
    ttff_ok = (ttff_b is None
               or (ttff_g is not None and ttff_g <= ttff_b))
    # curve dominance: at what fraction of the run budget the guided
    # arm's cumulative relation coverage was >= the blind arm's. The
    # acceptance asks for dominance, not one lucky endpoint — a single
    # "last growth run" index is run-to-run noise; >= 95% of the whole
    # curve is not.
    ca, cb = blind["bits_curve"], guided["bits_curve"]
    dominance = (sum(1 for x, y in zip(ca, cb) if y >= x)
                 / len(ca) if ca else 0.0)
    out["coverage_ratio"] = round(ratio, 3)
    out["coverage_ratio_ok"] = ratio >= min_ratio
    out["ttff_ok"] = ttff_ok
    out["curve_dominance"] = round(dominance, 3)
    out["curve_dominance_ok"] = dominance >= 0.95
    out["ok"] = bool(out["coverage_ratio_ok"] and ttff_ok
                     and out["curve_dominance_ok"])
    return out


def _example_schedule(example: str,
                      events: int) -> Optional[List[Tuple[str, str]]]:
    """Derive the identity space from an example's config (best
    effort): the policy's seed + proc-policy shape vary the hint
    population so the A/B exercises that experiment's bucket layout."""
    from namazu_tpu.utils.config import Config

    cfg_path = os.path.join(example, "config.toml")
    if not os.path.exists(cfg_path):
        return None
    try:
        cfg = Config.from_file(cfg_path)
    except Exception:
        return None
    name = os.path.basename(os.path.abspath(example))
    policy = str(cfg.get("explore_policy") or "random")
    return [(f"e{i % ENTITIES}", f"{name}:{policy}:k{i % IDENTITIES:02d}")
            for i in range(events)]
