"""Relation-coverage signatures: which orderings did a run exercise?

The search plane's coverage currency so far is the ``trace_digest`` —
one opaque hash per realized interleaving. Two runs that differ in ONE
ordering relation count as two digests, and ten runs that each explore
a genuinely new region of the ordering space count the same as ten
near-identical replays. This module refines the currency to the unit
the fuzzer actually controls: **ordering relations** between
occurrence-indexed hint buckets (doc/search.md).

Identity
--------
An event's relation identity is its **hint bucket** — ``fnv64a(replay
hint) % H``, the exact unit the genome's delay table indexes and the
precedence-pair features sample (ops/trace_encoding.py) — made unique
by occurrence index (the k-th event of bucket ``b`` is ``b#k``).
Using the bucket rather than the raw hint string means the SAME
signature space is derivable from three sources:

* flight-recorder record docs (``hint`` field -> bucket),
* stored traces (``event_hint`` -> bucket, the ``failure_seed``
  convention),
* encoded traces (``hint_ids`` ARE buckets) — which is what lets the
  search predict the relations a **candidate** table would exercise by
  simulating its release order, without ever executing it.

A relation is the DIRECTED pair "``x`` dispatched before ``y``" for
``x``, ``y`` within :data:`DEFAULT_WINDOW` dispatch positions of each
other (far-apart pairs are transitively implied by the chain of nearby
ones, and a delay perturbation can realistically flip only nearby
pairs). Each relation hashes into one bit of a fixed-width bitmap, so
signatures vectorize (numpy bool ops), pool by OR (knowledge plane),
and compare in O(width).

Determinism: every function here is a pure function of its inputs —
no wall clock, no global state — so two replays of the same recorded
run produce bit-identical signatures (pinned by
tests/test_guidance.py).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from namazu_tpu.policy.replayable import fnv64a

__all__ = [
    "DEFAULT_WIDTH", "DEFAULT_WINDOW", "SCAN_CAP", "GUIDANCE_DIMS",
    "hint_bucket", "bucket_sequence_from_docs",
    "bucket_sequence_from_trace", "bucket_sequence_from_encoded",
    "occurrence_index", "relation_pairs", "pair_bit", "signature_bits",
    "reverse_signature_bits",
    "dag_shape_features",
]

#: bitmap width (bits) of a relation signature. 4096 bits = 512 bytes
#: per campaign on the wire; at the DEFAULT_WINDOW pair density a run of
#: a few hundred events sets a few thousand candidate bits, so the map
#: saturates from genuine diversity, not from birthday collisions.
DEFAULT_WIDTH = 4096

#: relation window: ordered pairs are collected between events within
#: this many DISPATCH positions of each other. Far-apart relations are
#: transitively implied by the chain of nearby ones, and the per-run
#: pair count stays O(n * window) instead of O(n^2).
DEFAULT_WINDOW = 16

#: dispatch-order scan cap per run (the FLIP_SCAN_CAP stance,
#: obs/causality.py): past it the tail is dropped from the signature —
#: a bounded derivation that can run inside a live /analytics read.
SCAN_CAP = 512

#: dimensionality of the DAG-shape feature fragment appended to the
#: surrogate's precedence features when guidance is on: a
#: (GUIDANCE_DIMS - 4)-bucket fold of the relation bitmap plus four
#: shape scalars (see :func:`dag_shape_features`).
GUIDANCE_DIMS = 20


def hint_bucket(hint: str, H: int) -> int:
    """The relation identity of a hint — same formula as the delay
    table's index (policy/tpu.py ``_bucket``) and the trace encoder."""
    return int(fnv64a(hint.encode()) % H)


# -- bucket-sequence adapters (one canonical space, three sources) ---------

def bucket_sequence_from_docs(record_docs: Iterable[dict],
                              H: int) -> np.ndarray:
    """Dispatch-ordered hint buckets from flight-recorder record docs
    (the NDJSON shape — a live RunTrace snapshot, a ``GET /traces``
    body, or a dump file). Pure function of the docs: ordering comes
    from the recorded ``dispatched`` stamps, identity from the recorded
    hint (falling back to ``class:entity``, the ``failure_seed``
    convention for hint-less events)."""
    rows = []
    for doc in record_docs:
        t = doc.get("t") or {}
        if doc.get("kind") or "dispatched" not in t:
            continue  # search-plane entries / never-dispatched events
        hint = doc.get("hint") or (
            f"{doc.get('event_class') or 'event'}:"
            f"{doc.get('entity') or ''}")
        rows.append((t["dispatched"], hint_bucket(hint, H)))
    rows.sort(key=lambda r: r[0])
    return np.asarray([b for _, b in rows], np.int32)


def bucket_sequence_from_trace(trace, H: int) -> np.ndarray:
    """Dispatch-ordered hint buckets from a STORED trace's actions
    (``triggered_time`` is the realized release stamp) — the adapter
    the analytics plane uses, so the relation curve over a storage and
    the live guidance map count in one currency."""
    rows = []
    for a in trace:
        tt = a.triggered_time
        if not tt:
            continue
        hint = getattr(a, "event_hint", "") or \
            f"{a.event_class or a.class_name()}:{a.entity_id}"
        rows.append((tt, hint_bucket(hint, H)))
    rows.sort(key=lambda r: r[0])
    return np.asarray([b for _, b in rows], np.int32)


def bucket_sequence_from_encoded(enc,
                                 times: Optional[np.ndarray] = None
                                 ) -> np.ndarray:
    """Dispatch-ordered hint buckets from an encoded trace. ``times``
    overrides the encoding's own time vector — THE candidate-simulation
    hook: pass ``arrival + delays[hint_ids]`` and the returned sequence
    is the order a candidate delay table would realize against these
    arrivals (delay mode's exact release rule), so its predicted
    relation coverage is one :func:`signature_bits` call away."""
    m = enc.mask
    buckets = enc.hint_ids[m]
    t = (enc.arrival[m] if times is None else np.asarray(times)[m])
    order = np.argsort(t, kind="stable")
    return np.asarray(buckets[order], np.int32)


# -- the signature ---------------------------------------------------------

def occurrence_index(buckets: Sequence[int]) -> np.ndarray:
    """Per-position occurrence index: ``occ[i]`` = how many earlier
    positions hold the same bucket (the k-th event of bucket ``b`` is
    identity ``b#k``). Vectorized — grouped by a stable sort."""
    seq = np.asarray(buckets, np.int64)
    n = len(seq)
    occ = np.zeros((n,), np.int64)
    if n == 0:
        return occ
    order = np.argsort(seq, kind="stable")
    srt = seq[order]
    grp_start = np.r_[0, np.flatnonzero(np.diff(srt)) + 1]
    starts = np.repeat(grp_start, np.diff(np.r_[grp_start, n]))
    occ[order] = np.arange(n) - starts
    return occ


#: splitmix64 finalizer constants — a fixed, dependency-free integer
#: mix so the bit assignment is pure arithmetic (vectorizes over whole
#: candidate populations) and stable across processes/builds
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_KEY_STRIDE = np.uint64(1_000_003)


def _mix64(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * _MIX1
        x = (x ^ (x >> np.uint64(27))) * _MIX2
        return x ^ (x >> np.uint64(31))


def _pair_keys(buckets: Sequence[int], window: int, cap: int):
    """``(bx, ox, by, oy, gaps)`` column arrays of every directed
    in-window relation of a dispatch order (x strictly before y,
    within ``window`` positions); ``gaps`` is each pair's positional
    distance — emitted here, where the block layout is defined, so no
    caller has to re-derive it from the emission order.

    SAME-bucket pairs are excluded: occurrence indices are assigned in
    dispatch order, so "b#k before b#k+1" holds by construction in
    every run — a tautology that carries no ordering information, can
    never flip, and would permanently inflate the one-sided frontier
    (and the mutation bias aimed at it) with unreachable relations."""
    seq = np.asarray(buckets, np.int64)[:cap]
    occ = occurrence_index(seq)
    cols = ([], [], [], [], [])
    n = len(seq)
    for d in range(1, min(window, n - 1) + 1 if n > 1 else 1):
        keep = seq[:-d] != seq[d:]
        cols[0].append(seq[:-d][keep])
        cols[1].append(occ[:-d][keep])
        cols[2].append(seq[d:][keep])
        cols[3].append(occ[d:][keep])
        cols[4].append(np.full((int(keep.sum()),), d, np.int64))
    if not cols[0]:
        empty = np.zeros((0,), np.int64)
        return empty, empty, empty, empty, empty
    return tuple(np.concatenate(c) for c in cols)


def _keys_to_bits(bx, ox, by, oy, width: int) -> np.ndarray:
    with np.errstate(over="ignore"):
        key = bx.astype(np.uint64)
        for part in (ox, by, oy):
            key = key * _KEY_STRIDE + part.astype(np.uint64)
    return (_mix64(key) % np.uint64(width)).astype(np.int64)


def relation_pairs(buckets: Sequence[int],
                   window: int = DEFAULT_WINDOW,
                   cap: int = SCAN_CAP
                   ) -> List[Tuple[int, int, int, int]]:
    """The directed relations a dispatch order exercises, as python
    tuples ``(bucket_x, occ_x, bucket_y, occ_y)`` — the identity-
    bearing form the CoverageMap's pair table keys on. Repeated
    buckets occurrence-disambiguate against OTHER buckets' events;
    same-bucket pairs are excluded as tautologies (``_pair_keys``)."""
    bx, ox, by, oy, _gaps = _pair_keys(buckets, window, cap)
    return [(int(a), int(b), int(c), int(d))
            for a, b, c, d in zip(bx, ox, by, oy)]


def pair_bit(bx: int, ox: int, by: int, oy: int,
             width: int = DEFAULT_WIDTH) -> int:
    """The bitmap bit of one directed relation. Direction is encoded in
    the key ordering, so "x before y" and "y before x" land on (almost
    surely) different bits — a flip COVERS new ground."""
    return int(_keys_to_bits(*(np.asarray([v], np.int64)
                               for v in (bx, ox, by, oy)),
                             width)[0])


def signature_bits(buckets: Sequence[int],
                   width: int = DEFAULT_WIDTH,
                   window: int = DEFAULT_WINDOW,
                   cap: int = SCAN_CAP) -> np.ndarray:
    """One run's relation-coverage signature as sorted unique bit
    indices (int64). ``np.zeros(width, bool)`` with these set is the
    bitmap form; the sparse form is what travels the knowledge wire.
    Fully vectorized — cheap enough to run per CANDIDATE inside the
    guided pick, not just per executed run."""
    bx, ox, by, oy, _gaps = _pair_keys(buckets, window, cap)
    if not len(bx):
        return np.zeros((0,), np.int64)
    return np.unique(_keys_to_bits(bx, ox, by, oy, width))


def reverse_signature_bits(buckets: Sequence[int],
                           width: int = DEFAULT_WIDTH,
                           window: int = DEFAULT_WINDOW,
                           cap: int = SCAN_CAP) -> np.ndarray:
    """The bits a run's relations would cover FLIPPED — each observed
    "x before y" hashed as "y before x". The difference
    ``reverse_bits - covered_bits`` across a campaign is its open
    frontier in bit space: orderings whose one direction was exercised
    while the other never was, i.e. exactly where relation coverage
    can still grow after digest novelty reads saturated."""
    bx, ox, by, oy, _gaps = _pair_keys(buckets, window, cap)
    if not len(bx):
        return np.zeros((0,), np.int64)
    return np.unique(_keys_to_bits(by, oy, bx, ox, width))


# -- DAG-shape features (surrogate extension, doc/search.md) ---------------

def dag_shape_features(buckets_program: np.ndarray,
                       times_program: np.ndarray,
                       times_dispatch: np.ndarray,
                       width: int = DEFAULT_WIDTH,
                       dims: int = GUIDANCE_DIMS) -> np.ndarray:
    """A ``dims``-float summary of a run's happens-before SHAPE, the
    fragment appended to the surrogate's precedence features when
    guidance is on (models/search.py ``surrogate_feats_of``):

    * ``dims - 4`` values — the relation bitmap folded into that many
      buckets (bit count per fold, normalized by total relations): a
      coarse "which ordering regions did this run touch";
    * 4 shape scalars — program/dispatch edge-crossing density (the
      fraction of adjacent program-order pairs inverted in dispatch
      order — how hard the schedule reordered the testee), mean
      normalized displacement between the two orders, distinct-bucket
      density, and relation-bit density.

    All inputs are masked 1-D arrays over the same events; program and
    dispatch orders are derived from their respective time vectors.
    Pure and deterministic, like everything in this module.
    """
    n = len(buckets_program)
    out = np.zeros((dims,), np.float32)
    if n == 0 or dims <= 4:
        return out
    buckets = np.asarray(buckets_program)
    order_p = np.argsort(np.asarray(times_program), kind="stable")
    order_d = np.argsort(np.asarray(times_dispatch), kind="stable")
    rank_d = np.empty((n,), np.int64)
    rank_d[order_d] = np.arange(n)
    # dispatch ranks walked in program order: crossings and
    # displacement of the realized order against the testee's own
    prog_ranks = rank_d[order_p]
    seq = buckets[order_d]
    bits = signature_bits(seq, width=width)
    fold = dims - 4
    if len(bits):
        counts = np.bincount(bits % fold, minlength=fold)
        out[:fold] = counts / float(len(bits))
    if n > 1:
        out[fold] = float((np.diff(prog_ranks) < 0).sum()) / (n - 1)
        out[fold + 1] = float(
            np.abs(prog_ranks - np.arange(n)).mean()) / (n - 1)
    out[fold + 2] = len(np.unique(buckets)) / float(n)
    out[fold + 3] = min(1.0, len(bits) / float(max(1, n)))
    return out
