"""Guidance plane: causality-guided search (doc/search.md).

PR 10's causality plane made per-run happens-before structure
observable; this package makes it the search OBJECTIVE. The loop:

* :mod:`namazu_tpu.guidance.signature` — derive from each run a
  compact **relation-coverage signature**: which occurrence-indexed
  (hint-bucket, hint-bucket) ordering relations the run exercised,
  hashed into a fixed-width bitmap. Pure function of the recorded
  run — deterministic, wall-clock-free.
* :mod:`namazu_tpu.guidance.coverage` — the per-campaign
  :class:`CoverageMap`: novelty accounting (a run is interesting when
  it first-covers or FLIPS a relation, not merely when its digest is
  new), candidate-order gain prediction, one-sided-relation frontier,
  and the per-bucket mutation bias.
* :mod:`namazu_tpu.guidance.ab` — the seeded guided-vs-blind A/B
  acceptance driver (``nmz-tpu tools ab-guided``, the tier-1 smoke).

Integration points: ``models/search.py`` (coverage-guided candidate
pick + biased mutation through ``models/ga.py``/``parallel/islands``),
``models/ingest.py`` (map rebuild from history + knowledge-plane
coverage push/pull), ``obs/analytics.py`` (the relation-coverage curve
next to the digest curve), ``nmz-tpu tools coverage``.
"""

from __future__ import annotations

from namazu_tpu.guidance.coverage import (  # noqa: F401
    CoverageDelta,
    CoverageMap,
    MAX_PAIRS,
)
from namazu_tpu.guidance.signature import (  # noqa: F401
    DEFAULT_WIDTH,
    DEFAULT_WINDOW,
    GUIDANCE_DIMS,
    SCAN_CAP,
    bucket_sequence_from_docs,
    bucket_sequence_from_encoded,
    bucket_sequence_from_trace,
    dag_shape_features,
    hint_bucket,
    occurrence_index,
    pair_bit,
    relation_pairs,
    reverse_signature_bits,
    signature_bits,
)
