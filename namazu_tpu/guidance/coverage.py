"""Per-campaign relation-coverage accounting: the guidance frontier.

A :class:`CoverageMap` folds every executed run's relation signature
(guidance/signature.py) into one campaign-wide view and answers the
three questions the guided search loop asks (doc/search.md):

* **novelty** — did this run first-cover a relation, or flip a
  one-sided one? (:meth:`observe` returns the delta; a run is
  *interesting* when either happened, not merely when its digest is
  new);
* **prediction** — how much uncovered ground would a CANDIDATE order
  reach? (:meth:`predicted_gain` over a simulated bucket sequence —
  the coverage-guided fitness bonus);
* **direction** — which delay-table buckets participate in one-sided
  relations, i.e. where should mutation concentrate?
  (:meth:`mutation_bias` -> a per-bucket mutation-rate multiplier,
  :meth:`one_sided` -> the ranked frontier the CLI prints).

Two representations, one truth: a fixed-width bitmap (vectorized
novelty math, OR-pooling through the knowledge plane) and a bounded
directed-pair table (one-sidedness, flip scores, bucket attribution —
hash bits alone cannot name the relation they came from). The pair
table is capped; overflow is COUNTED (``pair_overflow``), never
silent.

Thread-safe: the search thread observes while an analytics scrape or
the knowledge push reads.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from namazu_tpu.guidance.signature import (
    DEFAULT_WIDTH,
    DEFAULT_WINDOW,
    SCAN_CAP,
    _keys_to_bits,
    _pair_keys,
    signature_bits,
)

__all__ = ["CoverageDelta", "CoverageMap", "MAX_PAIRS"]

#: directed pairs remembered with full identity (the bitmap keeps
#: covering past this; only the *nameable* frontier is bounded)
MAX_PAIRS = 16384


class CoverageDelta(NamedTuple):
    """What one observed run added to the campaign's frontier."""
    new_bits: int  # bitmap bits first set by this run
    first_covered: int  # directed pairs seen for the first time
    flipped: int  # pairs whose REVERSE was known but this direction new
    interesting: bool  # new_bits > 0 or flipped > 0 (the novelty rule)


class CoverageMap:
    """The per-campaign relation-coverage frontier (module docstring)."""

    def __init__(self, H: int, width: int = DEFAULT_WIDTH,
                 window: int = DEFAULT_WINDOW,
                 max_pairs: int = MAX_PAIRS) -> None:
        self.H = int(H)
        self.width = int(width)
        self.window = int(window)
        self.max_pairs = int(max_pairs)
        self._lock = threading.Lock()
        self._bits = np.zeros((self.width,), bool)
        #: directed (bx, ox, by, oy) -> times seen
        self._pairs: Dict[Tuple[int, int, int, int], int] = {}
        #: directed pair -> min positional gap ever observed (a nearby
        #: pair is cheap to flip with a small delay; the flip-score
        #: denominator)
        self._gap: Dict[Tuple[int, int, int, int], int] = {}
        self.pair_overflow = 0
        self.runs_observed = 0
        #: cumulative covered-bit curve, one point per observed run
        self.curve: List[int] = []

    # -- feeding -----------------------------------------------------------

    def observe(self, buckets: Sequence[int]) -> CoverageDelta:
        """Fold one EXECUTED run's dispatch order into the map. ONE
        vectorized pair derivation feeds both the bitmap and the pair
        table (this runs per stored run on every ingest — a second
        interpreted window walk would double the dominant cost)."""
        seq = np.asarray(buckets, np.int64)[:SCAN_CAP]
        bx, ox, by, oy, gaps = _pair_keys(seq, self.window, SCAN_CAP)
        n = len(seq)
        if len(bx):
            bits = np.unique(_keys_to_bits(bx, ox, by, oy, self.width))
            # group repeated pairs OUTSIDE the lock: the dict fold then
            # touches each DISTINCT pair once (count + min gap come in
            # aggregated), so the interpreted per-occurrence walk —
            # the dominant ingest cost on hint-repetitive workloads —
            # collapses to the run's unique-pair count
            # collision-free composite: occurrences < SCAN_CAP+1 by
            # construction, buckets < 2^20 for any realistic H, and
            # the full key stays < 2^64
            comp = (((bx.astype(np.uint64) * np.uint64(SCAN_CAP + 1)
                      + ox.astype(np.uint64))
                     * np.uint64(2 ** 20) + by.astype(np.uint64))
                    * np.uint64(SCAN_CAP + 1) + oy.astype(np.uint64))
            _, first_idx, inverse, counts = np.unique(
                comp, return_index=True, return_inverse=True,
                return_counts=True)
            min_gaps = np.full((len(first_idx),), n + 1, np.int64)
            np.minimum.at(min_gaps, inverse, gaps)
        else:
            bits = np.zeros((0,), np.int64)
            first_idx = counts = min_gaps = np.zeros((0,), np.int64)
        with self._lock:
            new_bits = first = flipped = 0
            if len(bits):
                new_bits = int((~self._bits[bits]).sum())
                self._bits[bits] = True
            for k in range(len(first_idx)):
                i = int(first_idx[k])
                key = (int(bx[i]), int(ox[i]), int(by[i]), int(oy[i]))
                gap = int(min_gaps[k])
                count = int(counts[k])
                seen = self._pairs.get(key)
                if seen is None:
                    if len(self._pairs) < self.max_pairs:
                        self._pairs[key] = count
                        first += 1
                        self._gap[key] = gap
                        if (key[2], key[3],
                                key[0], key[1]) in self._pairs:
                            flipped += 1
                    else:
                        self.pair_overflow += count
                else:
                    self._pairs[key] = seen + count
                    if gap < self._gap.get(key, self.window + 1):
                        self._gap[key] = gap
            self.runs_observed += 1
            covered = int(self._bits.sum())
            self.curve.append(covered)
        return CoverageDelta(new_bits=new_bits, first_covered=first,
                             flipped=flipped,
                             interesting=new_bits > 0 or flipped > 0)

    def merge_bits(self, bit_indices: Sequence[int]) -> int:
        """OR fleet coverage into this map (knowledge warm-start:
        relations the FLEET already exercised are not this campaign's
        frontier). Returns how many bits were new locally. Pair
        identities don't travel the wire — merged bits dampen the
        novelty bonus but cannot (and need not) name relations."""
        with self._lock:
            fresh = 0
            for b in bit_indices:
                b = int(b)
                if 0 <= b < self.width and not self._bits[b]:
                    self._bits[b] = True
                    fresh += 1
            return fresh

    # -- reading -----------------------------------------------------------

    def covered(self) -> int:
        with self._lock:
            return int(self._bits.sum())

    def occupancy(self) -> float:
        return self.covered() / float(self.width)

    def bits_list(self) -> List[int]:
        """Sparse wire form (knowledge push)."""
        with self._lock:
            return [int(i) for i in np.flatnonzero(self._bits)]

    def predicted_gain(self, buckets: Sequence[int]) -> float:
        """Fraction of a candidate order's relations that are currently
        UNCOVERED — the coverage-guided fitness bonus in [0, 1]. 0 for
        an empty candidate (nothing predicted, nothing rewarded)."""
        bits = signature_bits(buckets, self.width, self.window)
        if not len(bits):
            return 0.0
        with self._lock:
            new = int((~self._bits[bits]).sum())
        return new / float(len(bits))

    def one_sided(self, top: Optional[int] = None) -> List[Dict[str, Any]]:
        """The nameable frontier: directed relations whose REVERSE was
        never observed, ranked by predicted flip score — count-weighted
        proximity (a pair dispatched 2 positions apart flips with a
        small delay nudge; one 30 positions apart realistically
        doesn't)."""
        with self._lock:
            rows = []
            for (bx, ox, by, oy), count in self._pairs.items():
                if (by, oy, bx, ox) in self._pairs:
                    continue  # both directions covered
                gap = self._gap.get((bx, ox, by, oy), self.window)
                score = count / float(1 + gap)
                rows.append({
                    "first": f"b{bx}#{ox}", "then": f"b{by}#{oy}",
                    "buckets": [bx, by],
                    "count": count, "min_gap": gap,
                    "flip_score": round(score, 4),
                })
        rows.sort(key=lambda r: (-r["flip_score"],
                                 r["first"], r["then"]))
        return rows if top is None else rows[:top]

    def one_sided_count(self) -> int:
        with self._lock:
            return sum(1 for (bx, ox, by, oy) in self._pairs
                       if (by, oy, bx, ox) not in self._pairs)

    def mutation_bias(self, max_boost: float = 4.0) -> np.ndarray:
        """Per-bucket mutation-rate multiplier f32[H] (>= 1 everywhere):
        buckets participating in one-sided relations get boosted in
        proportion to their summed flip scores, normalized so the
        hottest bucket mutates ``max_boost`` times as often. A map with
        no one-sided relations (or no observations) returns all-ones —
        guidance-off-equivalent mutation. Accumulated straight off the
        pair table (this runs every search round; the formatted
        ``one_sided`` rows are for humans)."""
        weight = np.zeros((self.H,), np.float64)
        with self._lock:
            for (bx, ox, by, oy), count in self._pairs.items():
                if (by, oy, bx, ox) in self._pairs:
                    continue
                gap = self._gap.get((bx, ox, by, oy), self.window)
                score = count / float(1 + gap)
                for b in (bx, by):
                    if 0 <= b < self.H:
                        weight[b] += score
        peak = weight.max()
        if peak <= 0:
            return np.ones((self.H,), np.float32)
        bias = 1.0 + (max_boost - 1.0) * (weight / peak)
        return np.asarray(bias, np.float32)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            covered = int(self._bits.sum())
            return {
                "H": self.H,
                "width": self.width,
                "window": self.window,
                "covered_bits": covered,
                "occupancy": round(covered / float(self.width), 4),
                "directed_pairs": len(self._pairs),
                "pair_overflow": self.pair_overflow,
                "runs_observed": self.runs_observed,
                "curve": list(self.curve[-64:]),
            }
