"""The multi-tenant knowledge service: the sidecar's global memory.

One instance serves every campaign on a host (or fleet, over DCN): a
content-keyed failure pool on disk, per-scenario best delay tables for
cold-run warm-starts, and a shared reward surrogate trained across
tenants. All writes are crash-safe (``utils/atomic.py`` for JSON state,
tmp+rename for pool entries), so a killed sidecar restarts into the
same knowledge — and because the pool is content-keyed, tenants that
re-push after the restart dedupe exactly-once instead of doubling
entries.

Feature-space discipline: surrogate features are precedence-pair
embeddings whose pair sample depends on the tenant's occupied hint
buckets, so examples are only poolable between searches that share a
pair sample. The service therefore keys surrogate stores by
``(scenario, pairs_fp, K)`` — the cross-campaign case the warm-start
exists for (N campaigns of one scenario) shares all three, while an
unrelated experiment can never pollute another's training set.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from namazu_tpu import obs
from namazu_tpu.knowledge.client import WIRE_VERSION
from namazu_tpu.models.failure_pool import (
    entry_from_jsonable,
    entry_to_jsonable,
    pool_load,
    pool_put,
    pool_size,
)
from namazu_tpu.utils.atomic import atomic_write_json
from namazu_tpu.utils.log import get_logger

log = get_logger("knowledge.service")

#: labeled examples retained per surrogate store (digest-keyed LRU, so
#: re-pushes of the same interleaving refresh instead of duplicate)
MAX_EXAMPLES = 2048

#: minimum labeled examples PER CLASS before the shared surrogate
#: trains/serves — same rationale as ScheduleSearch.MIN_CLASS_EXAMPLES
MIN_CLASS_EXAMPLES = 3


def _surrogate_or_none(K: int):
    """Build a RewardSurrogate, or None when the learning stack (jax/
    flax/optax) is absent — the service then serves ``trained: false``
    and tenants fall back to their local fitness argmax."""
    try:
        from namazu_tpu.models.surrogate import RewardSurrogate

        return RewardSurrogate(K=K, seed=0)
    except Exception:
        log.warning("shared surrogate unavailable (learning stack not "
                    "importable); serving predictions disabled",
                    exc_info=True)
        return None


class _SurrogateStore:
    """One scenario+feature-space's labeled examples + online model.

    Example mutations happen under the service's global lock; the
    expensive parts — model fit (jax compile + epochs) and the npz
    persist — run OUTSIDE it on a snapshot, serialized per store by
    ``train_lock``, so a training round never stalls other tenants'
    pulls (or blows the pushing client's timeout into a phantom
    outage)."""

    def __init__(self, K: int):
        self.K = K
        # digest -> (feats f32[K], label); ordered for LRU eviction
        self.examples: "OrderedDict[str, Tuple[np.ndarray, float]]" = \
            OrderedDict()
        self.model = None
        self.model_failed = False  # learning stack absent: don't retry
        self.train_rounds = 0
        self.dirty = False  # examples added since the last train
        self.train_lock = threading.Lock()

    def add(self, digest: str, feats: np.ndarray, label: float) -> None:
        if digest in self.examples:
            del self.examples[digest]  # refresh LRU position + label
        self.examples[digest] = (feats, label)
        while len(self.examples) > MAX_EXAMPLES:
            self.examples.popitem(last=False)
        self.dirty = True

    def dataset(self) -> Tuple[np.ndarray, np.ndarray]:
        feats = np.stack([f for f, _ in self.examples.values()]) \
            if self.examples else np.zeros((0, self.K), np.float32)
        labels = np.asarray([l for _, l in self.examples.values()],
                            np.float32)
        return feats, labels

    def trainable(self) -> bool:
        labels = np.asarray([l for _, l in self.examples.values()])
        pos = int((labels > 0.5).sum())
        return min(pos, len(labels) - pos) >= MIN_CLASS_EXAMPLES

    def train_on(self, feats: np.ndarray, labels: np.ndarray) -> bool:
        """Fit one round on a snapshot — called OUTSIDE the global
        lock; returns whether a round ran."""
        with self.train_lock:
            if self.model_failed:
                return False
            if self.model is None:
                self.model = _surrogate_or_none(self.K)
                if self.model is None:
                    self.model_failed = True
                    return False
            self.model.train(feats, labels, epochs=2,
                             seed=self.train_rounds)
            self.train_rounds += 1
        obs.knowledge_surrogate_round()
        return True


class KnowledgeService:
    """Handler for the knowledge wire ops (hosted by the sidecar).

    Thread-safe: the sidecar serves each connection from its own thread
    and tenants push/pull concurrently; one lock serializes state
    mutations (none of these ops are on an event hot path)."""

    # v2: pool_push/pool_pull carry relation-coverage signatures
    # (guidance plane, doc/search.md) — a per-(scenario, space)
    # covered-bit set pooled by union, served back to warm-start a
    # cold campaign's coverage frontier. v3: the triage plane's
    # dossier ops (triage_push/triage_pull) — one minimized-reproducer
    # dossier per failure signature, so every tenant that hits a
    # signature pulls the minimization another tenant already paid
    # for. Older peers simply omit/refuse the newer ops; nothing
    # about the framing changed. The version constant is
    # single-sourced in knowledge/client.py so the frames the client
    # stamps can never disagree with what the service declares.
    VERSION = WIRE_VERSION
    OPS = ("pool_push", "pool_pull", "surrogate_predict", "stats",
           "triage_push", "triage_pull")

    def __init__(self, pool_dir: str, state_dir: str = ""):
        if not pool_dir:
            raise ValueError("KnowledgeService needs a pool directory")
        self.pool_dir = os.path.abspath(pool_dir)
        # state lives in a subdir by default: scenario/surrogate .npz
        # state must never be mistaken for pool entries by pool_size/
        # pool_load/fsck, which treat every <pool>/*.npz as a signature
        self.state_dir = os.path.abspath(
            state_dir or os.path.join(self.pool_dir, "_state"))
        os.makedirs(self.pool_dir, exist_ok=True)
        os.makedirs(self.state_dir, exist_ok=True)
        self._lock = threading.Lock()
        # fan-in instrumentation: how many ops are in flight and how
        # long they wait for the state lock (nmz_knowledge_fanin_*) —
        # the serialization N orchestrators' end-of-run pushes would
        # otherwise hide until it surfaces as client timeouts
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # tenant -> {"first_seen", "last_seen", "pushes", "pulls"}
        self._tenants: Dict[str, Dict[str, Any]] = {}
        # scenario fingerprint -> {"delays", "fitness", "H", "updated_at"}
        self._scenarios: Dict[str, Dict[str, Any]] = {}
        # "scenario@HxWxWIN" -> {"scenario", "H", "w", "win",
        # "bits": set[int]} — the fleet's pooled relation coverage
        # (guidance plane). Bits are only comparable within one
        # (H, width, window) space, so the store is keyed by scenario
        # AND space: mixed-width campaigns of one scenario accumulate
        # side by side instead of wiping each other, and a pull is an
        # exact-key lookup.
        self._coverage: Dict[str, Dict[str, Any]] = {}
        # (scenario, pairs_fp, K) -> _SurrogateStore
        self._surrogates: Dict[Tuple[str, str, int], _SurrogateStore] = {}
        # failure signature -> dossier doc (triage plane, wire v3):
        # one minimized reproducer per signature, replaced only by a
        # strictly smaller (fewer-flip) validated dossier
        self._triage: Dict[str, Dict[str, Any]] = {}
        self._pushes = 0
        self._pulls = 0
        self._dedupe_hits = 0
        self._triage_pulls = 0
        self._triage_hits = 0
        self._load_state()
        # fleet telemetry (doc/observability.md "Fleet telemetry"): the
        # tenant/pool gauges normally refresh per request — a relay
        # collector keeps them fresh across idle stretches too, so the
        # sidecar's fleet row never pushes week-old occupancy
        from namazu_tpu.obs import federation

        federation.register_collector(self._refresh_gauges)

    def _refresh_gauges(self) -> None:
        obs.knowledge_service_stats(len(self._tenants),
                                    pool_size(self.pool_dir))

    def close(self) -> None:
        """Detach from the telemetry relay (a dead service must not
        keep scanning its pool dir on every push cycle, nor shadow a
        replacement's gauges)."""
        from namazu_tpu.obs import federation

        federation.unregister_collector(self._refresh_gauges)

    # -- persistence (crash-safe; a restarted service resumes) -----------

    def _scenario_path(self) -> str:
        return os.path.join(self.state_dir, "scenarios.json")

    def _coverage_path(self) -> str:
        return os.path.join(self.state_dir, "coverage.json")

    def _triage_path(self) -> str:
        return os.path.join(self.state_dir, "triage.json")

    def _store_path(self, key: Tuple[str, str, int]) -> str:
        sid = hashlib.sha256(
            f"{key[0]}|{key[1]}|{key[2]}".encode()).hexdigest()[:16]
        return os.path.join(self.state_dir, f"surrogate_{sid}.npz")

    def _load_state(self) -> None:
        import json

        try:
            with open(self._scenario_path()) as f:
                self._scenarios = json.load(f)
        except FileNotFoundError:
            pass
        except Exception:
            log.exception("scenario table state unreadable; starting "
                          "with an empty table set")
        try:
            with open(self._coverage_path()) as f:
                loaded = json.load(f)
            self._coverage = {
                key: {"scenario": str(c.get("scenario", key)),
                      "H": int(c["H"]), "w": int(c["w"]),
                      "win": int(c.get("win", 0)),
                      "bits": {int(b) for b in c.get("bits", [])}}
                for key, c in loaded.items()
            }
        except FileNotFoundError:
            pass
        except Exception:
            log.exception("coverage state unreadable; starting with an "
                          "empty coverage set")
        try:
            with open(self._triage_path()) as f:
                loaded = json.load(f)
            self._triage = {str(sig): dict(d)
                            for sig, d in loaded.items()
                            if isinstance(d, dict)}
        except FileNotFoundError:
            pass
        except Exception:
            log.exception("triage dossier state unreadable; starting "
                          "with an empty dossier set")

    def _save_scenarios(self) -> None:
        try:
            atomic_write_json(self._scenario_path(), self._scenarios,
                              sort_keys=True)
        except OSError:
            log.exception("could not persist scenario tables")

    def _save_coverage(self) -> None:
        try:
            atomic_write_json(
                self._coverage_path(),
                {key: {"scenario": c["scenario"], "H": c["H"],
                       "w": c["w"], "win": c["win"],
                       "bits": sorted(c["bits"])}
                 for key, c in self._coverage.items()},
                sort_keys=True)
        except OSError:
            log.exception("could not persist pooled coverage")

    def _save_triage(self) -> None:
        try:
            atomic_write_json(self._triage_path(), self._triage,
                              sort_keys=True)
        except OSError:
            log.exception("could not persist triage dossiers")

    @staticmethod
    def _coverage_key(scenario: str, h: int, w: int, win: int) -> str:
        return f"{scenario}@{h}x{w}x{win}"

    def _save_store(self, key: Tuple[str, str, int], digests, feats,
                    labels) -> None:
        """Persist one example snapshot through utils/atomic.py (fsync +
        rename + dir fsync — the same durability as every other
        persistence site, per this module's crash-safety contract)."""
        import io

        from namazu_tpu.utils.atomic import atomic_write

        buf = io.BytesIO()
        np.savez(buf, feats=feats, labels=labels,
                 digests=np.asarray(digests),
                 scenario=np.asarray(key[0]),
                 pairs_fp=np.asarray(key[1]))
        try:
            atomic_write(self._store_path(key), buf.getvalue())
        except OSError:
            log.exception("could not persist surrogate examples")

    def _get_store(self, key: Tuple[str, str, int]) -> _SurrogateStore:
        store = self._surrogates.get(key)
        if store is not None:
            return store
        store = _SurrogateStore(K=key[2])
        try:
            with np.load(self._store_path(key)) as z:
                feats, labels = z["feats"], z["labels"]
                for d, f, l in zip(z["digests"], feats, labels):
                    store.add(str(d), np.asarray(f, np.float32), float(l))
            store.dirty = True  # retrain lazily from the recovered set
        except FileNotFoundError:
            pass
        except Exception:
            log.exception("surrogate example state unreadable; starting "
                          "empty")
        self._surrogates[key] = store
        return store

    # -- dispatch ---------------------------------------------------------

    def handle(self, req: dict) -> dict:
        op = str(req.get("op"))
        handler = {
            "pool_push": self._pool_push,
            "pool_pull": self._pool_pull,
            "surrogate_predict": self._surrogate_predict,
            "stats": self._stats,
            "triage_push": self._triage_push,
            "triage_pull": self._triage_pull,
        }.get(op)
        if handler is None:
            return {"ok": False, "v": self.VERSION,
                    "error": f"unknown knowledge op {op!r}"}
        # fan-in contract: the dispatch itself holds NO lock — each
        # handler takes the state lock only around its in-memory
        # mutations (via _locked, which also measures the wait), so one
        # tenant's pool-entry file loop or model inference never
        # serializes the other N-1 orchestrators' pushes behind it
        with self._inflight_lock:
            self._inflight += 1
            inflight = self._inflight
        obs.knowledge_fanin(inflight)
        try:
            try:
                resp = handler(req)
            except Exception as e:
                log.exception("knowledge op %s failed", op)
                resp = {"ok": False, "error": repr(e)}
            # deferred surrogate work (snapshots taken under the lock)
            # runs HERE, outside it: a jax fit + npz persist must never
            # stall other tenants' pulls behind the global lock (or
            # blow this client's timeout into a phantom outage)
            deferred = resp.pop("_deferred", ())
            trained = False
            for key, store, digests, feats, labels, want_train \
                    in deferred:
                self._save_store(key, digests, feats, labels)
                if want_train:
                    trained = store.train_on(feats, labels) or trained
            if deferred and op == "pool_push":
                resp["trained"] = trained  # settled now that the fit ran
            resp.setdefault("v", self.VERSION)
            obs.knowledge_service_stats(len(self._tenants),
                                        pool_size(self.pool_dir))
            return resp
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                inflight = self._inflight
            obs.knowledge_fanin(inflight)

    @contextlib.contextmanager
    def _locked(self):
        """The service state lock, with the wait measured into
        ``nmz_knowledge_fanin_lock_wait_seconds`` — if narrowing the
        critical sections ever regresses, the histogram says so before
        tenants' timeouts do."""
        t0 = time.monotonic()
        self._lock.acquire()
        try:
            obs.knowledge_fanin(self._inflight,
                                lock_wait_s=time.monotonic() - t0)
            yield
        finally:
            self._lock.release()

    def _touch_tenant(self, req: dict, what: str) -> str:
        tenant = str(req.get("tenant") or "anon")
        now = time.time()
        t = self._tenants.setdefault(
            tenant, {"first_seen": now, "pushes": 0, "pulls": 0})
        t["last_seen"] = now
        t[what] = t.get(what, 0) + 1
        return tenant

    # -- ops --------------------------------------------------------------

    def _pool_push(self, req: dict) -> dict:
        """Ingest failure signatures (content-keyed, exactly-once),
        optionally a scenario's best delay table, and optionally labeled
        surrogate examples. All three ride one op so a tenant's
        end-of-run push is a single round trip.

        The entry file loop (one fsync'd tmp+rename per signature) runs
        OUTSIDE the service lock: pool entries are content-keyed and
        ``pool_put`` is atomic per entry, so N orchestrators' pushes
        fan in concurrently instead of serializing behind one tenant's
        disk writes — only the in-memory table/coverage/example
        mutations take the lock."""
        scenario = str(req.get("scenario") or "")
        accepted = duplicates = rejected = 0
        for d in req.get("entries") or []:
            try:
                realized, arrival, seed, entry_h = entry_from_jsonable(d)
                _, added = pool_put(self.pool_dir, realized, arrival,
                                    seed, entry_h)
            except Exception:
                rejected += 1
                continue
            if added:
                accepted += 1
            else:
                duplicates += 1
        best = req.get("best")
        coverage = req.get("coverage")
        examples = req.get("examples") or []
        pairs_fp = str(req.get("pairs_fp") or "")
        with self._locked():
            self._touch_tenant(req, "pushes")
            self._pushes += 1
            self._dedupe_hits += duplicates
            if best and scenario:
                self._install_best(scenario, best)
            if coverage and scenario:
                self._merge_coverage(scenario, coverage)
            deferred = []
            if examples and scenario and pairs_fp:
                deferred = self._add_examples(scenario, pairs_fp,
                                              examples)
        return {"ok": True, "accepted": accepted,
                "duplicates": duplicates, "rejected": rejected,
                "trained": False,  # settled post-lock from _deferred
                "_deferred": deferred,
                "pool_size": pool_size(self.pool_dir)}

    def _install_best(self, scenario: str, best: dict) -> None:
        """Keep the highest-fitness delay table per scenario — the
        warm-start a cold campaign installs before its own history
        exists. Fitness comparisons only make sense within a scenario
        (same oracle, same weights), which is exactly the key."""
        try:
            delays = [float(x) for x in best["delays"]]
            fitness = float(best["fitness"])
            h = int(best.get("H") or len(delays))
        except (KeyError, TypeError, ValueError):
            return
        if not np.isfinite(fitness) or len(delays) != h:
            return
        cur = self._scenarios.get(scenario)
        if cur is not None and cur.get("H") == h \
                and cur.get("fitness", float("-inf")) >= fitness:
            return
        self._scenarios[scenario] = {
            "delays": delays, "fitness": fitness, "H": h,
            "updated_at": time.time(),
        }
        self._save_scenarios()

    def _merge_coverage(self, scenario: str, coverage: dict) -> None:
        """Union one campaign's relation-coverage bits into its
        (scenario, space) pooled frontier (guidance plane). A malformed
        push costs that push, never the stored state, and a push from a
        different (H, width, window) space lands in its OWN store —
        bits don't translate between spaces, and letting one space
        replace another would wipe the fleet's accumulated frontier."""
        try:
            h = int(coverage["H"])
            w = int(coverage["w"])
            win = int(coverage.get("win", 0))
            bits = {int(b) for b in coverage.get("bits", [])}
        except (KeyError, TypeError, ValueError):
            return
        if w <= 0 or any(b < 0 or b >= w for b in bits):
            return
        key = self._coverage_key(scenario, h, w, win)
        cur = self._coverage.get(key)
        if cur is not None:
            if bits <= cur["bits"]:
                return  # nothing new: skip the persist
            cur["bits"] |= bits
        else:
            self._coverage[key] = {"scenario": scenario, "H": h,
                                   "w": w, "win": win, "bits": bits}
        self._save_coverage()

    def _add_examples(self, scenario: str, pairs_fp: str,
                      examples: list) -> list:
        """Fold examples into their stores (under the global lock) and
        return the deferred persist/train snapshots for ``handle`` to
        run outside it."""
        stores_touched = set()
        for ex in examples:
            try:
                feats = np.asarray(ex["feats"], np.float32)
                label = float(ex["label"])
                digest = str(ex.get("digest") or "")
            except (KeyError, TypeError, ValueError):
                continue
            if feats.ndim != 1 or not digest:
                continue
            key = (scenario, pairs_fp, int(feats.shape[0]))
            self._get_store(key).add(digest, feats, label)
            stores_touched.add(key)
        deferred = []
        for key in stores_touched:
            store = self._surrogates[key]
            deferred.append(self._snapshot_deferred(key, store))
        return deferred

    def _snapshot_deferred(self, key: Tuple[str, str, int],
                           store: _SurrogateStore) -> Tuple:
        """Immutable (persist + maybe-train) work item, snapped under
        the global lock. ``dirty`` clears only when a train WILL run, so
        below-threshold examples keep accumulating toward one."""
        digests = list(store.examples.keys())
        feats, labels = store.dataset()
        want_train = (store.dirty and not store.model_failed
                      and store.trainable())
        if want_train:
            store.dirty = False
        return key, store, digests, feats, labels, want_train

    def _pool_pull(self, req: dict) -> dict:
        """Serve the warm-start: pooled signatures compatible with the
        tenant's bucket count (minus what it already has) plus the
        scenario's best delay table. The pool-dir scan runs outside the
        service lock (content-keyed entries never move once written);
        only the table/coverage lookups take it."""
        from namazu_tpu.models.failure_pool import MAX_LOAD

        h = int(req.get("H") or 0)
        scenario = str(req.get("scenario") or "")
        with self._locked():
            self._touch_tenant(req, "pulls")
            self._pulls += 1
            table: Optional[dict] = None
            cur = self._scenarios.get(scenario)
            if cur is not None and (h <= 0 or cur.get("H") == h):
                table = {"delays": cur["delays"],
                         "fitness": cur["fitness"], "H": cur["H"]}
            coverage: Optional[dict] = None
            space = req.get("coverage_space")
            if isinstance(space, dict):
                # v2 coverage warm-start: an exact (scenario, space)
                # key lookup — bit indices mean nothing across spaces
                try:
                    cov = self._coverage.get(self._coverage_key(
                        scenario, int(space.get("H", 0)),
                        int(space.get("w", 0)),
                        int(space.get("win", 0))))
                except (TypeError, ValueError):
                    cov = None
                if cov is not None:
                    coverage = {"H": cov["H"], "w": cov["w"],
                                "win": cov["win"],
                                "bits": sorted(cov["bits"])}
        exclude = set(req.get("exclude") or [])
        max_entries = int(req.get("max_entries", MAX_LOAD))
        entries = []
        if h > 0 and max_entries > 0:
            for e in pool_load(self.pool_dir, h, exclude=exclude,
                               max_entries=max_entries):
                try:
                    d = entry_to_jsonable(e.realized, e.arrival, e.seed, h)
                except Exception:
                    # one malformed on-disk entry (legacy format, manual
                    # edit) must cost that entry, never the whole pull —
                    # a failed pull reads as an outage to every tenant
                    log.exception("pool entry %s unserializable; skipped",
                                  e.digest)
                    continue
                d["digest"] = e.digest
                entries.append(d)
        resp = {"ok": True, "entries": entries, "scenario_table": table,
                "pool_size": pool_size(self.pool_dir)}
        if coverage is not None:
            resp["coverage"] = coverage
        return resp

    def _surrogate_predict(self, req: dict) -> dict:
        """P(reproduce) for candidate schedule feature vectors, from the
        shared model of this scenario's feature space. ``trained:
        false`` (not an error) when the space is unknown or still too
        thin — the tenant keeps its fitness argmax."""
        scenario = str(req.get("scenario") or "")
        pairs_fp = str(req.get("pairs_fp") or "")
        feats = np.asarray(req.get("feats") or [], np.float32)
        if feats.ndim != 2 or feats.shape[0] == 0:
            return {"ok": False, "error": "feats must be [N, K]"}
        key = (scenario, pairs_fp, int(feats.shape[1]))
        with self._locked():
            store = self._surrogates.get(key)
            if store is None and os.path.exists(self._store_path(key)):
                store = self._get_store(key)  # restart recovery
            if store is None:
                return {"ok": True, "trained": False}
            deferred = []
            if store.dirty:
                # a recovered (or thin-then-grown) example set retrains
                # lazily — deferred outside the lock like every fit, so
                # THIS reply says untrained (tenant keeps its argmax)
                # and the next predict is served from the fresh model
                deferred.append(self._snapshot_deferred(key, store))
            model = store.model
        if model is None:
            return {"ok": True, "trained": False, "_deferred": deferred}
        # inference runs outside the SERVICE lock (other tenants' ops
        # proceed) but under the store's train lock, never against
        # params a concurrent fit is mid-update on
        with store.train_lock:
            probs = model.predict(feats)
        return {"ok": True, "trained": True,
                "probs": [float(p) for p in probs],
                "train_rounds": store.train_rounds,
                "_deferred": deferred}

    def _triage_push(self, req: dict) -> dict:
        """Attach one minimized-reproducer dossier to its failure
        signature (triage plane, wire v3). Content-keyed like the pool:
        a re-push of the same signature only replaces the stored
        dossier when it is strictly better — validated beats
        unvalidated, then fewer minimal flips wins — so a worse late
        arrival can never clobber the fleet's best explanation."""
        dossier = req.get("dossier")
        if not isinstance(dossier, dict):
            return {"ok": False, "error": "triage_push needs a dossier"}
        sig = str(dossier.get("signature") or "")
        if not sig:
            return {"ok": False,
                    "error": "dossier has no failure signature"}
        dossier = dict(dossier, signature=sig)

        def _rank(d: dict) -> Tuple[int, float]:
            flips = d.get("minimal_flips")
            try:
                flips = float(flips)
            except (TypeError, ValueError):
                flips = float("inf")
            return (0 if d.get("validated") else 1, flips)

        with self._locked():
            self._touch_tenant(req, "pushes")
            cur = self._triage.get(sig)
            accepted = cur is None or _rank(dossier) < _rank(cur)
            if accepted:
                self._triage[sig] = dossier
                self._save_triage()
            return {"ok": True, "accepted": accepted,
                    "dossier_count": len(self._triage)}

    def _triage_pull(self, req: dict) -> dict:
        """Serve the dossier pooled for one failure signature — the
        cross-tenant payoff: a cold tenant hitting a known signature
        gets the minimized repro without paying for the replays."""
        sig = str(req.get("signature") or "")
        with self._locked():
            self._touch_tenant(req, "pulls")
            self._triage_pulls += 1
            dossier = self._triage.get(sig)
            if dossier is not None:
                self._triage_hits += 1
            return {"ok": True, "dossier": dossier,
                    "dossier_count": len(self._triage)}

    def _stats(self, req: dict) -> dict:
        """Pool/tenant occupancy for dashboards and the PR 3 analytics
        plane (obs/analytics.py folds this into its payload)."""
        with self._locked():
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        return {
            "ok": True,
            "pool_dir": self.pool_dir,
            "pool_size": pool_size(self.pool_dir),
            "tenant_count": len(self._tenants),
            "tenants": {k: dict(v) for k, v in self._tenants.items()},
            "scenario_count": len(self._scenarios),
            "scenarios": {
                fp: {"fitness": s["fitness"], "H": s["H"],
                     "updated_at": s["updated_at"]}
                for fp, s in self._scenarios.items()
            },
            "pushes": self._pushes,
            "pulls": self._pulls,
            "dedupe_hits": self._dedupe_hits,
            "triage": {
                "dossiers": len(self._triage),
                "pulls": self._triage_pulls,
                "hits": self._triage_hits,
                "signatures": sorted(self._triage),
            },
            "coverage": {
                key: {"scenario": c["scenario"], "H": c["H"],
                      "w": c["w"],
                      "covered_bits": len(c["bits"]),
                      "occupancy": round(len(c["bits"]) / c["w"], 4)
                      if c["w"] else 0.0}
                for key, c in self._coverage.items()
            },
            "surrogate": {
                "stores": len(self._surrogates),
                "examples": sum(len(s.examples)
                                for s in self._surrogates.values()),
                "train_rounds": sum(s.train_rounds
                                    for s in self._surrogates.values()),
            },
        }
