"""Global failure-knowledge plane (doc/knowledge.md).

ROADMAP item 3: the reference Namazu explores every experiment from
scratch — the exploration policy owns no cross-run state beyond what one
orchestrator process holds — and the cross-batch repro-rate floor drops
to 40% when a campaign's recording phase is unlucky (RESULTS.md). This
package federates the pieces that already exist in isolation (persistent
sidecar, content-keyed failure pools, reward surrogate) into one
multi-tenant knowledge service:

* :mod:`namazu_tpu.knowledge.service` — :class:`KnowledgeService`: the
  sidecar-hosted hub. Campaigns stream failure signatures (encoded
  traces keyed by the timing-invariant ``trace_digest``) in; the service
  maintains a global content-keyed pool (atomic crash-safe writes,
  dedupe is the filesystem itself), per-scenario best delay tables, and
  a shared :class:`RewardSurrogate` trained across tenants.
* :mod:`namazu_tpu.knowledge.client` — :class:`KnowledgeClient`: the
  campaign-side keep-alive framed-JSON client with graceful degradation:
  a knowledge outage must never fail a campaign, so every call site
  treats ``None`` as "skip, search locally" and the client re-probes the
  service after a cooldown (a restarted service recovers ingest without
  duplicate pool entries — content keying makes re-pushes no-ops).

Wire ops (versioned; served by ``nmz-tpu sidecar --pool-dir ...`` over
the same length-prefixed JSON framing as every sidecar request):
``pool_push``, ``pool_pull``, ``surrogate_predict``, ``stats``.
"""

from namazu_tpu.knowledge.client import (  # noqa: F401
    KnowledgeClient,
    shared_client,
)
from namazu_tpu.knowledge.service import KnowledgeService  # noqa: F401

KNOWLEDGE_OPS = KnowledgeService.OPS
