"""Campaign-side knowledge client: keep-alive framing, outage immunity.

The cardinal rule (doc/knowledge.md): a knowledge outage must never
fail a campaign. Every public method returns ``None`` instead of
raising when the service is unreachable, stale, or answers with an
error; call sites treat ``None`` as "skip — search locally". The first
failure logs one warning and opens a cooldown window (during which
calls return ``None`` immediately, so a dead service costs a campaign
nothing per run); after the cooldown the next call re-probes, so a
restarted service is picked up automatically — and because the pool is
content-keyed, the re-pushed backlog dedupes instead of duplicating.

Transport: one persistent length-prefixed-JSON connection (the PR 5
keep-alive pattern; the sidecar serves any number of frames per
connection since the same PR), with one transparent reconnect on a
stale socket.
"""

from __future__ import annotations

import hashlib
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from namazu_tpu import chaos, obs
from namazu_tpu.endpoint.agent import read_frame, write_frame
from namazu_tpu.models.failure_pool import (
    MAX_LOAD,
    PoolEntry,
    entries_to_pool_entries,
)
from namazu_tpu.utils.log import get_logger

log = get_logger("knowledge.client")

#: knowledge wire version, single-sourced here (the service's VERSION
#: re-exports it): v2 = v1 + the relation-coverage fields
#: (doc/knowledge.md); v3 = v2 + the triage dossier ops
#: (``triage_push``/``triage_pull``, doc/observability.md "Triage").
#: The client stamps every frame with it, so version-gating logic sees
#: what the peer actually speaks.
WIRE_VERSION = 3


def pairs_fingerprint(pairs) -> str:
    """Content fingerprint of a search's precedence-pair sample.
    Surrogate features are only comparable between searches that share
    the pair sample, so this fingerprint scopes the service-side example
    stores — campaigns of one scenario converge on the same pairs (same
    occupied buckets, K, H, seed) and pool; anything else is walled
    off."""
    a = np.ascontiguousarray(np.asarray(pairs))
    h = hashlib.sha256()
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()[:16]


class KnowledgeClient:
    #: seconds an outage silences the client before the next re-probe
    COOLDOWN_S = 30.0

    def __init__(self, addr: str, tenant: str = "", scenario: str = "",
                 timeout: float = 15.0,
                 cooldown_s: float = COOLDOWN_S) -> None:
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        self.addr = addr
        self.tenant = tenant or "anon"
        self.scenario = scenario
        self.timeout = timeout
        self.cooldown_s = cooldown_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._down_until = 0.0
        self._warned = False

    # -- transport --------------------------------------------------------

    def _connect(self) -> socket.socket:
        s = socket.create_connection((self._host, self._port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_sock()

    def _roundtrip(self, req: dict) -> dict:
        """One framed request/response on the persistent connection.

        Failure classes are deliberately distinct (doc/robustness.md):

        * **connection-level** — reset / EOF mid-reply / torn frame on
          an established socket. The usual cause is a service that
          restarted between runs (our keep-alive socket went stale) or
          dropped this one connection; the service itself is fine, so
          the request gets ONE immediate transparent retry on a fresh
          socket instead of burning a 30 s outage cooldown.
        * **availability-level** — connect refused (``_connect``
          raises, never reaches the retry) or a timeout (the service is
          up but hung; re-asking a fresh socket would just double the
          stall): these propagate at once and the caller opens the
          cooldown.

        Caller holds the lock."""
        for attempt in (0, 1):
            if self._sock is None:
                self._sock = self._connect()
            try:
                write_frame(self._sock, req)
                # chaos seam: the service dies mid-reply (framed EOF)
                if chaos.decide("knowledge.eof") is not None:
                    self._close_sock()
                    raise ConnectionResetError("chaos: mid-stream EOF")
                resp = read_frame(self._sock)
                if resp is None:
                    raise ConnectionError("connection closed mid-reply")
                return resp
            except (socket.timeout, TimeoutError) as e:
                self._close_sock()
                raise ConnectionError(f"timeout: {e}") from e
            except (OSError, ValueError) as e:
                self._close_sock()
                if attempt:
                    raise ConnectionError(str(e)) from e
        raise AssertionError("unreachable")

    def _request(self, req: dict) -> Optional[dict]:
        """Send one knowledge op; ``None`` = degraded (outage or the
        service refused the op). Never raises."""
        req = dict(req, v=WIRE_VERSION, tenant=self.tenant,
                   scenario=req.get("scenario", self.scenario))
        if obs.metrics.enabled():
            # causality plane (obs/context.py): stamp the op frame so
            # the service's clock merges ours (the framed server echoes
            # a stamp back, merged below) — knowledge traffic is part
            # of the cross-process happens-before story too
            req.setdefault("ctx", obs.context.wire_stamp())
        with self._lock:
            now = time.monotonic()
            if now < self._down_until:
                return None
            # chaos seam: a hard outage (as if the port were closed)
            if chaos.decide("knowledge.outage") is not None:
                self._mark_outage("chaos: injected outage")
                return None
            try:
                resp = self._roundtrip(req)
            except Exception as e:
                self._mark_outage(f"unreachable ({e})")
                return None
            if not resp.get("ok"):
                # an op-level refusal (unknown op on an old sidecar, no
                # --pool-dir configured) is as dead as a closed port:
                # cool down rather than re-asking every run
                self._mark_outage(resp.get("error", "request refused"))
                return None
            self._down_until = 0.0
            self._warned = False
            obs.context.observe_wire(resp.get("ctx"))
            return resp

    def _mark_outage(self, why: str) -> None:
        self._down_until = time.monotonic() + self.cooldown_s
        self._close_sock()
        obs.knowledge_outage()
        if not self._warned:
            self._warned = True
            log.warning(
                "knowledge service %s %s; degrading to local-only "
                "search (re-probing in %.0fs — an outage never fails a "
                "campaign)", self.addr, why, self.cooldown_s)
        else:
            log.debug("knowledge service %s still down: %s",
                      self.addr, why)

    def available(self) -> bool:
        """Best-effort liveness view (no wire traffic)."""
        return time.monotonic() >= self._down_until

    # -- ops --------------------------------------------------------------

    def push(self, entries: Sequence[dict] = (),
             best: Optional[dict] = None,
             examples: Sequence[dict] = (),
             pairs_fp: str = "",
             coverage: Optional[dict] = None) -> Optional[dict]:
        """Stream failure signatures / a best table / labeled surrogate
        examples / a relation-coverage signature (guidance plane, wire
        v2) to the service; returns its response or ``None`` when
        degraded."""
        if not entries and best is None and not examples \
                and coverage is None:
            return {"ok": True, "accepted": 0, "duplicates": 0}
        req: Dict = {"op": "pool_push", "entries": list(entries)}
        if best is not None:
            req["best"] = best
        if coverage is not None:
            req["coverage"] = coverage
        if examples:
            req["examples"] = list(examples)
            req["pairs_fp"] = pairs_fp
        resp = self._request(req)
        obs.knowledge_push(resp is not None,
                           accepted=(resp or {}).get("accepted", 0),
                           duplicates=(resp or {}).get("duplicates", 0))
        return resp

    def pull(self, H: int, exclude: Sequence[str] = (),
             max_entries: int = MAX_LOAD,
             coverage_space: Optional[dict] = None
             ) -> Optional[Tuple]:
        """Warm-start material: ``(pool entries, scenario table)`` —
        ``None`` when degraded (distinct from ``([], None)``, a healthy
        but empty service). With ``coverage_space`` (``{"H", "w",
        "win"}``, wire v2) the SAME round trip also fetches the
        scenario's pooled relation-coverage bits and the return grows a
        third element (the bit list; ``[]`` when nothing pooled for
        that exact space or the service predates v2)."""
        req = {"op": "pool_pull", "H": int(H),
               "exclude": list(exclude),
               "max_entries": int(max_entries)}
        if coverage_space is not None:
            req["coverage_space"] = dict(coverage_space)
        resp = self._request(req)
        if resp is None:
            obs.knowledge_pull(False)
            return None
        entries = entries_to_pool_entries(resp.get("entries") or [], H)
        obs.knowledge_pull(True)
        table = resp.get("scenario_table")
        if table is not None:
            try:
                delays = np.asarray(table["delays"], np.float32)
                if delays.shape != (int(H),):
                    table = None
                else:
                    table = {"delays": delays,
                             "fitness": float(table["fitness"])}
            except (KeyError, TypeError, ValueError):
                table = None
        if coverage_space is None:
            return entries, table
        cov = resp.get("coverage")
        bits: List[int] = []
        if isinstance(cov, dict):
            try:
                bits = [int(b) for b in cov.get("bits", [])]
            except (TypeError, ValueError):
                bits = []
        return entries, table, bits

    def scenario_table(self, H: int) -> Optional[dict]:
        """Just the scenario's best delay table (a cheap pull with no
        entries) — the cold-run hot-path warm-start."""
        pulled = self.pull(H, max_entries=0)
        return pulled[1] if pulled is not None else None

    def pull_coverage(self, H: int, width: int,
                      window: int) -> Optional[List[int]]:
        """Just the scenario's pooled relation-coverage bits (guidance
        plane, wire v2) for EXACTLY this (H, width, window) space —
        ``None`` when degraded (outage). An empty list is a healthy
        answer: nothing pooled yet, a pre-v2 service, or a pooled
        space that differs (bit indices don't translate — there is
        nothing safe to merge either way). ``window`` is required
        because serving is an exact-space lookup: a guessable default
        (0) would silently query a space no campaign pushes to.
        Ingest piggybacks the coverage on its entry pull instead (one
        round trip)."""
        pulled = self.pull(0, max_entries=0,
                           coverage_space={"H": int(H),
                                           "w": int(width),
                                           "win": int(window)})
        return pulled[2] if pulled is not None else None

    def predict(self, feats: np.ndarray,
                pairs_fp: str = "") -> Optional[np.ndarray]:
        """Shared-surrogate P(reproduce) per candidate feature vector;
        ``None`` when degraded or the model is untrained for this
        feature space — the caller keeps its fitness argmax."""
        feats = np.asarray(feats, np.float32)
        resp = self._request({
            "op": "surrogate_predict", "pairs_fp": pairs_fp,
            "feats": [[float(x) for x in row] for row in feats],
        })
        if resp is None or not resp.get("trained"):
            return None
        probs = np.asarray(resp.get("probs") or [], np.float32)
        return probs if probs.shape == (feats.shape[0],) else None

    def triage_push(self, dossier: dict) -> Optional[dict]:
        """Attach one minimized-reproducer dossier (triage plane, wire
        v3) to its failure signature; returns the service response or
        ``None`` when degraded. Same contract as every other op: an
        outage never raises into campaign code."""
        if not isinstance(dossier, dict) \
                or not dossier.get("signature"):
            return None
        return self._request({"op": "triage_push",
                              "dossier": dossier})

    def triage_pull(self, signature: str) -> Optional[dict]:
        """Fetch the minimized-reproducer dossier pooled for one failure
        signature (triage plane, wire v3). ``None`` = degraded OR no
        dossier pooled — either way the caller minimizes locally; a
        pre-v3 service refuses the op, which reads as an outage and
        cools down like one."""
        resp = self._request({"op": "triage_pull",
                              "signature": str(signature)})
        ok = resp is not None and resp.get("dossier") is not None
        obs.triage_dossier_pull(ok)
        return resp.get("dossier") if resp is not None else None

    def stats(self) -> Optional[dict]:
        return self._request({"op": "stats"})


# -- per-process shared clients ------------------------------------------

_clients: Dict[Tuple[str, str, str], KnowledgeClient] = {}
_clients_lock = threading.Lock()


def shared_client(addr: str, tenant: str = "",
                  scenario: str = "") -> KnowledgeClient:
    """One client per (addr, tenant, scenario) per process, so the
    policy, ingest, and the surrogate hook share a connection AND an
    outage cooldown — a dead service is probed once, not once per
    subsystem."""
    key = (addr, tenant or "anon", scenario)
    with _clients_lock:
        client = _clients.get(key)
        if client is None:
            client = _clients[key] = KnowledgeClient(
                addr, tenant=key[1], scenario=scenario)
        return client
