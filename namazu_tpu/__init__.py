"""namazu_tpu: a TPU-native programmable fuzzy scheduler for distributed systems.

A ground-up rebuild of the capabilities of Namazu (osrg/namazu, mirrored at
mukteshkrmishra/namazu): intercept nondeterministic events of a real
distributed system (packets, filesystem ops, process scheduling, in-process
function calls), defer them through a central orchestrator, and release them
in adversarial orders — with fault injection — to amplify the reproduction
probability of race conditions and flaky tests.

Two planes:

* **Control plane** (this package's ``signal``, ``orchestrator``, ``endpoint``,
  ``inspector``, ``storage``, ``cli`` modules): host-side, pure Python +
  C++ guest agents. Equivalent in capability to the reference's Go runtime
  (reference layer map: SURVEY.md section 1).
* **Search plane** (``ops``, ``models``, ``parallel`` modules): JAX/TPU.
  Event traces become schedule genomes (delay tables + permutation
  priorities); millions of candidate interleavings are scored in parallel
  (vmap + Pallas), and an island-model GA over a device mesh streams the
  best schedules back for real replay. This plane has no reference
  counterpart — it replaces the reference's random timer races
  (nmz/util/queue/impl.go) with a learned, massively parallel search.
"""

__version__ = "0.1.0"
