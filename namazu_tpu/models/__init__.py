"""Search-plane models: the genetic algorithm over schedule genomes and the
learned reward surrogate."""
