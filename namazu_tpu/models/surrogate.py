"""Learned reward surrogate: predict bug-reproduction probability from
schedule features.

The experiment oracle (validate script) is binary and costs a whole
wall-clock run (SURVEY.md section 7, "reward sparsity"). This small flax
MLP is trained online on (features, reproduced?) pairs from executed runs
and provides a dense score used to re-rank GA elites before paying for
real replays — the "learned surrogate" of BASELINE.json config 5.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn


class SurrogateMLP(nn.Module):
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden // 2)(x)
        x = nn.relu(x)
        return nn.Dense(1)(x)[..., 0]  # logits


class SurrogateState(NamedTuple):
    params: dict
    opt_state: optax.OptState
    steps: jax.Array


class RewardSurrogate:
    def __init__(self, K: int, hidden: int = 128, lr: float = 1e-3,
                 seed: int = 0):
        self.model = SurrogateMLP(hidden=hidden)
        self.tx = optax.adam(lr)
        params = self.model.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, K), jnp.float32)
        )
        self.state = SurrogateState(
            params=params,
            opt_state=self.tx.init(params),
            steps=jnp.zeros((), jnp.int32),
        )

        def loss_fn(params, feats, labels, weight):
            logits = self.model.apply(params, feats)
            per = optax.sigmoid_binary_cross_entropy(logits, labels)
            # weighted mean over the REAL rows only: partial batches are
            # padded to a fixed shape with zero-weight rows, so the loss
            # (and gradient) equals the unpadded mean while every batch
            # hits one compiled specialization
            return (per * weight).sum() / jnp.maximum(weight.sum(), 1.0)

        @jax.jit
        def train_step(state: SurrogateState, feats, labels, weight):
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, feats, labels, weight
            )
            updates, opt_state = self.tx.update(grads, state.opt_state,
                                                state.params)
            params = optax.apply_updates(state.params, updates)
            return SurrogateState(params, opt_state, state.steps + 1), loss

        @jax.jit
        def predict_fn(state: SurrogateState, feats):
            return jax.nn.sigmoid(self.model.apply(state.params, feats))

        self._train_step = train_step
        self._predict = predict_fn

    def train(self, feats: np.ndarray, labels: np.ndarray,
              epochs: int = 1, batch: int = 256,
              seed: int = 0) -> float:
        """Train on (feats [N,K], labels [N] in {0,1}); returns last loss.

        Every minibatch is padded to the fixed ``batch`` shape with
        zero-WEIGHT rows (the weighted loss ignores them exactly), so
        the jitted train step compiles ONCE per feature width no matter
        how the archive's occupancy grows between rounds — pre-padding,
        each new occupancy's partial tail batch was a fresh
        trace+compile in the middle of a campaign (compile-count and
        padded-vs-exact equality pinned by tests/test_fused_loop.py)."""
        n = len(feats)
        K = feats.shape[1]
        rng = np.random.RandomState(seed)
        loss = 0.0
        for _ in range(epochs):
            order = rng.permutation(n)
            for i in range(0, n, batch):
                idx = order[i : i + batch]
                nb = len(idx)
                f = np.zeros((batch, K), np.float32)
                f[:nb] = feats[idx]
                lb = np.zeros((batch,), np.float32)
                lb[:nb] = labels[idx]
                w = np.zeros((batch,), np.float32)
                w[:nb] = 1.0
                self.state, l = self._train_step(
                    self.state,
                    jnp.asarray(f),
                    jnp.asarray(lb),
                    jnp.asarray(w),
                )
                loss = float(l)
        return loss

    def predict(self, feats: np.ndarray) -> np.ndarray:
        """P(reproduce bug) per feature vector."""
        return np.asarray(self._predict(self.state, jnp.asarray(feats)))

    def rerank(self, feats: np.ndarray,
               top: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Indices (desc) + probabilities; used to pick which GA elites get
        real wall-clock replays."""
        p = self.predict(feats)
        order = np.argsort(-p)
        if top is not None:
            order = order[:top]
        return order, p[order]
