"""ScheduleSearch: the host-side driver around the sharded island GA.

Owns the novelty/failure archives (host ring buffers mirrored to device),
runs generations on the mesh, and extracts the best delay/fault tables for
the control plane to replay. Checkpointing is plain ``.npz`` (population,
archives, RNG state) — search state survives across experiment runs, which
the reference has no equivalent for (SURVEY.md section 5.4).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import numpy as np

from namazu_tpu.models.ga import GAConfig
from namazu_tpu.ops import trace_encoding as te
from namazu_tpu.ops.schedule import ScoreWeights


class SearchConfig(NamedTuple):
    H: int = te.DEFAULT_H  # hint buckets (genome length)
    L: int = te.DEFAULT_L  # max trace length
    K: int = te.DEFAULT_K  # feature pairs
    archive_size: int = 512  # novelty archive capacity
    failure_size: int = 64  # failure archive capacity
    population: int = 4096  # total genomes across all islands
    migrate_k: int = 8
    seed: int = 0
    ga: GAConfig = GAConfig()
    weights: ScoreWeights = ScoreWeights()


class BestSchedule(NamedTuple):
    delays: np.ndarray  # f32[H] seconds per hint bucket
    faults: np.ndarray  # f32[H] fault probability per hint bucket
    fitness: float


class SearchBase:
    """Shared host-side state of every search backend: the precedence-pair
    sample, the novelty/failure feature archives (ring buffers), and the
    backend-tagged ``.npz`` checkpoint format."""

    BACKEND = "base"

    def __init__(self, cfg: SearchConfig):
        self.cfg = cfg
        self.pairs = te.sample_pairs(cfg.K, cfg.H, cfg.seed)
        # neutral (0.5) features = "no information"; rings overwrite oldest
        self.archive = np.full((cfg.archive_size, cfg.K), 0.5, np.float32)
        self._archive_n = 0
        self.failures = np.full((cfg.failure_size, cfg.K), 0.5, np.float32)
        self._failure_n = 0
        self.generations_run = 0

    def _feats_of(self, encoded: te.EncodedTrace) -> np.ndarray:
        import jax.numpy as jnp

        from namazu_tpu.ops.schedule import TraceArrays, trace_features

        trace = TraceArrays(
            jnp.asarray(encoded.hint_ids),
            jnp.asarray(encoded.arrival),
            jnp.asarray(encoded.mask),
        )
        f = trace_features(trace, jnp.asarray(self.pairs),
                           self.cfg.weights.tau, self.cfg.H)
        return np.asarray(f)

    def add_executed_trace(self, encoded: te.EncodedTrace) -> None:
        """Record an executed run's interleaving into the novelty archive."""
        self.archive[self._archive_n % self.cfg.archive_size] = (
            self._feats_of(encoded)
        )
        self._archive_n += 1

    def add_failure_trace(self, encoded: te.EncodedTrace) -> None:
        """Record a bug-reproducing run — the bug-affinity target."""
        self.failures[self._failure_n % self.cfg.failure_size] = (
            self._feats_of(encoded)
        )
        self._failure_n += 1

    def _device_inputs(self, encoded):
        """(traces, pairs, archive, failures) as device arrays, from one
        encoded trace or a list of them."""
        import jax.numpy as jnp

        from namazu_tpu.ops.schedule import TraceArrays

        encs = encoded if isinstance(encoded, (list, tuple)) else [encoded]
        h, _, a, m = te.stack_traces(encs)
        trace = TraceArrays(jnp.asarray(h), jnp.asarray(a), jnp.asarray(m))
        return encs, trace, jnp.asarray(self.pairs), \
            jnp.asarray(self.archive), jnp.asarray(self.failures)

    # -- persistence -----------------------------------------------------

    def _state_dict(self) -> dict:
        raise NotImplementedError

    def _restore_state(self, z) -> None:
        raise NotImplementedError

    def save(self, path: str) -> None:
        import jax

        flat = {
            "backend": np.asarray(self.BACKEND),
            "archive": self.archive,
            "archive_n": np.asarray(self._archive_n),
            "failures": self.failures,
            "failure_n": np.asarray(self._failure_n),
            "key": np.asarray(jax.random.key_data(self._key)),
            "generations_run": np.asarray(self.generations_run),
        }
        flat.update(self._state_dict())
        tmp = path + ".tmp.npz"
        np.savez(tmp, **flat)
        os.replace(tmp, path)

    def load(self, path: str) -> None:
        import jax
        import jax.numpy as jnp

        with np.load(path) as z:
            # pre-backend-tag checkpoints (GA only) have no "backend" key
            saved = str(z["backend"]) if "backend" in z else "ga"
            if saved != self.BACKEND:
                raise ValueError(
                    f"checkpoint {path} was written by the {saved!r} "
                    f"backend, not {self.BACKEND!r}"
                )
            self.archive = z["archive"]
            self._archive_n = int(z["archive_n"])
            self.failures = z["failures"]
            self._failure_n = int(z["failure_n"])
            self._key = jax.random.wrap_key_data(jnp.asarray(z["key"]))
            self.generations_run = int(z["generations_run"])
            self._restore_state(z)


class ScheduleSearch(SearchBase):
    BACKEND = "ga"

    def __init__(self, cfg: SearchConfig = SearchConfig(),
                 mesh=None, n_devices: Optional[int] = None):
        import jax

        from namazu_tpu.parallel.islands import (
            init_island_state,
            make_island_step,
        )
        from namazu_tpu.parallel.mesh import make_mesh

        super().__init__(cfg)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        n_islands = 1
        for s in self.mesh.shape.values():
            n_islands *= s
        # population must divide evenly across islands
        per_island = max(1, cfg.population // n_islands)
        self.population = per_island * n_islands

        self._key = jax.random.PRNGKey(cfg.seed)
        if "h" in self.mesh.axis_names:
            # hybrid host x chip mesh -> hierarchical ICI/DCN migration
            from namazu_tpu.parallel.distributed import make_hier_island_step

            self._step = make_hier_island_step(
                self.mesh, cfg.ga, cfg.weights, migrate_k=cfg.migrate_k
            )
        else:
            self._step = make_island_step(
                self.mesh, cfg.ga, cfg.weights, migrate_k=cfg.migrate_k
            )
        self._state = init_island_state(
            jax.random.PRNGKey(cfg.seed + 1), self.population, cfg.H, cfg.ga
        )

    # -- search ----------------------------------------------------------

    def run(self, encoded, generations: int = 50) -> BestSchedule:
        """Evolve against one or more reference traces for N generations;
        returns the best schedule seen so far (monotonic across calls)."""
        _encs, trace, pairs, archive, failures = self._device_inputs(encoded)
        state = self._state
        for _ in range(generations):
            state = self._step(state, self._key, trace, pairs, archive,
                               failures)
        state.best_fitness.block_until_ready()
        self._state = state
        self.generations_run += generations
        return self.best()

    def best(self) -> BestSchedule:
        return BestSchedule(
            delays=np.asarray(self._state.best_delays),
            faults=np.asarray(self._state.best_faults),
            fitness=float(self._state.best_fitness),
        )

    # -- persistence -----------------------------------------------------

    def _state_dict(self) -> dict:
        return {
            "pop_delays": np.asarray(self._state.pop.delays),
            "pop_faults": np.asarray(self._state.pop.faults),
            "gen": np.asarray(self._state.gen),
            "best_fitness": np.asarray(self._state.best_fitness),
            "best_delays": np.asarray(self._state.best_delays),
            "best_faults": np.asarray(self._state.best_faults),
        }

    def _restore_state(self, z) -> None:
        import jax.numpy as jnp

        from namazu_tpu.parallel.islands import IslandState
        from namazu_tpu.models.ga import Population

        self._state = IslandState(
            pop=Population(
                delays=jnp.asarray(z["pop_delays"]),
                faults=jnp.asarray(z["pop_faults"]),
            ),
            gen=jnp.asarray(z["gen"]),
            best_fitness=jnp.asarray(z["best_fitness"]),
            best_delays=jnp.asarray(z["best_delays"]),
            best_faults=jnp.asarray(z["best_faults"]),
        )


class MCTSSearch(SearchBase):
    """Config-5 backend: root-parallel MCTS (models/mcts.py) behind the
    same driver API as :class:`ScheduleSearch`, so ``policy/tpu.py`` can
    swap backends with one config key (``search_backend = "mcts"``)."""

    BACKEND = "mcts"

    def __init__(self, cfg: SearchConfig = SearchConfig(), mcts_cfg=None,
                 mesh=None, n_devices: Optional[int] = None):
        import jax

        from namazu_tpu.models.mcts import MCTSConfig, make_parallel_mcts
        from namazu_tpu.parallel.mesh import make_mesh

        super().__init__(cfg)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self.mcts_cfg = mcts_cfg if mcts_cfg is not None else MCTSConfig(
            max_delay=cfg.ga.max_delay, max_fault=cfg.ga.max_fault
        )
        if self.mcts_cfg.tree_depth > cfg.H:
            # the tree cannot decide more buckets than the genome has
            self.mcts_cfg = self.mcts_cfg._replace(tree_depth=cfg.H)
        self._run = make_parallel_mcts(self.mesh, cfg.H, self.mcts_cfg,
                                       cfg.weights)
        self._key = jax.random.PRNGKey(cfg.seed)
        self._best_fitness = float("-inf")
        self._best_delays = np.zeros((cfg.H,), np.float32)
        self._best_faults = np.zeros((cfg.H,), np.float32)

    def _hint_order(self, encs) -> np.ndarray:
        """Bucket ids ordered by frequency across the reference traces —
        the tree decides the most-often-hit buckets first."""
        counts = np.zeros((self.cfg.H,), np.int64)
        for e in encs:
            counts += np.bincount(e.hint_ids[e.mask],
                                  minlength=self.cfg.H)
        return np.argsort(-counts)[: self.mcts_cfg.tree_depth].astype(
            np.int32
        )

    def run(self, encoded, generations: int = 1) -> BestSchedule:
        """Run ``max(1, generations // 64)`` independent tree searches of
        ``mcts_cfg.simulations`` expansions each (the GA's ``generations``
        knob maps onto simulation budget so configs stay comparable);
        returns the best schedule seen so far (monotonic across calls)."""
        import jax
        import jax.numpy as jnp

        encs, trace, pairs, archive, failures = self._device_inputs(encoded)
        hint_order = jnp.asarray(self._hint_order(encs))

        searches = max(1, generations // 64)
        for _ in range(searches):
            self._key, sub = jax.random.split(self._key)
            fit, d, f = self._run(sub, trace, pairs, archive, failures,
                                  hint_order)
            fit = float(fit)
            if fit > self._best_fitness:
                self._best_fitness = fit
                self._best_delays = np.asarray(d)
                self._best_faults = np.asarray(f)
        self.generations_run += searches * self.mcts_cfg.simulations
        return self.best()

    def best(self) -> BestSchedule:
        return BestSchedule(
            delays=self._best_delays,
            faults=self._best_faults,
            fitness=self._best_fitness,
        )

    # -- persistence -----------------------------------------------------

    def _state_dict(self) -> dict:
        return {
            "best_fitness": np.asarray(self._best_fitness, np.float32),
            "best_delays": self._best_delays,
            "best_faults": self._best_faults,
        }

    def _restore_state(self, z) -> None:
        self._best_fitness = float(z["best_fitness"])
        self._best_delays = z["best_delays"]
        self._best_faults = z["best_faults"]
