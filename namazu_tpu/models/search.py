"""ScheduleSearch: the host-side driver around the sharded island GA.

Owns the novelty/failure archives (host ring buffers mirrored to device),
runs generations on the mesh, and extracts the best delay/fault tables for
the control plane to replay. Checkpointing is plain ``.npz`` (population,
archives, RNG state) — search state survives across experiment runs, which
the reference has no equivalent for (SURVEY.md section 5.4).
"""

from __future__ import annotations

import os
import time
from typing import NamedTuple, Optional

import numpy as np

from namazu_tpu import obs
from namazu_tpu.models.ga import GAConfig
from namazu_tpu.ops import trace_encoding as te
from namazu_tpu.ops.schedule import ScoreWeights
from namazu_tpu.utils.log import get_logger

log = get_logger("models.search")


class SearchConfig(NamedTuple):
    H: int = te.DEFAULT_H  # hint buckets (genome length)
    L: int = te.DEFAULT_L  # encode-length cap hint; 0 = uncapped (the
    # driver encodes before calling run(), so this field is informational)
    K: int = te.DEFAULT_K  # feature pairs
    archive_size: int = 512  # novelty archive capacity
    failure_size: int = 64  # failure archive capacity
    population: int = 4096  # total genomes across all islands
    migrate_k: int = 8
    seed: int = 0
    ga: GAConfig = GAConfig()
    weights: ScoreWeights = ScoreWeights()
    # learned surrogate (BASELINE config 5): when > 0, an online MLP
    # P(reproduce | features) trained on executed runs re-ranks the top-k
    # genomes of the evolved population, and run() returns the candidate
    # with the highest predicted repro instead of the raw fitness argmax.
    # 0 disables (fitness argmax, the pre-surrogate behavior).
    surrogate_topk: int = 0
    # novelty anneal (GA backend): with fewer than this many DISTINCT
    # failure signatures in the archive the search keeps its full
    # configured novelty weight (keep exploring — exploiting 1-2
    # signatures overfits their noise, the round-4 A/B floor's root
    # cause); once the archive holds >= this many, the novelty weight is
    # scaled by min_failure_signatures / n_signatures (never below
    # novelty_floor) so a rich archive shifts the search toward
    # exploitation. 0 disables (static weights).
    min_failure_signatures: int = 0
    novelty_floor: float = 0.25
    # causality guidance (doc/search.md): weight of the predicted
    # relation-coverage gain in the final candidate pick, added on top
    # of the surrogate probability (or the normalized fitness when no
    # surrogate has trained). Only consulted once a CoverageMap is
    # wired via enable_guidance(); with none wired the search is
    # bit-identical to pre-guidance behavior.
    guidance_bonus: float = 0.5
    # fused search loop (doc/performance.md "Fused search loop"): run
    # the whole generation loop device-side — lax.scan over fused_chunk
    # generations per dispatch with the island state DONATED, traces
    # and archives device-resident across run() calls, host I/O
    # double-buffered against the next chunk's compute. Bit-exact with
    # the per-generation path by construction (same key fold order;
    # pinned by tests/test_fused_loop.py), so this is purely a
    # dispatch-shape choice. False = the pre-fusion per-generation loop.
    fused: bool = True
    fused_chunk: int = 16  # generations per fused dispatch
    # migration cadence, decoupled from the generation count: the ICI
    # ring permutes every migrate_every generations, a hybrid mesh's
    # DCN ring every dcn_migrate_every (1 = the pre-cadence behavior)
    migrate_every: int = 1
    dcn_migrate_every: int = 1
    # device-trace capture knob (doc/observability.md "Profiling"):
    # when non-empty, the FIRST fused run() of this search records a
    # jax.profiler device trace of its evolve section into
    # <device_trace_dir>/device_trace (open in perfetto / xprof) —
    # one capture per search, not continuous, so the dump cost never
    # taxes the loop it measures. The host-vs-device split stays in
    # nmz_search_phase_seconds; the trace is the per-op zoom-in.
    # "" disables (the default).
    device_trace_dir: str = ""


class BestSchedule(NamedTuple):
    delays: np.ndarray  # f32[H] seconds per hint bucket
    faults: np.ndarray  # f32[H] fault probability per hint bucket
    fitness: float


# -- device-resident buffers (fused search loop) ---------------------------

_row_update_jit = None


def _device_row_update(buf, row, slot: int):
    """Write one row of a device-resident 2-D buffer in place:
    ``dynamic_update_slice`` with the buffer DONATED, so a ring-slot
    overwrite costs one [K]- or [L]-row upload instead of re-staging the
    whole buffer next run. ``slot`` is traced — every occupancy hits the
    same compiled update. One jit serves all buffers (cache keys on
    shape/dtype)."""
    global _row_update_jit
    import jax
    import jax.numpy as jnp

    if _row_update_jit is None:
        def f(b, r, s):
            return jax.lax.dynamic_update_slice(b, r[None], (s, 0))

        _row_update_jit = jax.jit(f, donate_argnums=(0,))
    return _row_update_jit(buf, jnp.asarray(row),
                           jnp.asarray(slot, jnp.int32))


class _ResidentTraces:
    """Device-resident encoded-trace rows for the campaign's lifetime.

    The policy's ingest re-encodes a sliding window of recent reference
    traces every search request; pre-fusion, every request re-uploaded
    the whole stack. Here each distinct trace (content-keyed) is
    uploaded ONCE into a row of a fixed device buffer (appends via the
    donated ``dynamic_update_slice`` helper); a request's ordered
    [T, Lmax] view is assembled device-side by a row gather + column
    slice, so its arrays are value-identical to ``te.stack_traces`` of
    the same references (the fused-vs-unfused bit-exactness contract).
    Rows whose trace has left the reference window are evicted
    oldest-first when the buffer is full; a longer-than-resident trace
    forces a rebuild (lengths are quantized, so this converges fast).
    """

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self.slots: dict = {}  # digest -> row index
        self.order: list = []  # digests, oldest first (eviction order)
        self.bufs = None  # dict name -> device array [N, L]
        self.L = 0
        self.appends = 0  # rows uploaded incrementally (telemetry/tests)
        self.rebuilds = 0  # full re-stagings (telemetry/tests)

    @staticmethod
    def key_of(enc: "te.EncodedTrace") -> str:
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        h.update(enc.hint_ids.tobytes())
        h.update(enc.arrival.tobytes())
        h.update(enc.mask.tobytes())
        h.update(enc.faultable.tobytes())
        return h.hexdigest()

    def _pack(self, enc: "te.EncodedTrace", L: int):
        """One trace as (hint, arrival, mask, faultable) rows padded to
        L — ``te.pad_trace_row``, the host stacker's exact pad fills."""
        return te.pad_trace_row(enc, L)

    def _rebuild(self, encs, keys, Lmax: int) -> None:
        import jax.numpy as jnp

        self.capacity = max(self.capacity, len(encs))
        self.L = max(self.L, Lmax)
        host = {
            "hint": np.zeros((self.capacity, self.L), np.int32),
            "arr": np.zeros((self.capacity, self.L), np.float32),
            "mask": np.zeros((self.capacity, self.L), bool),
            "flt": np.zeros((self.capacity, self.L), bool),
        }
        self.slots = {}
        self.order = []
        for k, e in zip(keys, encs):
            if k in self.slots:
                continue
            slot = len(self.slots)
            rows = self._pack(e, self.L)
            for name in host:
                host[name][slot] = rows[name]
            self.slots[k] = slot
            self.order.append(k)
        self.bufs = {name: jnp.asarray(a) for name, a in host.items()}
        self.rebuilds += 1

    def _append(self, key: str, enc: "te.EncodedTrace", live) -> None:
        if len(self.slots) < self.capacity:
            slot = len(self.slots)
        else:
            # evict the oldest row not in the current reference window
            victim = next(k for k in self.order if k not in live)
            slot = self.slots.pop(victim)
            self.order.remove(victim)
        rows = self._pack(enc, self.L)
        for name in self.bufs:
            self.bufs[name] = _device_row_update(
                self.bufs[name], rows[name], slot)
        self.slots[key] = slot
        self.order.append(key)
        self.appends += 1

    def view(self, encs):
        """Device arrays (hint, arrival, mask, faultable), each [T, Lmax],
        for the ordered references — uploading only rows not already
        resident."""
        import jax.numpy as jnp

        keys = [self.key_of(e) for e in encs]
        Lmax = max(e.hint_ids.shape[0] for e in encs)
        live = set(keys)
        if (self.bufs is None or Lmax > self.L
                or len(live) > self.capacity):
            self._rebuild(encs, keys, Lmax)
        else:
            for k, e in zip(keys, encs):
                if k not in self.slots:
                    self._append(k, e, live)
        idx = jnp.asarray([self.slots[k] for k in keys], jnp.int32)
        return tuple(self.bufs[name][idx, :Lmax]
                     for name in ("hint", "arr", "mask", "flt"))

    def reset(self) -> None:
        self.bufs = None
        self.slots = {}
        self.order = []
        self.L = 0


def make_score_weights(
    release_mode: str = "delay",
    w_novelty: float = 1.0,
    w_bug: float = 1.0,
    w_delay_cost: float = 0.01,
    w_fault_cost: float = 0.05,
    tau: float = 0.005,
    reorder_gap: float = 0.002,
    reorder_window: float = 0.05,
) -> ScoreWeights:
    """ScoreWeights for a release mode — one home for the subtle part
    (shared by policy/tpu.py and the sidecar): scoring must model the
    same realization the control plane uses. Order mode permutes within
    reorder_window batches by the table's priorities; delay mode adds
    the table to arrivals. delay_cost=0 in order mode: uniform priority
    shifts don't change the permutation, so penalizing the table's mean
    would only drive priorities onto the 0 clip boundary (collapsing to
    arrival order via the tie-break); tau of the order of the gap keeps
    adjacent ranks' precedence features saturated."""
    if release_mode == "reorder":
        gap = max(reorder_gap, 1e-4)
        return ScoreWeights(
            novelty=w_novelty, bug=w_bug, fault_cost=w_fault_cost,
            order_mode=True, order_gap=gap,
            order_window=max(reorder_window, 0.0),
            tau=gap * 0.5, delay_cost=0.0,
        )
    return ScoreWeights(
        novelty=w_novelty, bug=w_bug, delay_cost=w_delay_cost,
        fault_cost=w_fault_cost, tau=tau,
    )


def _enable_persistent_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a stable user dir.

    Policy searches run inside short-lived ``run`` processes (SURVEY.md
    3.1 — the repro loop is many processes); without the cache every run
    re-pays the scorer's compile, which dwarfs the actual search at
    config-2 sizes. Idempotent and best-effort (older jax versions or
    read-only homes just skip it)."""
    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/namazu_tpu/xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - config name drift
        pass


class SearchBase:
    """Shared host-side state of every search backend: the precedence-pair
    sample, the novelty/failure feature archives (ring buffers), and the
    backend-tagged ``.npz`` checkpoint format."""

    BACKEND = "base"

    def __init__(self, cfg: SearchConfig):
        _enable_persistent_compile_cache()
        self.cfg = cfg
        self.pairs = te.sample_pairs(cfg.K, cfg.H, cfg.seed)
        # neutral (0.5) features = "no information"; rings overwrite oldest
        self.archive = np.full((cfg.archive_size, cfg.K), 0.5, np.float32)
        # label per archive slot: did that run reproduce the bug? (the
        # surrogate's training target; slots beyond _archive_n are unused)
        self.archive_labels = np.zeros((cfg.archive_size,), np.float32)
        self._archive_n = 0
        self.failures = np.full((cfg.failure_size, cfg.K), 0.5, np.float32)
        self._failure_n = 0
        # failure-signature dedupe: ingest re-feeds the WHOLE stored
        # history every search request, so without it the failure ring
        # fills with copies of the same 1-2 signatures and crowds out
        # older distinct ones — exactly the thin-signature regime the
        # novelty anneal and the cross-batch pool exist to escape.
        # Slot-aligned digests (evicted slot -> digest leaves the set).
        self._failure_digests = [""] * cfg.failure_size
        self._failure_digest_set: set = set()
        self.generations_run = 0
        # optional shared-surrogate hook (doc/knowledge.md): a callable
        # ``feats [N, K] -> probs [N] | None`` serving predictions from
        # the knowledge service's cross-tenant model. Consulted only
        # when the LOCAL surrogate is still too thin to train (the
        # exact cold-start window cross-campaign knowledge exists for);
        # None / a None return degrades to the fitness argmax
        self.remote_surrogate = None
        # causality guidance (doc/search.md): the per-campaign relation
        # CoverageMap, wired by enable_guidance() (policy/sidecar, only
        # when the guidance knob AND the obs plane are on). None = the
        # exact pre-guidance blind search — no extra features, no bias,
        # no bonus.
        self.guidance = None
        # per-archive-slot DAG-shape feature fragment (f32[size, G]),
        # allocated with the map: the surrogate's feature space becomes
        # [precedence K | guidance G] and the (scenario, pairs_fp, K')
        # walling keeps it from ever pooling with unguided campaigns
        self.guidance_feats = None
        # fault half of the genome is scored only when faults can be
        # non-zero; coin=None keeps the pre-config-4 jit cache entry
        self._coin = (te.fault_coin(cfg.seed, cfg.H)
                      if cfg.ga.max_fault > 0 else None)

    # -- causality guidance (doc/search.md) -------------------------------

    def enable_guidance(self, width: Optional[int] = None,
                        window: Optional[int] = None,
                        fresh: bool = False):
        """Wire the relation-coverage map (idempotent; a changed bitmap
        space rebuilds it — bit indices are only comparable within one
        (H, width, window) space). ``fresh`` rebuilds unconditionally:
        ingest passes it so the map stays a pure function of (stored
        history + fleet coverage) per ingest — a sidecar-cached search
        serving repeated requests must not double-observe the same
        history into one accumulating map. Returns the map."""
        from namazu_tpu.guidance import (
            DEFAULT_WIDTH,
            DEFAULT_WINDOW,
            GUIDANCE_DIMS,
            CoverageMap,
        )

        width = int(width or DEFAULT_WIDTH)
        window = int(window or DEFAULT_WINDOW)
        g = self.guidance
        if (g is None or fresh or g.width != width
                or g.window != window or g.H != self.cfg.H):
            self.guidance = CoverageMap(H=self.cfg.H, width=width,
                                        window=window)
        if self.guidance_feats is None:
            self.guidance_feats = np.zeros(
                (self.cfg.archive_size, GUIDANCE_DIMS), np.float32)
            # guidance wired onto a LIVE search (obs toggled on between
            # rounds): the feature space just widened, so a surrogate
            # trained at the old width and archive rows without aligned
            # fragments are both stale — same contract as the
            # checkpoint-restore width guard. The next ingest re-feeds
            # the full history with fragments attached.
            if getattr(self, "_surrogate", None) is not None:
                self._surrogate = None
            if self._archive_n > 0:
                self.archive[:] = 0.5
                self.archive_labels[:] = 0.0
                self._archive_n = 0
                self._mirror_invalidate()
        return self.guidance

    def _guidance_dims(self) -> int:
        return (0 if self.guidance_feats is None
                else self.guidance_feats.shape[1])

    def _guidance_feats_of(self, realized: te.EncodedTrace,
                           arrival: Optional[te.EncodedTrace]
                           ) -> np.ndarray:
        """DAG-shape fragment of one executed run: program order from
        the arrival view, dispatch order from the realized release
        times. Without an arrival view (legacy call sites) the realized
        view anchors both — the ordering fragment is still exact, only
        the crossing scalars degenerate to zero reordering."""
        from namazu_tpu.guidance import dag_shape_features

        src = arrival if arrival is not None else realized
        m = realized.mask
        return dag_shape_features(
            realized.hint_ids[m], src.arrival[m], realized.arrival[m],
            width=self.guidance.width, dims=self._guidance_dims())

    def set_occupied_buckets(self, occupied) -> None:
        """Refit the precedence-pair sample to the hint buckets actually
        observed in the recorded traces (``te.informative_pairs``) so the
        feature space resolves realizable precedences instead of mostly
        absent-vs-absent neutral pairs.

        When the pairs actually change, every stored feature is in the
        OLD space: the archives are cleared (the caller re-ingests the
        full history right after, ``policy/tpu.py _ingest_history``) and
        the best-so-far fitness is reset. Checkpoints persist the pairs,
        so a stable hint population across runs keeps archives and best
        intact."""
        new = te.informative_pairs(occupied, self.cfg.K, self.cfg.H,
                                   self.cfg.seed)
        if np.array_equal(new, self.pairs):
            return
        self.pairs = new
        self._mirror_invalidate()
        self.archive[:] = 0.5
        self.archive_labels[:] = 0.0
        if self.guidance_feats is not None:
            self.guidance_feats[:] = 0.0  # slot-aligned with archive
        self._archive_n = 0
        self.failures[:] = 0.5
        self._failure_n = 0
        # the caller re-ingests the full history right after, so the
        # digests must clear with the features they key
        self._failure_digests = [""] * self.cfg.failure_size
        self._failure_digest_set.clear()
        self._reset_best()

    def _reset_best(self) -> None:
        """Invalidate the best-so-far record (feature space changed)."""
        raise NotImplementedError

    def _feats_of(self, encoded: te.EncodedTrace) -> np.ndarray:
        import jax.numpy as jnp

        from namazu_tpu.ops.schedule import TraceArrays, trace_features

        trace = TraceArrays(
            jnp.asarray(encoded.hint_ids),
            jnp.asarray(encoded.arrival),
            jnp.asarray(encoded.mask),
        )
        f = trace_features(trace, jnp.asarray(self.pairs),
                           self.cfg.weights.tau, self.cfg.H)
        return np.asarray(f)

    def seed_population(self, delay_tables) -> None:
        """Inject imitation genomes before evolving; backends without an
        explicit population (MCTS builds its tree from scratch each run)
        ignore seeds."""

    def add_executed_trace(self, encoded: te.EncodedTrace,
                           reproduced: bool = False,
                           arrival: Optional[te.EncodedTrace] = None
                           ) -> None:
        """Record an executed run's interleaving into the novelty archive,
        labeled with whether it reproduced the bug (surrogate target).
        ``arrival`` (the same run's arrival-anchored view) feeds the
        guidance plane's DAG-shape features when guidance is wired."""
        slot = self._archive_n % self.cfg.archive_size
        self.archive[slot] = self._feats_of(encoded)
        self.archive_labels[slot] = 1.0 if reproduced else 0.0
        if self.guidance_feats is not None:
            self.guidance_feats[slot] = self._guidance_feats_of(
                encoded, arrival)
        self._archive_n += 1
        self._mirror_note("archive", slot, self.archive[slot])

    def add_failure_trace(self, encoded: te.EncodedTrace) -> None:
        """Record a bug-reproducing run — the bug-affinity target.
        Idempotent per distinct signature (content digest): re-ingesting
        the same stored failure never spends a ring slot."""
        from namazu_tpu.models.failure_pool import trace_digest

        digest = trace_digest(encoded)
        if digest in self._failure_digest_set:
            return
        slot = self._failure_n % self.cfg.failure_size
        evicted = self._failure_digests[slot]
        if evicted:
            self._failure_digest_set.discard(evicted)
        self.failures[slot] = self._feats_of(encoded)
        self._failure_digests[slot] = digest
        self._failure_digest_set.add(digest)
        self._failure_n += 1
        self._mirror_note("failures", slot, self.failures[slot])

    def distinct_failure_signatures(self) -> int:
        """How many distinct failure signatures the archive currently
        holds — the novelty anneal's progress variable."""
        return len(self._failure_digest_set)

    def has_failure_signature(self, digest: str) -> bool:
        """Whether a signature digest is already archived — lets ingest
        skip the whole embed/add path for known pooled entries (not just
        the ring write): without this, every search request re-embeds
        every pooled signature and stuffs duplicate reproduced=True rows
        into the novelty archive / surrogate training set."""
        return digest in self._failure_digest_set

    def _mirror_note(self, which: str, slot: int, row: np.ndarray) -> None:
        """Hook: one archive ring slot was overwritten — backends with a
        device-resident mirror (ScheduleSearch's fused loop) apply the
        same write on device via ``dynamic_update_slice`` instead of
        re-uploading the whole buffer next run. Base: no mirror."""

    def _mirror_invalidate(self) -> None:
        """Hook: a bulk archive/pairs mutation happened (checkpoint
        load, pair refit, guidance rewiring) — device mirrors must be
        rebuilt from the host arrays on the next run."""

    def _record_progress(self, generations: int, elapsed: float,
                         schedules_scored: int, best_fitness: float,
                         host_io_s: Optional[float] = None,
                         fit_curve: Optional[list] = None) -> None:
        """Publish one run()'s worth of search telemetry (obs plane):
        generations/sec, jitted-scorer schedules/s, best fitness, and the
        archive occupancies — live counterparts of bench.py's metric.
        ``host_io_s`` (fused loop) is the round's overlapped host-I/O
        lane wall time (doc/performance.md "Fused search loop")."""
        obs.search_round(
            self.BACKEND, generations, elapsed,
            schedules=schedules_scored, best_fitness=best_fitness,
            archive_entries=min(self._archive_n, self.cfg.archive_size),
            failure_entries=min(self._failure_n, self.cfg.failure_size),
            distinct_failures=self.distinct_failure_signatures(),
            host_io_s=host_io_s,
        )
        # flight recorder: the round lands on the run's search track and
        # advances the generation id that tags each policy decision;
        # archive occupancies ride along so the experiment plane can
        # reconstruct convergence/novelty trends per round
        # (obs/analytics.py convergence_stats)
        obs.record_generation(
            self.BACKEND, generations, elapsed, best_fitness,
            archive_entries=min(self._archive_n, self.cfg.archive_size),
            failure_entries=min(self._failure_n, self.cfg.failure_size),
            distinct_failures=self.distinct_failure_signatures(),
            host_io_s=host_io_s,
            fit_curve=fit_curve,
        )

    def labeled_archive(self):
        """(feats [N,K'], labels [N]) of the populated archive slots
        whose outcome is known (NaN labels — pre-surrogate checkpoints —
        are excluded). With guidance wired, K' = K + GUIDANCE_DIMS: the
        DAG-shape fragment rides along, so the surrogate learns from
        ordering SHAPE as well as precedence features."""
        n = min(self._archive_n, self.cfg.archive_size)
        feats, labels = self.archive[:n], self.archive_labels[:n]
        if self.guidance_feats is not None:
            feats = np.hstack([feats, self.guidance_feats[:n]])
        known = np.isfinite(labels)
        return feats[known], labels[known]

    def _device_inputs(self, encoded):
        """(traces, pairs, archive, failures) as device arrays, from one
        encoded trace or a list of them."""
        import jax.numpy as jnp

        from namazu_tpu.ops.schedule import TraceArrays

        encs = encoded if isinstance(encoded, (list, tuple)) else [encoded]
        h, _, a, m, fb = te.stack_traces(encs)
        # the faultable flag only matters when the fault half is scored;
        # leaving it None otherwise keeps the fault-off jit cache entry
        trace = TraceArrays(
            jnp.asarray(h), jnp.asarray(a), jnp.asarray(m),
            jnp.asarray(fb) if self._coin is not None else None,
        )
        return encs, trace, jnp.asarray(self.pairs), \
            jnp.asarray(self.archive), jnp.asarray(self.failures)

    # -- persistence -----------------------------------------------------

    def _state_dict(self) -> dict:
        raise NotImplementedError

    def _restore_state(self, z) -> None:
        raise NotImplementedError

    def save(self, path: str) -> None:
        import jax

        flat = {
            "backend": np.asarray(self.BACKEND),
            "hint_space": np.asarray(te.HINT_SPACE),
            "pairs": self.pairs,
            "archive": self.archive,
            "archive_labels": self.archive_labels,
            "archive_n": np.asarray(self._archive_n),
            "failures": self.failures,
            "failure_n": np.asarray(self._failure_n),
            "failure_digests": np.asarray(self._failure_digests),
            "key": np.asarray(jax.random.key_data(self._key)),
            "generations_run": np.asarray(self.generations_run),
        }
        if self.guidance_feats is not None:
            flat["guidance_feats"] = self.guidance_feats
        flat.update(self._state_dict())
        tmp = path + ".tmp.npz"
        np.savez(tmp, **flat)
        os.replace(tmp, path)

    def load(self, path: str) -> None:
        import jax
        import jax.numpy as jnp

        with np.load(path) as z:
            # pre-backend-tag checkpoints (GA only) have no "backend" key
            saved = str(z["backend"]) if "backend" in z else "ga"
            if saved != self.BACKEND:
                raise ValueError(
                    f"checkpoint {path} was written by the {saved!r} "
                    f"backend, not {self.BACKEND!r}"
                )
            if ("best_delays" in z
                    and z["best_delays"].shape != (self.cfg.H,)):
                # a mismatched genome length would load silently and
                # IndexError later on the policy's event hot path
                raise ValueError(
                    f"checkpoint {path} has H={z['best_delays'].shape[0]} "
                    f"delay buckets, config has H={self.cfg.H}"
                )
            space = te.checkpoint_hint_space(z)
            if space != te.HINT_SPACE:
                # every archived feature and evolved delay table keys
                # buckets in the old hint space; resuming from it would
                # deliver arbitrary delays under a "searched schedule" log
                raise ValueError(
                    f"checkpoint {path} was built in hint space {space!r}; "
                    f"this build hashes {te.HINT_SPACE!r} — delete it and "
                    "re-record"
                )
            if "pairs" in z:  # pre-informative-pairs checkpoints lack it
                self.pairs = z["pairs"]
            self.archive = z["archive"]
            if "archive_labels" in z:
                self.archive_labels = z["archive_labels"]
            else:
                # pre-surrogate checkpoint: outcomes of the archived runs
                # are unknown — NaN marks the slots unusable as training
                # data (a 0.0 default would teach the surrogate that the
                # runs that DID reproduce predict no-repro)
                self.archive_labels = np.full(
                    (self.cfg.archive_size,), np.nan, np.float32)
            self._archive_n = int(z["archive_n"])
            if self.guidance_feats is not None:
                if "guidance_feats" in z \
                        and z["guidance_feats"].shape \
                        == self.guidance_feats.shape:
                    self.guidance_feats = np.array(z["guidance_feats"])
                else:
                    # a pre-guidance (or differently-sized) checkpoint:
                    # its archive rows have no aligned DAG-shape
                    # fragment, and training a widened surrogate on
                    # zero-filled fragments would teach it that shape
                    # features mean nothing. Drop the archive — the
                    # very next ingest re-feeds the full stored history
                    # with fragments attached (models/ingest.py).
                    self.archive[:] = 0.5
                    self.archive_labels[:] = 0.0
                    self._archive_n = 0
            self.failures = z["failures"]
            self._failure_n = int(z["failure_n"])
            if "failure_digests" in z:
                self._failure_digests = [str(d) for d in
                                         z["failure_digests"]]
                self._failure_digest_set = {d for d in
                                            self._failure_digests if d}
            else:
                # pre-dedupe checkpoint: ring contents are unkeyed (and
                # possibly duplicated); the next ingest re-keys afresh
                self._failure_digests = [""] * self.cfg.failure_size
                self._failure_digest_set = set()
            self._key = jax.random.wrap_key_data(jnp.asarray(z["key"]))
            self.generations_run = int(z["generations_run"])
            self._restore_state(z)
        # every buffer just changed wholesale; device-resident mirrors
        # (fused loop) must rebuild from the restored host arrays
        self._mirror_invalidate()


class ScheduleSearch(SearchBase):
    BACKEND = "ga"

    def __init__(self, cfg: SearchConfig = SearchConfig(),
                 mesh=None, n_devices: Optional[int] = None):
        import jax

        from namazu_tpu.parallel.islands import init_island_state
        from namazu_tpu.parallel.mesh import make_mesh

        super().__init__(cfg)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        n_islands = 1
        for s in self.mesh.shape.values():
            n_islands *= s
        # population must divide evenly across islands
        per_island = max(1, cfg.population // n_islands)
        self.population = per_island * n_islands

        self._key = jax.random.PRNGKey(cfg.seed)
        if "h" in self.mesh.axis_names:
            # hybrid host x chip mesh -> hierarchical ICI/DCN migration,
            # each ring on its own cadence (dcn_migrate_every decouples
            # the thin DCN exchange from the generation count)
            from namazu_tpu.parallel.distributed import hier_rings

            self._rings = hier_rings(
                migrate_k=cfg.migrate_k,
                migrate_every=cfg.migrate_every,
                dcn_every=cfg.dcn_migrate_every,
            )
        else:
            self._rings = (("i", cfg.migrate_k, cfg.migrate_every),)
        from namazu_tpu.parallel.islands import make_multiaxis_island_step

        self._step = make_multiaxis_island_step(
            self.mesh, cfg.ga, cfg.weights, rings=self._rings
        )
        self._state = init_island_state(
            jax.random.PRNGKey(cfg.seed + 1), self.population, cfg.H, cfg.ga
        )
        self._surrogate = None  # built lazily on first labeled training
        # fused-loop machinery (doc/performance.md "Fused search loop"):
        # per-chunk-length fused step cache, device mirrors of the host
        # archive rings (kept in sync by _mirror_note's row updates),
        # and the device-resident reference-trace store
        self._fused_steps: dict = {}
        self._dev_mirrors = {"archive": None, "failures": None}
        self._dev_pairs = None
        self._dev_pairs_src = None
        self._dev_coin = None
        self._traces = _ResidentTraces()
        # host-side snapshot of (best_delays, best_faults, best_fitness)
        # from the last COMPLETED round: donation means a failed fused
        # dispatch leaves self._state pointing at deleted buffers, and
        # this (a few KB) is what _recover_state rebuilds the best from
        self._best_snapshot = None
        # one-shot device-trace capture latch (cfg.device_trace_dir)
        self._device_traced = False

    def _reset_best(self) -> None:
        import jax.numpy as jnp

        self._state = self._state._replace(
            best_fitness=jnp.full((), -jnp.inf, jnp.float32))

    # -- device-resident mirrors (fused loop) -----------------------------

    def _mirror_note(self, which: str, slot: int, row: np.ndarray) -> None:
        """A host archive ring slot was overwritten: apply the same row
        write to the device mirror (donated dynamic_update_slice) so the
        next fused run stages one [K] row instead of the whole ring."""
        mirrors = getattr(self, "_dev_mirrors", None)
        if mirrors is None:
            return
        buf = mirrors.get(which)
        if buf is not None:
            mirrors[which] = _device_row_update(buf, row, slot)

    def _mirror_invalidate(self) -> None:
        """Bulk host-side mutation (checkpoint load, pair refit,
        guidance rewiring): device mirrors rebuild from the host arrays
        on the next fused run. The resident TRACE rows stay — they are
        content-keyed and none of these mutations rewrites a recorded
        trace."""
        if getattr(self, "_dev_mirrors", None) is not None:
            self._dev_mirrors = {"archive": None, "failures": None}
            self._dev_pairs = None
            self._dev_pairs_src = None

    def _device_inputs_fused(self, encoded):
        """The fused-run analogue of ``_device_inputs``: the ordered
        trace view comes from the resident store (only missing rows
        upload), pairs/archive/failure buffers from the device mirrors
        (row-synced by ``_mirror_note``; staged whole only after a bulk
        invalidation). Array VALUES are identical to ``_device_inputs``
        for the same references — the property the fused-vs-unfused
        bit-exactness test leans on."""
        import jax.numpy as jnp

        from namazu_tpu.ops.schedule import TraceArrays

        encs = encoded if isinstance(encoded, (list, tuple)) else [encoded]
        h, a, m, fb = self._traces.view(encs)
        trace = TraceArrays(h, a, m,
                            fb if self._coin is not None else None)
        if self._dev_pairs is None or self._dev_pairs_src is not self.pairs:
            self._dev_pairs = jnp.asarray(self.pairs)
            self._dev_pairs_src = self.pairs
        if self._dev_mirrors["archive"] is None:
            self._dev_mirrors["archive"] = jnp.asarray(self.archive)
        if self._dev_mirrors["failures"] is None:
            self._dev_mirrors["failures"] = jnp.asarray(self.failures)
        return (encs, trace, self._dev_pairs,
                self._dev_mirrors["archive"], self._dev_mirrors["failures"])

    def _place_state(self) -> None:
        """Commit the island state to its mesh sharding (population
        sharded over the island axes, scalars/best replicated) BEFORE
        the first fused dispatch. A freshly-initialized (or
        checkpoint-restored / seeded) state is uncommitted, and jit
        keys its cache on concrete shardings: without this, the first
        fused call compiles for the uncommitted layout and the second
        — fed the donated-out, properly-sharded state — compiles AGAIN,
        which is exactly the warm-request jit cost the sidecar exists
        to amortize away. ``device_put`` on an already-placed array is
        a no-op, so steady-state calls cost nothing."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from namazu_tpu.models.ga import Population
        from namazu_tpu.parallel.islands import IslandState

        axes = tuple(self.mesh.axis_names)
        pop_sh = NamedSharding(self.mesh, P(axes))
        rep = NamedSharding(self.mesh, P())
        st = self._state
        self._state = IslandState(
            pop=Population(
                delays=jax.device_put(st.pop.delays, pop_sh),
                faults=jax.device_put(st.pop.faults, pop_sh),
            ),
            gen=jax.device_put(st.gen, rep),
            best_fitness=jax.device_put(st.best_fitness, rep),
            best_delays=jax.device_put(st.best_delays, rep),
            best_faults=jax.device_put(st.best_faults, rep),
        )

    def _fused_step_for(self, generations: int):
        """The jitted fused step for a chunk length (cached: a campaign
        with a fixed generations-per-run sees at most two lengths —
        the chunk and the remainder)."""
        fn = self._fused_steps.get(generations)
        if fn is None:
            from namazu_tpu.parallel.islands import make_fused_island_step

            fn = make_fused_island_step(
                self.mesh, self.cfg.ga, self.cfg.weights,
                rings=self._rings, generations=generations)
            self._fused_steps[generations] = fn
        return fn

    def seed_population(self, delay_tables) -> None:
        """Inject imitation genomes into the population before evolving.

        The GA's objective (match the failure archive in feature space)
        has local optima the mutation kernel rarely escapes — e.g. the
        asymmetric early/late delivery split that decides a leader
        election. But the control plane already *has* near-reproducing
        genomes: each recorded failure's per-bucket injected delays
        (release - arrival) form a delay table that, replayed against
        similar arrivals, re-enacts that failure's interleaving up to the
        system's reactions. Those tables are spread across the islands
        (one per stride) so every island refines from a demonstration
        instead of from noise; crossover/migration then mix them with the
        evolved material."""
        import jax
        import jax.numpy as jnp

        if len(delay_tables) == 0:
            return
        if jax.process_count() > 1:  # pragma: no cover - DCN runs
            # per-process seeding would diverge island contents between
            # hosts; skip rather than corrupt the sharded population
            return
        seeds = np.clip(
            np.stack([np.asarray(t, np.float32) for t in delay_tables]),
            0.0, self.cfg.ga.max_delay)
        n = min(seeds.shape[0], self.population)
        delays = np.array(jax.device_get(self._state.pop.delays))
        stride = max(1, self.population // n)
        idx = [min(i * stride, self.population - 1) for i in range(n)]
        delays[idx] = seeds[:n]
        # uncommitted on purpose: the island step's shard_map shards its
        # inputs itself; a device_put-committed array would pin the
        # population to one device and fail on a multi-device mesh
        self._state = self._state._replace(
            pop=self._state.pop._replace(delays=jnp.asarray(delays)))

    # -- search ----------------------------------------------------------

    def run(self, encoded, generations: int = 50) -> BestSchedule:
        """Evolve against one or more reference traces for N generations.

        Returns the best schedule seen so far (monotonic across calls) —
        unless ``cfg.surrogate_topk > 0`` and the surrogate has trained on
        both outcomes, in which case the evolved population's top-k by
        fitness are re-ranked by predicted P(reproduce) and the winner is
        returned (the candidate worth the next wall-clock replay).

        ``cfg.fused`` (default) runs the device-side fused loop; both
        paths produce bit-identical populations and best tables
        (tests/test_fused_loop.py), the fused one just stops paying a
        host round trip per generation and a full re-staging per run."""
        if self.cfg.fused:
            return self._run_fused(encoded, generations)
        return self._run_stepwise(encoded, generations)

    def _run_stepwise(self, encoded, generations: int) -> BestSchedule:
        """The pre-fusion loop: one jitted dispatch per generation.
        Kept callable (cfg.fused=False) as the fused path's bit-exact
        reference and for debugging single generations."""
        # per-phase wall-time breakdown (nmz_search_phase_seconds +
        # jax.profiler.TraceAnnotation when a profiler session is live):
        # "encode" = host->device staging, "evolve" = the fused
        # mutate/score/select/migrate loop (its in-step phases are
        # jax.named_scope-annotated in parallel/islands.py, visible in a
        # device profile), "extract"/"surrogate" = best extraction
        with obs.search_phase("encode"):
            _encs, trace, pairs, archive, failures = \
                self._device_inputs(encoded)
        import jax.numpy as jnp

        coin = None if self._coin is None else jnp.asarray(self._coin)
        nov_scale = jnp.asarray(self.novelty_scale(), jnp.float32)
        # guided mutation (doc/search.md): buckets participating in
        # one-sided/uncovered ordering relations mutate more often —
        # None (no map) keeps the unbiased kernel bit-for-bit
        bias = (None if self.guidance is None
                else jnp.asarray(self.guidance.mutation_bias()))
        state = self._state
        t0 = time.perf_counter()
        with obs.search_phase("evolve"):
            for _ in range(generations):
                state = self._step(state, self._key, trace, pairs, archive,
                                   failures, coin, nov_scale, bias)
            state.best_fitness.block_until_ready()
        elapsed = time.perf_counter() - t0
        self._state = state
        self.generations_run += generations
        self._record_progress(generations, elapsed,
                              generations * self.population,
                              float(state.best_fitness))
        with obs.search_phase("surrogate"):
            picked = self._surrogate_pick(trace, pairs, archive, failures,
                                          nov_scale, encs=_encs)
        if picked is not None:
            return picked
        with obs.search_phase("extract"):
            return self.best()

    def _maybe_start_device_trace(self) -> bool:
        """Start the one-shot ``jax.profiler`` device-trace capture
        when ``cfg.device_trace_dir`` is set and nothing was captured
        yet. Fail-open: a profiler the runtime can't start (no jax, a
        capture already live elsewhere) degrades to no trace, never an
        error into the search."""
        if not self.cfg.device_trace_dir or self._device_traced:
            return False
        self._device_traced = True
        out = os.path.join(self.cfg.device_trace_dir, "device_trace")
        try:
            import jax

            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out)
        except Exception as e:
            log.warning("device-trace capture unavailable (%s); "
                        "search continues untraced", e)
            return False
        log.info("capturing device trace of this evolve section "
                 "into %s", out)
        return True

    def _stop_device_trace(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # stop must never mask the evolve outcome
            log.debug("device-trace stop failed", exc_info=True)
            return
        obs.search_device_trace(
            os.path.join(self.cfg.device_trace_dir, "device_trace"))

    def _run_fused(self, encoded, generations: int) -> BestSchedule:
        """The device-resident loop (doc/performance.md "Fused search
        loop"): generations run in fused_chunk-sized scans — one jitted
        dispatch each, island state donated — while the host lane drains
        the PREVIOUS chunk's per-generation best-fitness history
        (``jax.device_get`` on arrays the device finished or is
        finishing while the current chunk computes). The host gap shows
        up as ``nmz_search_phase_seconds{phase="host_io"}`` and the
        generation record's ``host_io_s``."""
        with obs.search_phase("encode"):
            encs, trace, pairs, archive, failures = \
                self._device_inputs_fused(encoded)
        import jax.numpy as jnp

        if self._coin is not None and self._dev_coin is None:
            self._dev_coin = jnp.asarray(self._coin)
        coin = self._dev_coin if self._coin is not None else None
        nov_scale = jnp.asarray(self.novelty_scale(), jnp.float32)
        bias = (None if self.guidance is None
                else jnp.asarray(self.guidance.mutation_bias()))
        host_io_s = 0.0
        fit_curve: list = []
        pending = None
        tracing = self._maybe_start_device_trace()
        t0 = time.perf_counter()
        with obs.search_phase("evolve"):
            # the whole evolve section recovers as one unit: dispatch
            # is ASYNC, so a device-side failure can surface not at the
            # fused() call but later — at the host lane's device_get of
            # a poisoned history, or at the final block_until_ready.
            # Wherever it surfaces, the donated-in buffers are gone and
            # self._state must be rebuilt, or every later run() of a
            # long-lived sidecar search fails against deleted arrays.
            try:
                self._place_state()  # one jit cache entry, not two
                done = 0
                while done < generations:
                    g = min(self.cfg.fused_chunk, generations - done)
                    fused = self._fused_step_for(g)
                    # the input state is DONATED: keep only the
                    # returned one
                    state, fit_hist = fused(
                        self._state, self._key, trace, pairs, archive,
                        failures, coin, nov_scale, bias)
                    self._state = state
                    done += g
                    if pending is not None:
                        # double-buffered host lane: drain chunk N-1's
                        # snapshot while chunk N computes on device
                        th = time.perf_counter()
                        with obs.search_phase("host_io"):
                            self._drain_host_lane(pending, fit_curve)
                        host_io_s += time.perf_counter() - th
                    pending = fit_hist
                if pending is not None:
                    th = time.perf_counter()
                    with obs.search_phase("host_io"):
                        self._drain_host_lane(pending, fit_curve)
                    host_io_s += time.perf_counter() - th
                self._state.best_fitness.block_until_ready()
            except Exception:
                self._recover_state()
                raise
            finally:
                if tracing:
                    self._stop_device_trace()
        elapsed = time.perf_counter() - t0
        self.generations_run += generations
        # recovery snapshot (tiny: two [H] rows + a scalar): the newest
        # completed round's best, host-side, surviving any later
        # donated-dispatch failure
        self._best_snapshot = (
            np.asarray(self._state.best_delays),
            np.asarray(self._state.best_faults),
            float(self._state.best_fitness),
        )
        # scorer-throughput source label "fused": the serving figure of
        # the fused loop, beside the backend-labeled gauge search_round
        # publishes (doc/observability.md)
        obs.scorer_throughput(
            "fused", generations * self.population / max(elapsed, 1e-9))
        self._record_progress(generations, elapsed,
                              generations * self.population,
                              float(self._state.best_fitness),
                              host_io_s=host_io_s, fit_curve=fit_curve)
        with obs.search_phase("surrogate"):
            picked = self._surrogate_pick(trace, pairs, archive, failures,
                                          nov_scale, encs=encs)
        if picked is not None:
            return picked
        with obs.search_phase("extract"):
            return self.best()

    def _drain_host_lane(self, fit_hist, fit_curve: list) -> None:
        """The overlapped host-I/O work for one completed chunk: fetch
        its per-generation global-best history (blocks only until THAT
        chunk's results exist — the current chunk keeps computing),
        publish live progress, and grow the per-generation curve that
        lands on the round's flight-recorder generation record
        (``fit_curve``). Everything here runs while the device is busy,
        which is what closes the pre-fusion host gaps."""
        vals = np.asarray(fit_hist)
        fit_curve.extend(float(v) for v in vals)
        if vals.size:
            # the gauge is "best fitness seen so far": publish the
            # running max (this run's curve so far, floored at the last
            # completed round's best) — a chunk's own last generation
            # can sit BELOW an earlier best and must not regress it
            prev = (self._best_snapshot[2] if self._best_snapshot
                    else float("-inf"))
            obs.search_progress(self.BACKEND, max(prev, max(fit_curve)))

    def _recover_state(self) -> None:
        """Rebuild a usable island state after a fused dispatch died
        mid-flight: the donated input buffers are deleted, so the
        population restarts fresh (keyed off the generation counter —
        no replayed draws) while the best-so-far tables restore from
        the host snapshot of the last completed round. Progress inside
        the failed round is lost; the object — and a long-lived
        sidecar serving it — keeps working."""
        import jax
        import jax.numpy as jnp

        from namazu_tpu.parallel.islands import init_island_state

        log.warning(
            "fused dispatch failed mid-round; rebuilding island state "
            "(population restarts, best-so-far restored from the last "
            "completed round)")
        self._state = init_island_state(
            jax.random.PRNGKey(self.cfg.seed + 1 + self.generations_run),
            self.population, self.cfg.H, self.cfg.ga)
        self._state = self._state._replace(
            gen=jnp.asarray(self.generations_run, jnp.int32))
        snap = self._best_snapshot
        if snap is not None:
            bd, bf, fit = snap
            self._state = self._state._replace(
                best_fitness=jnp.asarray(fit, jnp.float32),
                best_delays=jnp.asarray(bd),
                best_faults=jnp.asarray(bf),
            )

    def novelty_scale(self) -> float:
        """Annealed multiplier on ``weights.novelty`` (see
        ``SearchConfig.min_failure_signatures``): 1.0 while the failure
        archive holds fewer than the threshold's worth of distinct
        signatures, then decays as threshold/n, floored."""
        ms = self.cfg.min_failure_signatures
        if ms <= 0:
            return 1.0
        n = self.distinct_failure_signatures()
        if n < ms:
            return 1.0
        return max(self.cfg.novelty_floor, ms / n)

    def _fetch_population(self):
        """Population as host numpy arrays (delays, faults).

        On a multi-process mesh the population is sharded across hosts
        and ``np.asarray`` on it raises "non-addressable devices"; gather
        it explicitly so surrogate re-ranking and checkpointing work in
        real DCN runs, not just virtual-host meshes."""
        import jax

        pop = self._state.pop
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return (
                np.asarray(multihost_utils.process_allgather(
                    pop.delays, tiled=True)),
                np.asarray(multihost_utils.process_allgather(
                    pop.faults, tiled=True)),
            )
        return np.asarray(pop.delays), np.asarray(pop.faults)

    # -- surrogate (BASELINE config 5) ------------------------------------

    #: minimum labeled examples PER CLASS before surrogate re-ranking
    #: may override the fitness argmax: an MLP fit on one positive
    #: re-ranks near-randomly, and handing it veto power over the
    #: evolved best dilutes a good schedule into mush (observed: with a
    #: single recorded failure the re-ranked pick lost the failure's
    #: decisive starve pattern that the argmax carried)
    MIN_CLASS_EXAMPLES = 3

    def _surrogate_input_dims(self) -> int:
        """Surrogate feature width: precedence K, plus the guidance
        plane's DAG-shape fragment when a map is wired. The knowledge
        service keys example stores by this width, so guided and
        unguided campaigns can never pool training data."""
        return self.cfg.K + self._guidance_dims()

    def _train_surrogate(self):
        """Fit the online MLP on the labeled archive; returns it, or None
        when surrogate use is off or either outcome class is still too
        thin to learn from."""
        if self.cfg.surrogate_topk <= 0:
            return None
        feats, labels = self.labeled_archive()
        pos = int((labels > 0.5).sum())
        neg = int(len(labels) - pos)
        if min(pos, neg) < self.MIN_CLASS_EXAMPLES:
            return None  # nothing reliably learnable yet
        if self._surrogate is None:
            from namazu_tpu.models.surrogate import RewardSurrogate

            self._surrogate = RewardSurrogate(
                K=self._surrogate_input_dims(), seed=self.cfg.seed)
        self._surrogate.train(feats, labels, epochs=4,
                              seed=self.cfg.seed + self.generations_run)
        return self._surrogate

    def _candidate_guidance(self, delays: np.ndarray, encs):
        """Predicted relation-coverage gain + DAG-shape fragment per
        candidate delay table, simulated against the most recent
        reference trace under the delay-mode release rule
        (``release = arrival + delays[bucket]`` — the same
        counterfactual the scorer anchors on). Returns
        ``(gains f32[k], frags f32[k, G])``."""
        from namazu_tpu.guidance import dag_shape_features

        enc = encs[0]
        m = enc.mask
        buckets = enc.hint_ids[m]
        arrivals = enc.arrival[m]
        k = delays.shape[0]
        gains = np.zeros((k,), np.float32)
        frags = np.zeros((k, self._guidance_dims()), np.float32)
        for i in range(k):
            times = arrivals + delays[i][buckets]
            order = np.argsort(times, kind="stable")
            gains[i] = self.guidance.predicted_gain(buckets[order])
            frags[i] = dag_shape_features(
                buckets, arrivals, times,
                width=self.guidance.width, dims=self._guidance_dims())
        return gains, frags

    def _surrogate_pick(self, trace, pairs, archive, failures,
                        nov_scale=None, encs=()) -> Optional[BestSchedule]:
        """Re-rank the evolved population's fitness top-k; return the
        winner (None = nothing to re-rank with — fitness argmax).

        The base score is predicted P(reproduce): the local online MLP
        once it has enough of both outcome classes, before that — the
        cold-start window — the shared knowledge-service surrogate
        (``remote_surrogate``), and with neither trained, the top-k's
        min-max-normalized fitness. With a guidance map wired
        (doc/search.md) the pick becomes COVERAGE-GUIDED:
        ``cfg.guidance_bonus`` times each candidate's predicted
        relation-coverage gain is added on top, so among comparably
        promising schedules the one predicted to exercise untested
        orderings wins the next wall-clock replay. Without a map the
        behavior is exactly the pre-guidance surrogate re-rank."""
        surrogate = self._train_surrogate()
        remote = self.remote_surrogate if surrogate is None else None
        guided = self.guidance is not None and len(encs) > 0
        if self.cfg.surrogate_topk <= 0:
            return None  # explicit knob: raw fitness argmax only
        if surrogate is None and remote is None and not guided:
            return None
        import jax.numpy as jnp

        from namazu_tpu.ops.schedule import score_population_multi

        k = min(self.cfg.surrogate_topk, self.population)
        # de-shard the island population (a few MB) — this re-score runs
        # outside shard_map, where scatter on an @i-sharded operand is
        # ambiguous; trace arrives stacked [T, L] from _device_inputs
        delays_np, faults = self._fetch_population()
        delays = jnp.asarray(delays_np)
        fitness, feats = score_population_multi(
            delays, trace, pairs, archive, failures, self.cfg.weights,
            faults=None if self._coin is None else jnp.asarray(faults),
            coin=None if self._coin is None else jnp.asarray(self._coin),
            novelty_scale=nov_scale,
        )
        top = np.asarray(jnp.argsort(-fitness)[:k])
        # features averaged over the reference traces, like the fitness
        cand_feats = np.asarray(feats[top].mean(axis=1))
        gains = frags = None
        if guided:
            gains, frags = self._candidate_guidance(delays_np[top], encs)
        base = None
        if surrogate is not None or remote is not None:
            full = (cand_feats if frags is None
                    else np.hstack([cand_feats, frags]))
            base = (surrogate.predict(full) if surrogate is not None
                    else remote(full))
        if base is None:
            if gains is None:
                return None  # outage/untrained, no guidance: argmax
            f = np.asarray(fitness)[top]
            span = float(f.max() - f.min())
            base = ((f - f.min()) / span if span > 0
                    else np.zeros_like(f))
        score = (np.asarray(base) if gains is None
                 else np.asarray(base) + self.cfg.guidance_bonus * gains)
        winner = int(top[int(np.argmax(score))])
        return BestSchedule(
            delays=np.asarray(delays[winner]),
            faults=faults[winner],
            fitness=float(fitness[winner]),
        )

    def best(self) -> BestSchedule:
        return BestSchedule(
            delays=np.asarray(self._state.best_delays),
            faults=np.asarray(self._state.best_faults),
            fitness=float(self._state.best_fitness),
        )

    # -- persistence -----------------------------------------------------

    def _state_dict(self) -> dict:
        pop_delays, pop_faults = self._fetch_population()
        d = {
            "pop_delays": pop_delays,
            "pop_faults": pop_faults,
            "gen": np.asarray(self._state.gen),
            "best_fitness": np.asarray(self._state.best_fitness),
            "best_delays": np.asarray(self._state.best_delays),
            "best_faults": np.asarray(self._state.best_faults),
        }
        if self._surrogate is not None:
            from jax.flatten_util import ravel_pytree

            vec, _ = ravel_pytree(self._surrogate.state.params)
            d["surrogate_params"] = np.asarray(vec)
        return d

    def _restore_state(self, z) -> None:
        import jax.numpy as jnp

        from namazu_tpu.parallel.islands import IslandState
        from namazu_tpu.models.ga import Population

        pd = np.asarray(z["pop_delays"])
        pf = np.asarray(z["pop_faults"])
        expected = (self.population, self.cfg.H)
        if pd.shape != expected or pf.shape != expected:
            # a population/genome-width mismatch (config changed between
            # runs, or a checkpoint from a differently-sized mesh) must
            # not crash the load OR shard-mismatch later inside the
            # step: keep the fresh population and re-evolve — archives,
            # best tables, and the RNG stream restore as usual (the PR
            # 11 width-mismatch-retrains rule extended to the island
            # state; pinned by tests/test_fused_loop.py)
            log.warning(
                "checkpoint population %s does not fit this config %s; "
                "keeping a fresh population (archives and best tables "
                "restored)", pd.shape, expected)
            pop = self._state.pop
        else:
            pop = Population(delays=jnp.asarray(pd),
                             faults=jnp.asarray(pf))
        self._state = IslandState(
            pop=pop,
            gen=jnp.asarray(z["gen"]),
            best_fitness=jnp.asarray(z["best_fitness"]),
            best_delays=jnp.asarray(z["best_delays"]),
            best_faults=jnp.asarray(z["best_faults"]),
        )
        # the recovery snapshot tracks the restored best too — a fused
        # dispatch failing right after a checkpoint load must not lose
        # the loaded tables (_recover_state)
        self._best_snapshot = (
            np.asarray(z["best_delays"]),
            np.asarray(z["best_faults"]),
            float(z["best_fitness"]),
        )
        if "surrogate_params" in z:
            from jax.flatten_util import ravel_pytree

            from namazu_tpu.models.surrogate import RewardSurrogate

            # deterministic re-init yields the unravel structure; the
            # optimizer restarts (momentum is not worth persisting)
            self._surrogate = RewardSurrogate(
                K=self._surrogate_input_dims(), seed=self.cfg.seed)
            ref, unravel = ravel_pytree(self._surrogate.state.params)
            saved = jnp.asarray(z["surrogate_params"])
            if saved.shape == ref.shape:
                self._surrogate.state = self._surrogate.state._replace(
                    params=unravel(saved)
                )
            else:
                # guidance was toggled since this checkpoint was
                # written: the feature widths differ, so the persisted
                # weights don't apply — retrain from the labeled
                # archive instead of failing the whole load
                self._surrogate = None


class MCTSSearch(SearchBase):
    """Config-5 backend: root-parallel MCTS (models/mcts.py) behind the
    same driver API as :class:`ScheduleSearch`, so ``policy/tpu.py`` can
    swap backends with one config key (``search_backend = "mcts"``)."""

    BACKEND = "mcts"

    def __init__(self, cfg: SearchConfig = SearchConfig(), mcts_cfg=None,
                 mesh=None, n_devices: Optional[int] = None):
        import jax

        from namazu_tpu.models.mcts import MCTSConfig, make_parallel_mcts
        from namazu_tpu.parallel.mesh import make_mesh

        super().__init__(cfg)
        self.mesh = mesh if mesh is not None else make_mesh(n_devices)
        self.mcts_cfg = mcts_cfg if mcts_cfg is not None else MCTSConfig(
            max_delay=cfg.ga.max_delay, max_fault=cfg.ga.max_fault
        )
        if self.mcts_cfg.max_fault > 0 and self._coin is None:
            # an explicit mcts_cfg can enable fault search even when
            # cfg.ga doesn't — the rollouts still need the fault coin
            self._coin = te.fault_coin(cfg.seed, cfg.H)
        if self.mcts_cfg.tree_depth > cfg.H:
            # the tree cannot decide more buckets than the genome has
            self.mcts_cfg = self.mcts_cfg._replace(tree_depth=cfg.H)
        self._run = make_parallel_mcts(self.mesh, cfg.H, self.mcts_cfg,
                                       cfg.weights)
        self._key = jax.random.PRNGKey(cfg.seed)
        self._best_fitness = float("-inf")
        self._best_delays = np.zeros((cfg.H,), np.float32)
        self._best_faults = np.zeros((cfg.H,), np.float32)
        self._seed_tables: Optional[np.ndarray] = None  # f32[S, H]

    def _reset_best(self) -> None:
        self._best_fitness = float("-inf")

    #: seed tables are cyclically tiled to this fixed row count so the
    #: jitted search sees ONE seeds shape — otherwise every new recorded
    #: failure (S = 1, 2, 3, ...) would force a full recompile of the
    #: parallel MCTS
    SEED_ROWS = 16

    def seed_population(self, delay_tables) -> None:
        """Demonstration tables steer the rollouts: half of each rollout
        batch completes unpinned buckets from a noise-perturbed seed
        (models/mcts.py _make_rollout) — the MCTS analogue of the GA's
        population seeding, same source (recorded failures' injected
        delays)."""
        if len(delay_tables) == 0:
            return
        raw = np.clip(
            np.stack([np.asarray(t, np.float32) for t in delay_tables]),
            0.0, self.mcts_cfg.max_delay)
        reps = -(-self.SEED_ROWS // raw.shape[0])
        self._seed_tables = np.tile(raw, (reps, 1))[: self.SEED_ROWS]

    def _hint_order(self, encs) -> np.ndarray:
        """Bucket ids ordered by frequency across the reference traces —
        the tree decides the most-often-hit buckets first."""
        counts = np.zeros((self.cfg.H,), np.int64)
        for e in encs:
            counts += np.bincount(e.hint_ids[e.mask],
                                  minlength=self.cfg.H)
        return np.argsort(-counts)[: self.mcts_cfg.tree_depth].astype(
            np.int32
        )

    def run(self, encoded, generations: int = 1) -> BestSchedule:
        """Run ``max(1, generations // 64)`` independent tree searches of
        ``mcts_cfg.simulations`` expansions each (the GA's ``generations``
        knob maps onto simulation budget so configs stay comparable);
        returns the best schedule seen so far (monotonic across calls)."""
        import jax
        import jax.numpy as jnp

        encs, trace, pairs, archive, failures = self._device_inputs(encoded)
        hint_order = jnp.asarray(self._hint_order(encs))
        coin = None if self._coin is None else jnp.asarray(self._coin)
        seeds = (None if self._seed_tables is None
                 else jnp.asarray(self._seed_tables))

        searches = max(1, generations // 64)
        t0 = time.perf_counter()
        for _ in range(searches):
            self._key, sub = jax.random.split(self._key)
            fit, d, f = self._run(sub, trace, pairs, archive, failures,
                                  hint_order, coin, seeds)
            fit = float(fit)
            if fit > self._best_fitness:
                self._best_fitness = fit
                self._best_delays = np.asarray(d)
                self._best_faults = np.asarray(f)
        elapsed = time.perf_counter() - t0
        sims = searches * self.mcts_cfg.simulations
        self.generations_run += sims
        self._record_progress(sims, elapsed,
                              sims * self.mcts_cfg.rollouts,
                              self._best_fitness)
        return self.best()

    def best(self) -> BestSchedule:
        return BestSchedule(
            delays=self._best_delays,
            faults=self._best_faults,
            fitness=self._best_fitness,
        )

    # -- persistence -----------------------------------------------------

    def _state_dict(self) -> dict:
        return {
            "best_fitness": np.asarray(self._best_fitness, np.float32),
            "best_delays": self._best_delays,
            "best_faults": self._best_faults,
        }

    def _restore_state(self, z) -> None:
        self._best_fitness = float(z["best_fitness"])
        self._best_delays = z["best_delays"]
        self._best_faults = z["best_faults"]
