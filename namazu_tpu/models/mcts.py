"""Jittable Monte-Carlo tree search over schedule genomes (config 5).

The GA (models/ga.py) treats the genome as a flat vector; MCTS instead
*sequentialises* it: hint buckets are ordered by importance (frequency in
the reference traces), each tree level picks one of ``D`` quantised delay
levels for the next bucket, and leaf values come from batched random
rollouts (complete the remaining buckets uniformly, score the whole batch
with the same counterfactual-interleaving scorer the GA uses —
ops/schedule.py). The search therefore concentrates simulation budget on
the few buckets that actually flip precedence features, which is exactly
the regime where flat GA mutation wastes samples.

TPU-first design, in the style of DeepMind's mctx: the tree lives in
fixed-shape arrays (parent/children/visit/value), one simulation =
select (``lax.while_loop`` descent by normalised UCT) -> expand (one node)
-> rollout (``[R, H]`` delay matrix scored in one vmap/MXU batch) ->
backprop (``lax.while_loop`` up the parent chain), and the whole
``simulations``-iteration search is a single ``lax.fori_loop`` under
``jit``. No Python control flow touches the hot loop; root-parallel trees
across devices ride ``shard_map`` + ``all_gather`` like the GA islands.

The reference has no counterpart (its exploration is one random schedule
per wall-clock run, SURVEY.md §2.3/§2.9); this is the "MCTS variant"
called for by SURVEY.md §7 step 6 / BASELINE.json config 5.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from namazu_tpu.ops.schedule import (
    ScoreWeights,
    TraceArrays,
    normalize_fault_trace,
    replicated_trace_specs,
    score_population_multi,
)
from namazu_tpu.parallel.mesh import shard_map as compat_shard_map

NO_CHILD = jnp.int32(-1)


class MCTSConfig(NamedTuple):
    tree_depth: int = 24  # buckets decided by the tree (most important first)
    n_levels: int = 8  # quantised delay levels per bucket
    simulations: int = 256  # tree expansions per search call
    rollouts: int = 64  # random completions scored per leaf (one batch)
    c_uct: float = 1.25  # exploration constant (on [0,1]-normalised values)
    max_delay: float = 0.1  # seconds; level j = j/(D-1) * max_delay
    max_fault: float = 0.0  # rollout fault-probability cap (0 = off)


class Tree(NamedTuple):
    """Fixed-capacity search tree, N = simulations + 1 nodes."""

    parent: jax.Array  # i32[N]
    action: jax.Array  # i32[N] level chosen on the edge into this node
    depth: jax.Array  # i32[N] root = 0
    children: jax.Array  # i32[N, D], NO_CHILD where unexpanded
    visit: jax.Array  # f32[N]
    value_sum: jax.Array  # f32[N]
    n_nodes: jax.Array  # i32 scalar


class MCTSResult(NamedTuple):
    best_fitness: jax.Array  # f32 scalar
    best_delays: jax.Array  # f32[H]
    best_faults: jax.Array  # f32[H]
    tree_visits: jax.Array  # f32[N] (diagnostics: visit counts)
    root_child_visits: jax.Array  # f32[D] (diagnostics)


def init_tree(cfg: MCTSConfig) -> Tree:
    N, D = cfg.simulations + 1, cfg.n_levels
    return Tree(
        parent=jnp.full((N,), NO_CHILD),
        action=jnp.full((N,), NO_CHILD),
        depth=jnp.zeros((N,), jnp.int32),
        children=jnp.full((N, D), NO_CHILD),
        visit=jnp.zeros((N,), jnp.float32),
        value_sum=jnp.zeros((N,), jnp.float32),
        n_nodes=jnp.ones((), jnp.int32),  # node 0 = root
    )


def _ucb_scores(tree: Tree, node: jax.Array, vmin: jax.Array,
                vmax: jax.Array, c: float) -> jax.Array:
    """Normalised-UCT score per child slot; unexpanded slots get +inf so
    every action is tried once before any is revisited."""
    kids = tree.children[node]  # i32[D]
    safe = jnp.maximum(kids, 0)
    v = tree.visit[safe]
    q = tree.value_sum[safe] / jnp.maximum(v, 1.0)
    # until two distinct values exist (vmax==vmin, or still +-inf), all
    # visited children tie at 0.5 and exploration alone drives selection
    denom = vmax - vmin
    q01 = jnp.where(
        denom > 1e-9, (q - vmin) / jnp.maximum(denom, 1e-9), 0.5
    )
    q01 = jnp.where(jnp.isfinite(q01), q01, 0.5)
    explore = c * jnp.sqrt(jnp.log(tree.visit[node] + 1.0)
                           / jnp.maximum(v, 1.0))
    scored = q01 + explore
    return jnp.where(kids == NO_CHILD, jnp.inf, scored)


class _SearchCarry(NamedTuple):
    tree: Tree
    key: jax.Array
    vmin: jax.Array  # running min of rollout values (for UCT normalisation)
    vmax: jax.Array
    best_fitness: jax.Array
    best_delays: jax.Array
    best_faults: jax.Array


def _make_rollout(trace: TraceArrays, pairs, archive, failure_feats,
                  hint_order, level_values, H: int, cfg: MCTSConfig,
                  weights: ScoreWeights, coin=None, seeds=None):
    """Returns rollout(key, levels i32[tree_depth]) ->
    (mean_fitness, best_fitness, best_delays, best_faults).

    When ``cfg.max_fault > 0`` (and a fault ``coin`` is given), the random
    fault matrices participate in the counterfactual score — the returned
    best fault table is *selected*, not an unselected random draw.

    ``seeds f32[S, H]`` (S may be 0) are demonstration delay tables —
    recorded failures' injected delays, same source as the GA's
    population seeding: up to half of each rollout batch completes the
    unpinned buckets from a noise-perturbed seed instead of uniform
    noise, so leaf values reflect what the demonstrations reach from
    this tree prefix and the tree is steered toward them."""
    n_seeds = 0 if seeds is None else seeds.shape[0]
    n_seeded_rows = min(cfg.rollouts // 2, max(0, n_seeds * 4))

    def rollout(key, levels):
        kd, kf, ks = jax.random.split(key, 3)
        R = cfg.rollouts
        delays = jax.random.uniform(kd, (R, H), jnp.float32, 0.0,
                                    cfg.max_delay)
        if n_seeded_rows > 0:
            rep = jnp.tile(seeds, (-(-n_seeded_rows // n_seeds), 1))
            rep = rep[:n_seeded_rows]
            noise = jax.random.normal(ks, (n_seeded_rows, H)) * (
                0.05 * cfg.max_delay)
            delays = delays.at[:n_seeded_rows].set(
                jnp.clip(rep + noise, 0.0, cfg.max_delay))
        faults = jax.random.uniform(kf, (R, H), jnp.float32, 0.0,
                                    cfg.max_fault)
        # pin the tree-assigned buckets
        assigned = levels >= 0  # bool[tree_depth]
        val = level_values[jnp.maximum(levels, 0)]  # f32[tree_depth]
        pin_val = jnp.zeros((H,), jnp.float32).at[hint_order].set(val)
        pin_mask = jnp.zeros((H,), bool).at[hint_order].set(assigned)
        delays = jnp.where(pin_mask[None, :], pin_val[None, :], delays)
        score_faults = faults if (cfg.max_fault > 0 and coin is not None) \
            else None
        fitness, _ = score_population_multi(
            delays, trace, pairs, archive, failure_feats, weights,
            faults=score_faults, coin=coin,
        )  # f32[R]
        b = jnp.argmax(fitness)
        return fitness.mean(), fitness[b], delays[b], faults[b]

    return rollout


def mcts_search(
    key: jax.Array,
    trace: TraceArrays,  # stacked [T, L] arrays (see stack_traces)
    pairs: jax.Array,  # i32[K, 2]
    archive: jax.Array,  # f32[A, K]
    failure_feats: jax.Array,  # f32[F, K]
    hint_order: jax.Array,  # i32[tree_depth] bucket ids, important first
    H: int,
    cfg: MCTSConfig = MCTSConfig(),
    weights: ScoreWeights = ScoreWeights(),
    coin: jax.Array | None = None,  # f32[H] deterministic fault coin
    seeds: jax.Array | None = None,  # f32[S, H] demonstration tables
) -> MCTSResult:
    """Run one full MCTS; pure function of its inputs (jit-safe)."""
    if coin is None and cfg.max_fault > 0:
        # without the coin the rollout fault tables would be returned
        # unscored — the round-1 bug config 4 fixes. Guarded here (not
        # just in make_parallel_mcts) so every public entry enforces it.
        raise ValueError(
            "fault search is enabled (max_fault > 0) but no fault coin "
            "was passed; build one with trace_encoding.fault_coin(seed, H)"
        )
    D, Td = cfg.n_levels, cfg.tree_depth
    level_values = jnp.linspace(0.0, cfg.max_delay, D).astype(jnp.float32)
    rollout = _make_rollout(trace, pairs, archive, failure_feats,
                            hint_order, level_values, H, cfg, weights,
                            coin=coin, seeds=seeds)

    def simulate(i, carry: _SearchCarry) -> _SearchCarry:
        tree, key = carry.tree, carry.key
        key, ksel, kroll = jax.random.split(key, 3)

        # -- selection: descend by UCT until an unexpanded slot or max depth
        def sel_cond(s):
            _node, _levels, done, _act = s
            return ~done

        def sel_body(s):
            node, levels, _done, _act = s
            d = tree.depth[node]
            at_max = d >= Td

            def pick():
                scores = _ucb_scores(tree, node, carry.vmin, carry.vmax,
                                     cfg.c_uct)
                a = jnp.argmax(scores).astype(jnp.int32)
                child = tree.children[node, a]
                lv = levels.at[d].set(a)
                # child exists -> keep descending; else stop and expand
                nxt = jnp.where(child == NO_CHILD, node, child)
                return nxt, lv, child == NO_CHILD, a

            def stop():  # terminal leaf: rollout from here, no expansion
                return node, levels, jnp.bool_(True), NO_CHILD

            return jax.lax.cond(at_max, stop, pick)

        levels0 = jnp.full((Td,), NO_CHILD)
        node, levels, _done, act = jax.lax.while_loop(
            sel_cond, sel_body,
            (jnp.int32(0), levels0, jnp.bool_(False), NO_CHILD),
        )

        # -- expansion: allocate one node (skip when terminal, act < 0)
        expand = act >= 0
        new = tree.n_nodes
        safe_act = jnp.maximum(act, 0)
        tree = Tree(
            parent=tree.parent.at[new].set(
                jnp.where(expand, node, tree.parent[new])),
            action=tree.action.at[new].set(
                jnp.where(expand, act, tree.action[new])),
            depth=tree.depth.at[new].set(
                jnp.where(expand, tree.depth[node] + 1, tree.depth[new])),
            children=tree.children.at[node, safe_act].set(
                jnp.where(expand, new, tree.children[node, safe_act])),
            visit=tree.visit,
            value_sum=tree.value_sum,
            n_nodes=tree.n_nodes + expand.astype(jnp.int32),
        )
        leaf = jnp.where(expand, new, node)

        # -- rollout: batch of random completions under the pinned prefix
        mean_v, roll_fit, roll_d, roll_f = rollout(kroll, levels)

        # -- backprop to root
        def bp_cond(s):
            n, _t = s
            return n != NO_CHILD

        def bp_body(s):
            n, t = s
            t = Tree(
                parent=t.parent, action=t.action, depth=t.depth,
                children=t.children,
                visit=t.visit.at[n].add(1.0),
                value_sum=t.value_sum.at[n].add(mean_v),
                n_nodes=t.n_nodes,
            )
            return t.parent[n], t

        _, tree = jax.lax.while_loop(bp_cond, bp_body, (leaf, tree))

        improved = roll_fit > carry.best_fitness
        return _SearchCarry(
            tree=tree,
            key=key,
            vmin=jnp.minimum(carry.vmin, mean_v),
            vmax=jnp.maximum(carry.vmax, mean_v),
            best_fitness=jnp.where(improved, roll_fit, carry.best_fitness),
            best_delays=jnp.where(improved, roll_d, carry.best_delays),
            best_faults=jnp.where(improved, roll_f, carry.best_faults),
        )

    carry0 = _SearchCarry(
        tree=init_tree(cfg),
        key=key,
        vmin=jnp.full((), jnp.inf, jnp.float32),
        vmax=jnp.full((), -jnp.inf, jnp.float32),
        best_fitness=jnp.full((), -jnp.inf, jnp.float32),
        best_delays=jnp.zeros((H,), jnp.float32),
        best_faults=jnp.zeros((H,), jnp.float32),
    )
    out = jax.lax.fori_loop(0, cfg.simulations, simulate, carry0)
    return MCTSResult(
        best_fitness=out.best_fitness,
        best_delays=out.best_delays,
        best_faults=out.best_faults,
        tree_visits=out.tree.visit,
        root_child_visits=out.tree.visit[
            jnp.maximum(out.tree.children[0], 0)
        ] * (out.tree.children[0] != NO_CHILD),
    )


@functools.partial(jax.jit, static_argnames=("H", "cfg", "weights"))
def mcts_search_jit(key, trace, pairs, archive, failure_feats, hint_order,
                    H: int, cfg: MCTSConfig = MCTSConfig(),
                    weights: ScoreWeights = ScoreWeights(),
                    coin=None, seeds=None) -> MCTSResult:
    return mcts_search(key, trace, pairs, archive, failure_feats,
                       hint_order, H, cfg, weights, coin=coin,
                       seeds=seeds)


def make_parallel_mcts(mesh, H: int, cfg: MCTSConfig = MCTSConfig(),
                       weights: ScoreWeights = ScoreWeights()):
    """Root-parallel MCTS over a device mesh: each device grows an
    independent tree from a folded key (rollout batches keep the MXU busy
    per device), then the per-device bests are ``all_gather``-ed and the
    argmax is replicated — same collective shape as the GA islands'
    global-best agreement (parallel/islands.py). Works on flat (``i``) and
    hybrid host x chip (``h x i``) meshes alike: the key is folded with
    every mesh axis and the gather runs axis by axis."""
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def _local(key, trace, pairs, archive, failure_feats, hint_order,
               coin, seeds):
        for ax in axes:
            key = jax.random.fold_in(key, jax.lax.axis_index(ax))
        res = mcts_search(key, trace, pairs, archive, failure_feats,
                          hint_order, H, cfg, weights, coin=coin,
                          seeds=seeds)
        all_fit, all_d, all_f = (res.best_fitness, res.best_delays,
                                 res.best_faults)
        for ax in reversed(axes):
            all_fit = jax.lax.all_gather(all_fit, ax)
            all_d = jax.lax.all_gather(all_d, ax)
            all_f = jax.lax.all_gather(all_f, ax)
        all_fit = all_fit.reshape(-1)
        all_d = all_d.reshape(-1, all_d.shape[-1])
        all_f = all_f.reshape(-1, all_f.shape[-1])
        g = jnp.argmax(all_fit)
        return all_fit[g], all_d[g], all_f[g]

    def make_sharded(trace_spec):
        return compat_shard_map(
            _local,
            mesh=mesh,
            in_specs=(P(), trace_spec, P(), P(), P(), P(), P(), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )

    fault_trace_spec, nofault_trace_spec = replicated_trace_specs()
    sharded_fault = make_sharded(fault_trace_spec)
    sharded_nofault = make_sharded(nofault_trace_spec)

    @jax.jit
    def run(key, trace: TraceArrays, pairs, archive, failure_feats,
            hint_order, coin=None, seeds=None):
        if trace.hint_ids.ndim == 1:
            trace = jax.tree.map(lambda x: x[None], trace)
        if seeds is None:  # static absence -> 0-row array, one code path
            seeds = jnp.zeros((0, H), jnp.float32)
        had_coin = coin is not None
        trace = normalize_fault_trace(trace, coin)
        if not had_coin:
            if cfg.max_fault > 0:
                # mcts_search would raise the same error, but only after
                # the ones-substitution below had masked it — check first
                raise ValueError(
                    "fault search is enabled (max_fault > 0) but no "
                    "fault coin was passed; build one with "
                    "trace_encoding.fault_coin(seed, H)"
                )
            # coin >= 1 never beats a fault probability in [0, 1]
            coin = jnp.ones((H,), jnp.float32)
            return sharded_nofault(key, trace, pairs, archive,
                                   failure_feats, hint_order, coin, seeds)
        return sharded_fault(key, trace, pairs, archive, failure_feats,
                             hint_order, coin, seeds)

    return run
