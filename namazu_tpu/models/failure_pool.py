"""Durable cross-run failure-signature pool.

The north-star A/B's weakest batches share one root cause (round-4
measurements, RESULTS.md): phase B trains on whatever failures phase A
happened to record — often one or two — and a search exploiting so few
signatures overfits their noise. The reference has no answer to this
(each experiment's history dir is an island; ``nmz run`` never looks
outside it, cli/run.go:171-248). This pool is the cross-experiment
memory: every ingested failure's *realized* encoding (the signature the
search chases) plus its demonstration seed table is written to a shared
directory, content-addressed; any later ingest — same storage, another
batch, another process — folds the pooled signatures into its failure
archive and seed set before evolving.

Layout: one ``<digest>.npz`` per distinct signature (write-to-tmp +
rename, so concurrent runs and sidecar requests never see a torn file;
identical signatures land on the same name, making the pool its own
dedupe). Entries are keyed by the content digest of the masked encoded
trace, so re-ingesting the same stored run is a no-op.

Entries stamp the hint space and bucket count; a pool written by a
different build or config is skipped entry-by-entry, never trusted.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import numpy as np

from namazu_tpu.ops.trace_encoding import HINT_SPACE, EncodedTrace
from namazu_tpu.utils.log import get_logger

log = get_logger("models.failure_pool")

#: newest entries loaded per ingest — matches the failure archive's ring
#: capacity (SearchConfig.failure_size); loading more would only evict
#: older signatures from the very archive the pool exists to fill
MAX_LOAD = 64


class PoolEntry(NamedTuple):
    digest: str
    realized: EncodedTrace  # release-time view (archive embedding)
    arrival: EncodedTrace  # arrival view (reference fallback)
    seed: Optional[np.ndarray]  # f32[H] demonstration table, or None


def trace_digest(enc: EncodedTrace) -> str:
    """Content digest of the masked trace: the hint/entity SEQUENCE,
    timing excluded. Absolute arrival timestamps differ on every run,
    so a timing-sensitive digest counts failing RUNS, not failure
    MODES — under it the novelty anneal's ``distinct_failure_signatures``
    progress variable just mirrors run count and the anneal fires on
    noise. Two runs that interleaved the same events in the same order
    are one signature. Padding is excluded so the same run hashes
    identically under different encode lengths."""
    m = enc.mask
    h = hashlib.sha256()
    h.update(enc.hint_ids[m].tobytes())
    h.update(enc.entity_ids[m].tobytes())
    return h.hexdigest()[:32]


def pool_add(pool_dir: str, realized: EncodedTrace, arrival: EncodedTrace,
             seed: Optional[np.ndarray], H: int) -> str:
    """Persist one failure signature; returns its digest. Idempotent —
    an existing entry with the same digest is left untouched."""
    return pool_put(pool_dir, realized, arrival, seed, H)[0]


def pool_put(pool_dir: str, realized: EncodedTrace, arrival: EncodedTrace,
             seed: Optional[np.ndarray], H: int) -> Tuple[str, bool]:
    """:func:`pool_add` that also reports whether the entry was NEW
    (False = content-keyed dedupe hit). The knowledge service counts
    dedupe hits per push; concurrent writers racing on one signature
    both land on the same filename via atomic rename, so the final pool
    holds exactly one entry either way."""
    digest = trace_digest(realized)
    os.makedirs(pool_dir, exist_ok=True)
    path = os.path.join(pool_dir, f"{digest}.npz")
    if os.path.exists(path):
        return digest, False
    payload = {
        "hint_space": np.asarray(HINT_SPACE),
        "H": np.asarray(H),
        "hint_ids": realized.hint_ids,
        "entity_ids": realized.entity_ids,
        "released": realized.arrival,  # the realized view's time vector
        "arrival": arrival.arrival,
        "mask": realized.mask,
        "faultable": realized.faultable,
    }
    if seed is not None:
        payload["seed"] = np.asarray(seed, np.float32)
    fd, tmp = tempfile.mkstemp(dir=pool_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return digest, True


def pool_load(pool_dir: str, H: int,
              exclude: Optional[Set[str]] = None,
              max_entries: int = MAX_LOAD) -> List[PoolEntry]:
    """Newest-first pooled signatures compatible with this build/config.

    Entries from another hint space or bucket count are skipped with one
    aggregate warning (same contract as the checkpoint loader,
    models/search.py load): training on them would chase signatures in
    a different feature space.
    """
    exclude = exclude or set()
    if not os.path.isdir(pool_dir):
        return []
    files = []
    for name in os.listdir(pool_dir):
        if not name.endswith(".npz"):
            continue
        if name[:-4] in exclude:  # fast path: current-format filenames
            continue
        path = os.path.join(pool_dir, name)
        try:
            files.append((os.path.getmtime(path), path))
        except OSError:
            continue
    files.sort(reverse=True)  # newest first
    entries: List[PoolEntry] = []
    seen_digests: Set[str] = set()
    incompatible = 0
    for _, path in files:
        if len(entries) >= max_entries:
            break
        try:
            with np.load(path) as z:
                if (str(z["hint_space"]) != HINT_SPACE
                        or int(z["H"]) != H):
                    incompatible += 1
                    continue
                ids = z["hint_ids"]
                ents = z["entity_ids"]
                mask = z["mask"]
                fb = z["faultable"]
                realized = EncodedTrace(ids, ents, z["released"], mask,
                                        faultable=fb)
                # digest recomputed from CONTENT, never trusted from the
                # filename: entries written before a digest-format change
                # keep their old names, and a filename digest would
                # bypass every downstream dedupe keyed on the current
                # format (duplicate surrogate positives, burned ring
                # slots) — recomputing re-keys old pools transparently
                digest = trace_digest(realized)
                if digest in exclude or digest in seen_digests:
                    continue
                seen_digests.add(digest)
                entries.append(PoolEntry(
                    digest=digest,
                    realized=realized,
                    arrival=EncodedTrace(ids, ents, z["arrival"], mask,
                                         faultable=fb),
                    seed=np.array(z["seed"]) if "seed" in z else None,
                ))
        except Exception:
            log.exception("unreadable pool entry %s; skipping", path)
    if incompatible:
        log.warning(
            "%d pooled signature(s) from another hint space or bucket "
            "count were skipped (this build: %s, H=%d)",
            incompatible, HINT_SPACE, H)
    return entries


def pool_size(pool_dir: str) -> int:
    """Number of stored signatures (cheap: directory listing)."""
    if not os.path.isdir(pool_dir):
        return 0
    return sum(1 for n in os.listdir(pool_dir) if n.endswith(".npz"))


# -- wire form (knowledge service, doc/knowledge.md) ---------------------

def entry_to_jsonable(realized: EncodedTrace, arrival: EncodedTrace,
                      seed: Optional[np.ndarray], H: int) -> Dict[str, Any]:
    """One failure signature as a JSON-able dict — the ``pool_push``
    wire form. Only the masked prefix travels (padding re-grows on the
    receiving side and is digest-neutral anyway)."""
    m = realized.mask
    d: Dict[str, Any] = {
        "hint_space": HINT_SPACE,
        "H": int(H),
        "hint_ids": realized.hint_ids[m].tolist(),
        "entity_ids": realized.entity_ids[m].tolist(),
        "released": realized.arrival[m].tolist(),
        "arrival": arrival.arrival[m].tolist(),
        "faultable": realized.faultable[m].tolist(),
    }
    if seed is not None:
        d["seed"] = np.asarray(seed, np.float32).tolist()
    return d


def entry_from_jsonable(d: Dict[str, Any]) -> Tuple[EncodedTrace,
                                                    EncodedTrace,
                                                    Optional[np.ndarray],
                                                    int]:
    """Inverse of :func:`entry_to_jsonable`: ``(realized, arrival, seed,
    H)``. Raises on malformed/mismatched payloads — the caller skips the
    entry (wire peers are never trusted blindly, same contract as
    :func:`pool_load`)."""
    if d.get("hint_space") != HINT_SPACE:
        raise ValueError(
            f"entry from hint space {d.get('hint_space')!r} "
            f"(this build: {HINT_SPACE!r})")
    hint_ids = np.asarray(d["hint_ids"], np.int32)
    n = len(hint_ids)
    entity_ids = np.asarray(d["entity_ids"], np.int32)
    released = np.asarray(d["released"], np.float32)
    arrival_t = np.asarray(d["arrival"], np.float32)
    faultable = np.asarray(d.get("faultable", np.ones(n)), bool)
    if not (len(entity_ids) == len(released) == len(arrival_t)
            == len(faultable) == n):
        # every array, faultable included: a mismatched length would be
        # persisted into the shared pool and poison every later pull
        # (the re-serialization indexes faultable by the mask)
        raise ValueError("entry arrays disagree on length")
    mask = np.ones((n,), bool)
    realized = EncodedTrace(hint_ids, entity_ids, released, mask,
                            faultable=faultable)
    arrival = EncodedTrace(hint_ids, entity_ids, arrival_t, mask,
                           faultable=faultable)
    seed = (np.asarray(d["seed"], np.float32)
            if d.get("seed") is not None else None)
    return realized, arrival, seed, int(d["H"])


def entries_to_pool_entries(dicts: Sequence[Dict[str, Any]], H: int
                            ) -> List[PoolEntry]:
    """Decode pulled wire entries into :class:`PoolEntry` objects,
    skipping (with one aggregate warning) anything malformed or from
    another hint space / bucket count."""
    out: List[PoolEntry] = []
    skipped = 0
    for d in dicts:
        try:
            realized, arrival, seed, entry_h = entry_from_jsonable(d)
            if entry_h != H:
                skipped += 1
                continue
            out.append(PoolEntry(digest=trace_digest(realized),
                                 realized=realized, arrival=arrival,
                                 seed=seed))
        except Exception:
            skipped += 1
    if skipped:
        log.warning("%d pulled knowledge entr(ies) were malformed or "
                    "from another hint space/bucket count; skipped",
                    skipped)
    return out


# -- integrity (nmz-tpu tools fsck over a pool dir) ----------------------

def pool_fsck(pool_dir: str, repair: bool = False) -> Dict[str, Any]:
    """Integrity report over a shared pool directory: stray atomic-write
    temps (a hard-killed writer's leftovers; ``repair`` sweeps them) and
    unreadable/torn ``.npz`` entries (``repair`` quarantines them with a
    ``.bad`` suffix so loaders stop re-parsing them). Content-keyed
    entries are self-deduplicating, so there is no cross-entry state to
    reconcile."""
    report: Dict[str, Any] = {
        "pool_dir": os.path.abspath(pool_dir),
        "entries": 0,
        "tmp_artifacts": [],
        "unreadable_entries": [],
        "repaired": [],
    }
    if not os.path.isdir(pool_dir):
        return report
    for name in sorted(os.listdir(pool_dir)):
        path = os.path.join(pool_dir, name)
        if name.endswith(".tmp"):
            report["tmp_artifacts"].append(name)
            if repair:
                try:
                    os.unlink(path)
                    report["repaired"].append(name)
                except OSError:
                    pass
            continue
        if not name.endswith(".npz"):
            continue
        try:
            with np.load(path) as z:
                _ = z["hint_ids"]  # force a header + member read
            report["entries"] += 1
        except Exception:
            report["unreadable_entries"].append(name)
            if repair:
                try:
                    os.replace(path, path + ".bad")
                    report["repaired"].append(name)
                except OSError:
                    pass
    return report
