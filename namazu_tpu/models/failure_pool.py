"""Durable cross-run failure-signature pool.

The north-star A/B's weakest batches share one root cause (round-4
measurements, RESULTS.md): phase B trains on whatever failures phase A
happened to record — often one or two — and a search exploiting so few
signatures overfits their noise. The reference has no answer to this
(each experiment's history dir is an island; ``nmz run`` never looks
outside it, cli/run.go:171-248). This pool is the cross-experiment
memory: every ingested failure's *realized* encoding (the signature the
search chases) plus its demonstration seed table is written to a shared
directory, content-addressed; any later ingest — same storage, another
batch, another process — folds the pooled signatures into its failure
archive and seed set before evolving.

Layout: one ``<digest>.npz`` per distinct signature (write-to-tmp +
rename, so concurrent runs and sidecar requests never see a torn file;
identical signatures land on the same name, making the pool its own
dedupe). Entries are keyed by the content digest of the masked encoded
trace, so re-ingesting the same stored run is a no-op.

Entries stamp the hint space and bucket count; a pool written by a
different build or config is skipped entry-by-entry, never trusted.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import List, NamedTuple, Optional, Sequence, Set

import numpy as np

from namazu_tpu.ops.trace_encoding import HINT_SPACE, EncodedTrace
from namazu_tpu.utils.log import get_logger

log = get_logger("models.failure_pool")

#: newest entries loaded per ingest — matches the failure archive's ring
#: capacity (SearchConfig.failure_size); loading more would only evict
#: older signatures from the very archive the pool exists to fill
MAX_LOAD = 64


class PoolEntry(NamedTuple):
    digest: str
    realized: EncodedTrace  # release-time view (archive embedding)
    arrival: EncodedTrace  # arrival view (reference fallback)
    seed: Optional[np.ndarray]  # f32[H] demonstration table, or None


def trace_digest(enc: EncodedTrace) -> str:
    """Content digest of the masked trace: the hint/entity SEQUENCE,
    timing excluded. Absolute arrival timestamps differ on every run,
    so a timing-sensitive digest counts failing RUNS, not failure
    MODES — under it the novelty anneal's ``distinct_failure_signatures``
    progress variable just mirrors run count and the anneal fires on
    noise. Two runs that interleaved the same events in the same order
    are one signature. Padding is excluded so the same run hashes
    identically under different encode lengths."""
    m = enc.mask
    h = hashlib.sha256()
    h.update(enc.hint_ids[m].tobytes())
    h.update(enc.entity_ids[m].tobytes())
    return h.hexdigest()[:32]


def pool_add(pool_dir: str, realized: EncodedTrace, arrival: EncodedTrace,
             seed: Optional[np.ndarray], H: int) -> str:
    """Persist one failure signature; returns its digest. Idempotent —
    an existing entry with the same digest is left untouched."""
    digest = trace_digest(realized)
    os.makedirs(pool_dir, exist_ok=True)
    path = os.path.join(pool_dir, f"{digest}.npz")
    if os.path.exists(path):
        return digest
    payload = {
        "hint_space": np.asarray(HINT_SPACE),
        "H": np.asarray(H),
        "hint_ids": realized.hint_ids,
        "entity_ids": realized.entity_ids,
        "released": realized.arrival,  # the realized view's time vector
        "arrival": arrival.arrival,
        "mask": realized.mask,
        "faultable": realized.faultable,
    }
    if seed is not None:
        payload["seed"] = np.asarray(seed, np.float32)
    fd, tmp = tempfile.mkstemp(dir=pool_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return digest


def pool_load(pool_dir: str, H: int,
              exclude: Optional[Set[str]] = None,
              max_entries: int = MAX_LOAD) -> List[PoolEntry]:
    """Newest-first pooled signatures compatible with this build/config.

    Entries from another hint space or bucket count are skipped with one
    aggregate warning (same contract as the checkpoint loader,
    models/search.py load): training on them would chase signatures in
    a different feature space.
    """
    exclude = exclude or set()
    if not os.path.isdir(pool_dir):
        return []
    files = []
    for name in os.listdir(pool_dir):
        if not name.endswith(".npz"):
            continue
        if name[:-4] in exclude:  # fast path: current-format filenames
            continue
        path = os.path.join(pool_dir, name)
        try:
            files.append((os.path.getmtime(path), path))
        except OSError:
            continue
    files.sort(reverse=True)  # newest first
    entries: List[PoolEntry] = []
    seen_digests: Set[str] = set()
    incompatible = 0
    for _, path in files:
        if len(entries) >= max_entries:
            break
        try:
            with np.load(path) as z:
                if (str(z["hint_space"]) != HINT_SPACE
                        or int(z["H"]) != H):
                    incompatible += 1
                    continue
                ids = z["hint_ids"]
                ents = z["entity_ids"]
                mask = z["mask"]
                fb = z["faultable"]
                realized = EncodedTrace(ids, ents, z["released"], mask,
                                        faultable=fb)
                # digest recomputed from CONTENT, never trusted from the
                # filename: entries written before a digest-format change
                # keep their old names, and a filename digest would
                # bypass every downstream dedupe keyed on the current
                # format (duplicate surrogate positives, burned ring
                # slots) — recomputing re-keys old pools transparently
                digest = trace_digest(realized)
                if digest in exclude or digest in seen_digests:
                    continue
                seen_digests.add(digest)
                entries.append(PoolEntry(
                    digest=digest,
                    realized=realized,
                    arrival=EncodedTrace(ids, ents, z["arrival"], mask,
                                         faultable=fb),
                    seed=np.array(z["seed"]) if "seed" in z else None,
                ))
        except Exception:
            log.exception("unreadable pool entry %s; skipping", path)
    if incompatible:
        log.warning(
            "%d pooled signature(s) from another hint space or bucket "
            "count were skipped (this build: %s, H=%d)",
            incompatible, HINT_SPACE, H)
    return entries


def pool_size(pool_dir: str) -> int:
    """Number of stored signatures (cheap: directory listing)."""
    if not os.path.isdir(pool_dir):
        return 0
    return sum(1 for n in os.listdir(pool_dir) if n.endswith(".npz"))
