"""Genetic algorithm over schedule genomes — fully jittable.

One generation (tournament selection -> uniform crossover -> gaussian/flip
mutation -> elitism) is a pure function of (population, fitness, PRNG key),
so it vmaps/shard_maps cleanly: per-device islands evolve independently and
exchange elites over ICI (namazu_tpu/parallel/islands.py).

Genome layout: ``delays f32[P,H]`` in [0, max_delay], ``faults f32[P,H]``
in [0, max_fault].
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class GAConfig(NamedTuple):
    max_delay: float = 0.1  # seconds; genome delay range
    max_fault: float = 0.0  # per-hint fault probability cap (0 = off)
    tournament_size: int = 3
    crossover_rate: float = 0.6
    mutation_sigma: float = 0.01  # gaussian sigma on delays, seconds
    mutation_rate: float = 0.15  # per-gene mutation probability
    elite_frac: float = 0.0625  # top fraction copied through unchanged


class Population(NamedTuple):
    delays: jax.Array  # f32[P, H]
    faults: jax.Array  # f32[P, H]


def init_population(key: jax.Array, P: int, H: int,
                    cfg: GAConfig) -> Population:
    kd, kf = jax.random.split(key)
    delays = jax.random.uniform(kd, (P, H), jnp.float32, 0.0, cfg.max_delay)
    faults = jax.random.uniform(kf, (P, H), jnp.float32, 0.0, cfg.max_fault)
    return Population(delays, faults)


def tournament_select(key: jax.Array, fitness: jax.Array, n: int,
                      k: int) -> jax.Array:
    """n winners of size-k tournaments -> indices int32[n]."""
    P = fitness.shape[0]
    cand = jax.random.randint(key, (n, k), 0, P)
    return cand[jnp.arange(n), jnp.argmax(fitness[cand], axis=-1)]


def _uniform_crossover(key: jax.Array, a: jax.Array, b: jax.Array,
                       rate: float) -> jax.Array:
    km, kr = jax.random.split(key)
    do = jax.random.uniform(kr, (a.shape[0], 1)) < rate
    mask = jax.random.bernoulli(km, 0.5, a.shape)
    child = jnp.where(mask, a, b)
    return jnp.where(do, child, a)


def _mutate(key: jax.Array, x: jax.Array, sigma: float, rate: float,
            lo: float, hi: float, rate_scale=None) -> jax.Array:
    """``rate_scale`` (f32[H], optional) multiplies the per-gene
    mutation probability — the guidance plane's mutation bias
    (doc/search.md): buckets participating in uncovered/one-sided
    ordering relations mutate more often. ``None`` (and all-ones) is
    bit-identical to the unbiased kernel: ``bernoulli(p)`` is
    ``uniform < p`` either way, and the draw count is unchanged."""
    kn, km = jax.random.split(key)
    noise = jax.random.normal(kn, x.shape) * sigma
    p = rate if rate_scale is None \
        else jnp.clip(rate * rate_scale, 0.0, 1.0)
    mask = jax.random.bernoulli(km, p, x.shape)
    return jnp.clip(x + jnp.where(mask, noise, 0.0), lo, hi)


@functools.partial(jax.jit, static_argnames=("cfg",))
def ga_generation(key: jax.Array, pop: Population, fitness: jax.Array,
                  cfg: GAConfig, delay_bias=None) -> Population:
    """Evolve one generation. Elites (top elite_frac by fitness) survive
    unchanged in the first slots; the rest are tournament offspring.

    ``delay_bias`` (f32[H], optional) scales the DELAY half's per-gene
    mutation rate (clipped to [0, 1]) — coverage guidance concentrating
    perturbation on the buckets whose relations are untested. The fault
    half is untouched: fault flips change which events EXIST, not their
    order, so ordering-coverage bias has nothing to say about them.

    Draw-order contract (the search plane's analogue of
    ``ScheduledQueue.put_many``'s): one generation consumes exactly the
    splits/draws derived from its ``key``, and the per-generation key is
    always ``fold_in(base_key, gen)`` — whether generations run one
    jitted dispatch at a time or fused in a ``lax.scan``
    (parallel/islands.py). That is what makes the fused loop bit-exact
    with the stepwise loop (tests/test_fused_loop.py)."""
    P, H = pop.delays.shape
    n_elite = max(1, int(P * cfg.elite_frac))
    ks = jax.random.split(key, 6)

    elite_idx = jax.lax.top_k(fitness, n_elite)[1]

    pa = tournament_select(ks[0], fitness, P, cfg.tournament_size)
    pb = tournament_select(ks[1], fitness, P, cfg.tournament_size)
    child_d = _uniform_crossover(ks[2], pop.delays[pa], pop.delays[pb],
                                 cfg.crossover_rate)
    child_f = _uniform_crossover(ks[2], pop.faults[pa], pop.faults[pb],
                                 cfg.crossover_rate)
    child_d = _mutate(ks[3], child_d, cfg.mutation_sigma, cfg.mutation_rate,
                      0.0, cfg.max_delay, rate_scale=delay_bias)
    child_f = _mutate(ks[4], child_f, cfg.mutation_sigma * 0.5,
                      cfg.mutation_rate, 0.0, cfg.max_fault)

    # overwrite the first n_elite children with the elites
    child_d = child_d.at[:n_elite].set(pop.delays[elite_idx])
    child_f = child_f.at[:n_elite].set(pop.faults[elite_idx])
    return Population(child_d, child_f)
