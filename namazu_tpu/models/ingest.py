"""History ingest: stored experiment runs -> search-plane state.

Shared by the in-process policy (policy/tpu.py) and the persistent
search sidecar (namazu_tpu/sidecar.py): both must featurize the same
history the same way — arrival-anchored references, realized-release
embeddings, failure-derived demonstration seeds, hint-space guard — or
a schedule trained in one home would not replay in the other.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from namazu_tpu import obs
from namazu_tpu.ops import trace_encoding as te
from namazu_tpu.signal.base import HINT_SPACE
from namazu_tpu.utils.log import get_logger

log = get_logger("models.ingest")

#: most recent runs whose labeled features feed the shared surrogate
#: per ingest — bounds the extra featurize cost (and the wire payload)
#: on long histories; older runs were already pushed by earlier ingests
MAX_EXAMPLE_PUSH = 64


def _push_surrogate_examples(client, search, encoded) -> None:
    """Stream (digest, features, reproduced?) for the most recent runs
    to the knowledge service's shared surrogate. Runs AFTER
    ``set_occupied_buckets``: features only pool between searches with
    the same precedence-pair sample, and the pairs are final once the
    occupied buckets are set — the fingerprint scopes the server-side
    store (knowledge/service.py). Best-effort: surrogate sharing is an
    accelerator, never a dependency."""
    from namazu_tpu.knowledge.client import pairs_fingerprint
    from namazu_tpu.models.failure_pool import trace_digest

    try:
        examples = []
        for enc, enc_rt, ok, _seed in encoded[-MAX_EXAMPLE_PUSH:]:
            feats = search._feats_of(enc_rt)
            if search.guidance_feats is not None:
                # guided campaigns train on [precedence | DAG-shape];
                # the widened K keys a separate service-side store, so
                # the walling holds without any new wire field
                feats = np.concatenate(
                    [feats, search._guidance_feats_of(enc_rt, enc)])
            examples.append({
                "digest": trace_digest(enc_rt),
                "feats": [float(x) for x in feats],
                "label": 0.0 if ok else 1.0,
            })
        client.push(examples=examples,
                    pairs_fp=pairs_fingerprint(search.pairs))
    except Exception:
        log.exception("could not push surrogate examples")


class IngestParams(NamedTuple):
    H: int = te.DEFAULT_H
    L: int = 0  # explicit trace-length cap; 0 = policy defaults
    release_mode: str = "delay"  # "delay" | "reorder"
    reference_mode: str = "recent"  # "recent" | "envelope"
    max_interval: float = 0.1  # seed-table clip (seconds)
    max_reference_traces: int = 4
    max_seed_genomes: int = 16
    # order mode scores dense (whole-trace lexsort), so uncapped encodes
    # would materialize [population, L] intermediates per generation
    order_mode_max_l: int = 4096
    # shared failure-signature pool directory ("" = off): every ingested
    # failure is persisted there, and pooled signatures from OTHER runs/
    # batches/experiments are folded into the failure archive + seeds —
    # the cross-batch memory that keeps a search from training on the
    # 1-2 failures its own phase A happened to record
    # (models/failure_pool.py)
    failure_pool: str = ""
    # knowledge-service address "host:port" ("" = off): the remote
    # backend behind the same pool interface (doc/knowledge.md) —
    # failures stream to the fleet-global pool and pooled signatures
    # from OTHER campaigns/hosts fold back in, with graceful degradation
    # to the local pool (or none) on outage. tenant/scenario identify
    # the pushing campaign and the experiment fingerprint for
    # warm-start keying and the shared surrogate's feature-space scoping
    knowledge: str = ""
    knowledge_tenant: str = ""
    knowledge_scenario: str = ""
    # causality guidance (doc/search.md): rebuild the per-campaign
    # relation CoverageMap from the stored history on every ingest (a
    # pure function of the recorded runs — no extra persistence to
    # corrupt), warm-start its frontier from the knowledge service's
    # pooled coverage, and push the campaign's own bits back. 0 width/
    # window = the guidance defaults.
    guidance: bool = False
    guidance_width: int = 0
    guidance_window: int = 0


def failure_seed(trace, H: int, max_interval: float):
    """Per-bucket delay table replaying this failure's injected delays:
    for the first released event of each bucket, ``release - arrival``
    IS the delay the recording policy injected on it (absolute times —
    no anchor needed). Replayed against similar arrivals, the table
    re-enacts the failure's interleaving up to the system's reactions;
    it seeds the search as a demonstration (models/search.py
    seed_population)."""
    seed = np.zeros((H,), np.float32)
    seen = set()
    got = False
    for a in trace:
        arr = getattr(a, "event_arrived", None)
        rel = a.triggered_time
        if not arr or not rel:
            continue
        hint = getattr(a, "event_hint", "") or \
            f"{a.event_class or a.class_name()}:{a.entity_id}"
        b = te.hint_bucket(hint, H)
        if b in seen:
            continue
        seen.add(b)
        seed[b] = min(max(rel - arr, 0.0), max_interval)
        got = True
    return seed if got else None


def ingest_history(search, storage, p: IngestParams) -> List:
    """Feed stored traces into the search's archives; return the
    reference traces to evolve against.

    References are the most recent SUCCESSFUL runs (padded with failures
    only when no success exists yet): the counterfactual asks "what
    would delaying bucket X do to the interleaving the next run will
    naturally produce", so it must be anchored on arrivals close to what
    an ordinary run records. The failure traces instead supply the
    *target* features through the failure archive (bug-affinity term) —
    embedded at their REALIZED release times, where a delay-induced
    failure's signature actually lives (te.encode_trace docstring).
    """
    if storage is None:
        return []
    try:
        n = storage.nr_stored_histories()
    except Exception:
        return []
    # causality guidance: wire the map BEFORE any archive write so the
    # DAG-shape feature fragments land slot-aligned with the archive.
    # ``fresh``: every ingest re-feeds the WHOLE stored history, so the
    # map rebuilds from scratch each time — a persistent (sidecar)
    # search serving repeated requests must not double-observe
    gmap = None
    if p.guidance:
        gmap = search.enable_guidance(p.guidance_width or None,
                                      p.guidance_window or None,
                                      fresh=True)
    encoded = []
    skipped_unstamped = 0
    for i in range(n):
        try:
            trace = storage.get_stored_history(i)
            ok = storage.is_successful(i)
        except Exception:
            continue
        # runs recorded under a different replay-hint format hash into a
        # different bucket space — training on them would deliver
        # arbitrary delays under a "searched schedule" log. Absent
        # stamps default to "content-v1", the same convention the
        # checkpoint loader uses (te.checkpoint_hint_space): every
        # recording made by a stamping build carries the tag
        # (cli/run_cmd.py).
        try:
            stamp = ((storage.get_metadata(i) or {})
                     .get("hint_space", "content-v1"))
        except Exception:
            stamp = "content-v1"
        if stamp != HINT_SPACE:
            skipped_unstamped += 1
            continue
        if p.L > 0:
            cap: Optional[int] = p.L
        elif p.release_mode == "reorder":
            cap = p.order_mode_max_l
        else:
            cap = None  # delay mode scores long traces blockwise
        # two views of every run, one encode pass: arrival-anchored =
        # counterfactual reference; realized = archive embedding
        enc, enc_rt = te.encode_trace_views(trace, L=cap, H=p.H)
        if enc.truncated:
            log.warning(
                "trace %d truncated: %d events beyond the L=%d cap were "
                "dropped from scoring (%s)", i, enc.truncated, cap,
                "configured trace_length" if p.L > 0
                else "order-mode memory bound")
        seed = None if ok else failure_seed(trace, p.H, p.max_interval)
        encoded.append((enc, enc_rt, ok, seed))
    if skipped_unstamped:
        log.warning(
            "%d stored run(s) recorded in another hint space were "
            "excluded from search ingest (this build: %s); re-record "
            "under the current build to train on them",
            skipped_unstamped, HINT_SPACE)
    # cross-batch failure pool: persist this storage's failures, then
    # pull in signatures recorded by OTHER runs/batches (dedup by
    # content digest — re-ingesting our own failures is a no-op). With a
    # knowledge service configured the same flow additionally rides the
    # fleet-global pool: push own failures up, pull the fleet's down —
    # and an outage silently degrades to the local-only path (the
    # client logs one warning; a campaign never fails on knowledge)
    pooled = []
    client = None
    if p.knowledge:
        from namazu_tpu.knowledge import shared_client

        client = shared_client(p.knowledge, tenant=p.knowledge_tenant,
                               scenario=p.knowledge_scenario)
    if p.failure_pool or client is not None:
        from namazu_tpu.models.failure_pool import (
            entry_to_jsonable,
            pool_add,
            pool_load,
            trace_digest,
        )

        own = set()
        push_entries = []
        for enc, enc_rt, ok, seed in encoded:
            if ok:
                continue
            try:
                own.add(trace_digest(enc_rt))
                if p.failure_pool:
                    pool_add(p.failure_pool, enc_rt, enc, seed, p.H)
                if client is not None:
                    push_entries.append(
                        entry_to_jsonable(enc_rt, enc, seed, p.H))
            except Exception:
                log.exception("could not pool failure signature")
        if p.failure_pool:
            pooled = pool_load(p.failure_pool, p.H, exclude=own)
        if client is not None:
            client.push(entries=push_entries)  # None on outage: fine
            have = own | {e.digest for e in pooled}
            # the coverage-frontier warm-start piggybacks on the entry
            # pull (one round trip): relations the FLEET already
            # exercised are not this campaign's frontier. An outage
            # returns None — local-only coverage, never a failed
            # ingest (the cardinal knowledge rule).
            space = (None if gmap is None
                     else {"H": gmap.H, "w": gmap.width,
                           "win": gmap.window})
            remote = client.pull(p.H, exclude=have,
                                 coverage_space=space)
            if remote is not None:
                r_entries, _table = remote[0], remote[1]
                # the cold-run warm-start: fleet signatures this search
                # has never seen are about to enter its archives
                fresh = sum(
                    1 for e in r_entries
                    if not search.has_failure_signature(e.digest))
                obs.knowledge_warmstart("archive", fresh)
                pooled = pooled + r_entries
                if gmap is not None:
                    obs.knowledge_warmstart(
                        "coverage", gmap.merge_bits(remote[2]))
        if pooled:
            log.info("folding %d pooled failure signature(s) into the "
                     "search (pool %s%s)", len(pooled),
                     p.failure_pool or "-",
                     f", knowledge {p.knowledge}" if p.knowledge else "")
    # concentrate the feature pairs on the buckets the experiment
    # actually produces BEFORE embedding anything (a pair change clears
    # the archives; the loop below repopulates them in full)
    occupied = sorted(
        {int(b) for enc, _, _, _ in encoded
         for b in enc.hint_ids[enc.mask]}
        | {int(b) for e in pooled
           for b in e.realized.hint_ids[e.realized.mask]})
    search.set_occupied_buckets(occupied)
    seeds = [s for _, _, ok, s in encoded if not ok and s is not None]
    # most recent failures first: when seeds outnumber slots the
    # freshest demonstrations win; pooled demonstrations (already
    # newest-first) fill the remaining slots
    seeds = seeds[::-1] + [e.seed for e in pooled if e.seed is not None]
    if seeds:
        search.seed_population(seeds[: p.max_seed_genomes])
    if gmap is not None:
        # fold every known run's realized ordering into the coverage
        # frontier — pooled entries too, and BEFORE the archive-dedupe
        # skip below: a checkpoint-restored search may already hold a
        # signature whose relations this (fresh) map has never seen
        from namazu_tpu.guidance import bucket_sequence_from_encoded

        for e in pooled:
            gmap.observe(bucket_sequence_from_encoded(e.realized))
        for _enc, enc_rt, _ok, _seed in encoded:
            gmap.observe(bucket_sequence_from_encoded(enc_rt))
    for e in pooled:
        # same treatment as an in-storage failure: archive embedding
        # (novelty + surrogate positive) and failure-signature target —
        # once per distinct signature (re-requests must not duplicate
        # surrogate positives or evict diverse runs from the archive).
        # Pooled entries go in FIRST: the failure archive is a ring, and
        # adding them after the storage's own failures could wrap around
        # and evict exactly the signatures most relevant to THIS
        # experiment — the storage's own must always survive a full pool
        if search.has_failure_signature(e.digest):
            continue
        search.add_executed_trace(e.realized, reproduced=True,
                                  arrival=e.arrival)
        search.add_failure_trace(e.realized)
    failures, successes = [], []
    for enc, enc_rt, ok, _ in encoded:
        # "failure" = the run reproduced the bug (validate failed); the
        # label feeds the surrogate's training set
        search.add_executed_trace(enc_rt, reproduced=not ok, arrival=enc)
        if not ok:
            search.add_failure_trace(enc_rt)
            failures.append(enc)
        else:
            successes.append(enc)
    if gmap is not None:
        scenario = p.knowledge_scenario or "local"
        obs.relation_coverage(scenario, gmap.covered(), gmap.width,
                              gmap.one_sided_count())
        if client is not None:
            # publish the campaign's frontier so the NEXT cold campaign
            # of this scenario warm-starts past it; best-effort like
            # every knowledge op
            client.push(coverage={
                "H": gmap.H, "w": gmap.width, "win": gmap.window,
                "bits": gmap.bits_list(),
            })
    if client is not None and encoded:
        _push_surrogate_examples(client, search, encoded)
    if p.reference_mode == "envelope" and successes:
        return [te.envelope_trace(successes)]
    pool = successes if successes else failures
    if not pool and pooled:
        # a fresh storage with no runs of its own can still evolve
        # against pooled signatures' natural arrivals
        pool = [e.arrival for e in reversed(pooled)]
    return pool[::-1][: p.max_reference_traces]
