"""Virtual-clock plane: the epoch page + per-run activation.

The TimeSource abstraction (utils/timesource.py) fast-forwards the
*in-process* clock; this package is everything needed to extend that
clock across the process boundary to the testee
(doc/performance.md "Virtual clock"):

* :class:`EpochPage` — a tiny mmap'd shared-memory file the
  orchestrator writes and every interposed process reads. It carries
  the virtual offset under a seqlock plus one slot per interposed
  THREAD recording its park state: ``deadline_ns == 0`` means the
  thread is running (doing real work outside a hooked wait — the
  pinning rule's cross-process face), ``> 0`` means it is parked in a
  virtualized sleep/poll until that virtual nanosecond. The
  coordinator only jumps when every claimed slot is parked, and the
  earliest slot deadline competes with the delay queue's as the jump
  target.
* the LD_PRELOAD interposer (``native/clock_interpose.cc``) is the C
  reader/claimant of the page: it virtualizes ``clock_gettime`` /
  ``gettimeofday`` and converts ``nanosleep``/``usleep``/``sleep`` and
  ``poll``/``select``/``epoll_wait`` timeouts into parked epochs —
  short real-sleep quanta that re-read the offset, so a jump is
  observed within ~2ms of wall time.
* :func:`activate` / :class:`VclockHandle` — the per-run lifecycle
  `run --virtual-clock` drives: create the page, install a
  :class:`~namazu_tpu.utils.timesource.VirtualTimeSource` over it,
  start the coordinator, and export ``NMZ_VCLOCK`` (+ ``LD_PRELOAD``)
  to the experiment's children.

Binary page layout (little-endian, 64 slots):

====== ===== =========================================================
offset size  field
====== ===== =========================================================
0      8     magic ``NMZVCLK1``
8      8     u64 seq — seqlock (odd while the writer is mid-update)
16     8     i64 offset_ns — virtual = CLOCK_MONOTONIC + offset
24     8     u64 slot_count
32     16×N  slots: u64 owner ``(pid << 32) | tid`` (0 = free),
             i64 deadline_ns (0 = running, >0 = parked until virtual)
====== ===== =========================================================

The seqlock write protocol (seq odd → fields → seq even) is what lets
the C side read a consistent offset without a lock; slot claims are a
compare-and-swap on the owner word, C-side only — Python only ever
*reads* slots, plus garbage-collects slots whose owner thread is gone
(a thread that died mid-run must not pin the clock forever).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import platform
import struct
from typing import Any, Dict, Optional, Tuple

from namazu_tpu import obs
from namazu_tpu.utils import timesource
from namazu_tpu.utils.log import get_logger

log = get_logger("vclock")

__all__ = ["EpochPage", "VclockHandle", "activate", "interposer_path",
           "ENV_PAGE", "ENV_LIB"]

#: the page-path env every interposed child reads
ENV_PAGE = "NMZ_VCLOCK"
#: optional override for the interposer .so location
ENV_LIB = "NMZ_VCLOCK_LIB"

MAGIC = b"NMZVCLK1"
SLOTS = 64
_HEADER = struct.Struct("<8sQqQ")          # magic, seq, offset_ns, slots
_SLOT = struct.Struct("<Qq")               # owner, deadline_ns
PAGE_SIZE = _HEADER.size + SLOTS * _SLOT.size
#: deadlines at/above this are "parked without a deadline" (a thread in
#: an indefinite poll/select): they satisfy the all-parked check but
#: never propose a jump target — matches kForever in clock_interpose.cc
FOREVER_NS = 1 << 62

#: futex(2) syscall numbers by machine — parked interposed threads
#: FUTEX_WAIT on the page's seq word, and publish() FUTEX_WAKEs them so
#: a jump is observed in microseconds rather than a polling quantum.
#: On an unlisted machine the wake is skipped and parked threads fall
#: back to their bounded re-check slice: slower, never wrong.
_SYS_FUTEX = {"x86_64": 202, "aarch64": 98}.get(platform.machine())
_FUTEX_WAKE = 1
try:
    _libc = ctypes.CDLL(None, use_errno=True)
except OSError:                                    # pragma: no cover
    _libc = None


class EpochPage:
    """The orchestrator-side (writer) face of one run's epoch page."""

    def __init__(self, path: str, create: bool = True) -> None:
        self.path = path
        if create or not os.path.exists(path):
            with open(path, "wb") as f:
                f.write(_HEADER.pack(MAGIC, 0, 0, SLOTS))
                f.write(b"\x00" * (SLOTS * _SLOT.size))
        self._f = open(path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), PAGE_SIZE)
        magic, _, _, slots = _HEADER.unpack_from(self._mm, 0)
        if magic != MAGIC:
            raise ValueError(f"{path} is not an epoch page")
        self.slots = int(slots)

    # -- writer ----------------------------------------------------------

    def publish(self, offset_s: float) -> None:
        """Seqlock write of the virtual offset: bump seq odd, store the
        offset, bump seq even. A C reader that straddles the update
        retries until seq is stable-and-even."""
        seq = struct.unpack_from("<Q", self._mm, 8)[0]
        struct.pack_into("<Q", self._mm, 8, seq + 1)
        struct.pack_into("<q", self._mm, 16, int(offset_s * 1e9))
        struct.pack_into("<Q", self._mm, 8, seq + 2)
        self._futex_wake()

    def _futex_wake(self) -> None:
        """Wake every interposed thread FUTEX_WAITing on the seq word
        (its low 32 bits — the futex ABI watches one int) so a freshly
        published jump is observed immediately."""
        if _libc is None or _SYS_FUTEX is None:
            return
        addr = ctypes.addressof(ctypes.c_uint32.from_buffer(self._mm, 8))
        _libc.syscall(ctypes.c_long(_SYS_FUTEX), ctypes.c_void_p(addr),
                      ctypes.c_int(_FUTEX_WAKE),
                      ctypes.c_int(2 ** 31 - 1),
                      None, None, ctypes.c_int(0))

    # -- reader ----------------------------------------------------------

    def offset_s(self) -> float:
        return struct.unpack_from("<q", self._mm, 16)[0] / 1e9

    def slot_states(self) -> list:
        """``[(owner, deadline_ns)]`` for every claimed slot."""
        out = []
        for i in range(self.slots):
            owner, deadline = _SLOT.unpack_from(
                self._mm, _HEADER.size + i * _SLOT.size)
            if owner:
                out.append((owner, deadline))
        return out

    def parked_state(self) -> Tuple[bool, Optional[float], int]:
        """``(all_parked, earliest_deadline_virtual_s, claimed)`` —
        what the fast-forward coordinator's pinning rule reads. A slot
        in the running state (deadline 0) pins the clock to wall rate;
        dead owners are garbage-collected first so a crashed thread
        cannot pin forever."""
        self._gc_dead()
        earliest: Optional[int] = None
        claimed = 0
        all_parked = True
        for owner, deadline in self.slot_states():
            claimed += 1
            if deadline == 0:
                all_parked = False
            elif deadline < FOREVER_NS and (earliest is None
                                            or deadline < earliest):
                earliest = deadline
        return (all_parked,
                earliest / 1e9 if earliest is not None else None,
                claimed)

    def _gc_dead(self) -> None:
        """Free slots whose owner thread no longer exists. /proc is the
        authority: an interposed thread that exited without running its
        thread-local destructor (SIGKILL) leaves a running-state slot
        that would otherwise veto every future jump."""
        for i in range(self.slots):
            off = _HEADER.size + i * _SLOT.size
            owner = struct.unpack_from("<Q", self._mm, off)[0]
            if not owner:
                continue
            pid, tid = owner >> 32, owner & 0xFFFFFFFF
            if not os.path.exists(f"/proc/{pid}/task/{tid}"):
                struct.pack_into("<Qq", self._mm, off, 0, 0)

    def close(self) -> None:
        try:
            self._mm.close()
        finally:
            self._f.close()


def interposer_path() -> Optional[str]:
    """The built clock interposer .so, or None. ``NMZ_VCLOCK_LIB``
    wins; the default is the repo's native build dir (same layout the
    fs interposer uses)."""
    override = os.environ.get(ENV_LIB, "")
    if override:
        return override if os.path.exists(override) else None
    here = os.path.dirname(os.path.abspath(__file__))
    candidate = os.path.join(here, "..", "..", "native", "build",
                             "libnmz_clock_interpose.so")
    candidate = os.path.normpath(candidate)
    return candidate if os.path.exists(candidate) else None


class VclockHandle:
    """One run's virtual-clock session: owns the page, the installed
    VirtualTimeSource, and the coordinator thread."""

    def __init__(self, page: EpochPage,
                 source: timesource.VirtualTimeSource,
                 previous: timesource.TimeSource,
                 lib: Optional[str]) -> None:
        self.page = page
        self.source = source
        self._previous = previous
        self.lib = lib
        self._finished = False

    def child_env(self) -> Dict[str, str]:
        """The env every experiment child needs: the page path, and the
        interposer prepended to LD_PRELOAD (composing with the fs
        interposer when both planes are armed). Without a built
        interposer children simply keep wall-rate waits — they then
        hold no slots, so with ``vclock_min_entities`` unset the
        in-process delay queue still fast-forwards."""
        env = {ENV_PAGE: self.page.path}
        if self.lib:
            existing = os.environ.get("LD_PRELOAD", "")
            env["LD_PRELOAD"] = (f"{self.lib}:{existing}" if existing
                                 else self.lib)
        return env

    def finish(self) -> Dict[str, Any]:
        """Stop fast-forwarding, restore the previous TimeSource, and
        return (and publish) the session summary. Idempotent."""
        if self._finished:
            return self.source.summary()
        self._finished = True
        self.source.stop_coordinator()
        timesource.install(self._previous)
        summary = self.source.summary()
        if summary["speedup_ratio"] is not None:
            obs.vclock_speedup(summary["speedup_ratio"])
        obs.vclock_pinned(summary["pinned_s"])
        self.page.close()
        log.info(
            "virtual clock: %.2fs wall covered %.2fs virtual "
            "(%.0f jump(s) skipped %.2fs; pinned to wall rate %.2fs; "
            "speedup %sx)", summary["wall_elapsed_s"],
            summary["virtual_elapsed_s"], summary["jumps"],
            summary["jumped_s"], summary["pinned_s"],
            summary["speedup_ratio"])
        return summary


def activate(workdir: str, cfg=None,
             page_name: str = "vclock.page") -> VclockHandle:
    """Arm the virtual clock for one run: create the epoch page in
    ``workdir``, install a VirtualTimeSource reading it as the process
    default (so every ScheduledQueue, liveness stamp, and lease TTL
    constructed afterwards runs virtual), and start the fast-forward
    coordinator. The caller exports :meth:`VclockHandle.child_env` to
    its experiment children and calls :meth:`VclockHandle.finish` when
    the run ends."""
    page = EpochPage(os.path.join(workdir, page_name), create=True)
    min_entities = 0
    if cfg is not None:
        min_entities = int(cfg.get("vclock_min_entities", 0) or 0)
    source = timesource.VirtualTimeSource(epoch_page=page,
                                          min_entities=min_entities)
    previous = timesource.install(source)
    source.start_coordinator()
    lib = interposer_path()
    if lib is None:
        log.warning(
            "virtual clock armed without the LD_PRELOAD interposer "
            "(native/build/libnmz_clock_interpose.so not built): "
            "in-process delays fast-forward, child-process waits stay "
            "wall-rate")
    return VclockHandle(page, source, previous, lib)
