"""Persistent search sidecar: the orchestrator ⇄ JAX boundary.

SURVEY.md §5.8 calls for a fourth endpoint-like boundary beside
local/REST/agent: the control plane ships recorded history to a
long-lived JAX process and gets the best schedule back. Without it,
every `run` process pays search construction + jit warm-up (seconds)
for a two-second experiment; the sidecar holds the compiled search and
device state for the WHOLE experiment, so a per-run search request costs
one ingest + a few warm generations (~100 ms class) plus a loopback
round trip.

Wire: framed JSON over TCP (the same 4-byte little-endian length prefix
as the guest-agent endpoint — endpoint/agent.py read_frame/write_frame),
keep-alive: a connection may carry any number of request/response pairs
(the PR 5 persistent-connection pattern; requests on one connection are
served in order). Old one-shot clients — send one frame, read the
reply, close — keep working: the server loop simply sees EOF.

* ``{"op": "ping"}`` -> ``{"ok": true, "searches": N}``
* ``{"op": "search", "key": str, "storage": dir,
     "search_params": {...}, "ingest_params": {...},
     "generations": N, "checkpoint": path}``
  -> ``{"ok": true, "fitness": f, "delays": [...], "faults": [...],
        "generations_run": N}``
* knowledge-plane ops (``pool_push`` / ``pool_pull`` /
  ``surrogate_predict`` / ``stats``; doc/knowledge.md) when the sidecar
  was started with ``--pool-dir`` — without it they answer
  ``{"ok": false, ...}`` and clients degrade to local-only search.

The sidecar reads the storage directory itself (same host by design —
this boundary rides loopback/DCN, never the per-event hot path), runs
the SAME ingest the in-process policy uses (models/ingest.py), and
persists the checkpoint so in-process and sidecar searches are
interchangeable mid-experiment. A changed ``search_params`` fingerprint
for a key rebuilds that search.

Start one with ``nmz-tpu sidecar --listen 127.0.0.1:10990``; point the
policy at it with ``sidecar = "127.0.0.1:10990"`` in
``explore_policy_param``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from namazu_tpu import obs
from namazu_tpu.endpoint.agent import read_frame, write_frame
from namazu_tpu.endpoint.framed import FramedServer
from namazu_tpu.storage import load_storage
from namazu_tpu.utils.log import get_logger

log = get_logger("sidecar")


def build_search_from_params(p: dict):
    """Construct a search backend from a flat JSON-able params dict (the
    policy's knobs, TPUSearchPolicy._search_params)."""
    from namazu_tpu.models.ga import GAConfig
    from namazu_tpu.models.search import (
        MCTSSearch,
        ScheduleSearch,
        SearchConfig,
        make_score_weights,
    )

    weights = make_score_weights(
        release_mode=p.get("release_mode", "delay"),
        w_novelty=p.get("w_novelty", 1.0),
        w_bug=p.get("w_bug", 1.0),
        w_delay_cost=p.get("w_delay_cost", 0.01),
        w_fault_cost=p.get("w_fault_cost", 0.05),
        tau=p.get("tau", 0.005),
        reorder_gap=p.get("reorder_gap", 0.002),
        reorder_window=p.get("reorder_window", 0.05),
    )
    cfg = SearchConfig(
        H=p.get("H", 256), L=p.get("L", 0), K=p.get("K", 256),
        population=p.get("population", 4096),
        migrate_k=p.get("migrate_k", 8),
        seed=p.get("seed", 0),
        ga=GAConfig(max_delay=p.get("max_interval", 0.1),
                    max_fault=p.get("max_fault", 0.0)),
        weights=weights,
        surrogate_topk=p.get("surrogate_topk", 16),
        min_failure_signatures=p.get("min_failure_signatures", 0),
        novelty_floor=p.get("novelty_floor", 0.25),
        guidance_bonus=p.get("guidance_bonus", 0.5),
        fused=bool(p.get("fused", True)),
        fused_chunk=int(p.get("fused_chunk", 16)),
        migrate_every=int(p.get("migrate_every", 1)),
        dcn_migrate_every=int(p.get("dcn_migrate_every", 1)),
        device_trace_dir=str(p.get("device_trace_dir", "") or ""),
    )
    n_devices = p.get("devices")
    if p.get("search_backend", "ga") == "mcts":
        from namazu_tpu.models.mcts import MCTSConfig

        mcts_cfg = MCTSConfig(
            tree_depth=p.get("mcts_tree_depth", 24),
            n_levels=p.get("mcts_levels", 8),
            simulations=p.get("mcts_simulations", 256),
            rollouts=p.get("mcts_rollouts", 64),
            max_delay=p.get("max_interval", 0.1),
            max_fault=p.get("max_fault", 0.0),
        )
        search = MCTSSearch(cfg, mcts_cfg=mcts_cfg, n_devices=n_devices)
    else:
        search = ScheduleSearch(cfg, n_devices=n_devices)
    if p.get("guidance"):
        # wired before any checkpoint load (SearchService._get_search)
        # so archive rows and DAG-shape fragments stay slot-aligned —
        # same ordering contract as policy/tpu.py _build_search
        search.enable_guidance(p.get("guidance_width") or None,
                               p.get("guidance_window") or None)
    return search


class SearchService:
    """Holds one live search per experiment key."""

    def __init__(self) -> None:
        # key -> (params-fingerprint, search)
        self._searches: Dict[str, Tuple[str, object]] = {}
        self._lock = threading.Lock()
        # one lock per key, held across the whole ingest+evolve+save:
        # a timed-out client's next request for the same storage must
        # queue behind the in-flight one — concurrent ingest would clear
        # the archives mid-evolve (set_occupied_buckets) and corrupt the
        # shared checkpoint
        self._key_locks: Dict[str, threading.Lock] = {}

    def handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            resp = {"ok": True, "searches": len(self._searches)}
        elif op == "search":
            resp = self._search(req)
        else:
            resp = {"ok": False, "error": f"unknown op {op!r}"}
        obs.sidecar_request(str(op), bool(resp.get("ok")))
        return resp

    def _get_search(self, key: str, params: dict, checkpoint: str):
        fp = json.dumps(params, sort_keys=True)
        with self._lock:
            cached = self._searches.get(key)
        if cached is not None and cached[0] == fp:
            search = cached[1]
            self._maybe_reload(search, checkpoint)
            return search, False
        # build OUTSIDE the global lock: jit construction can take
        # seconds and must not block ping or other keys' requests — the
        # caller already holds this key's lock, which serializes
        # same-key requests (ADVICE r4)
        search = build_search_from_params(params)
        if checkpoint and os.path.exists(checkpoint):
            try:
                search.load(checkpoint)
                log.info("loaded checkpoint %s (gen %d)",
                         checkpoint, search.generations_run)
            except Exception:
                log.exception("checkpoint %s not loadable; fresh "
                              "search", checkpoint)
        with self._lock:
            self._searches[key] = (fp, search)
        return search, True

    def _maybe_reload(self, search, checkpoint: str) -> None:
        """Reload a cached search whose on-disk checkpoint is AHEAD of
        it: when a sidecar request fails the policy falls back to an
        in-process evolve and saves, so serving the next request from
        the stale in-memory state would overwrite those generations at
        the next save (lost update, ADVICE r4). generations_run is
        monotonic, so disk-ahead detection is one npz field read."""
        if not checkpoint or not os.path.exists(checkpoint):
            return
        try:
            with np.load(checkpoint) as z:
                disk_gen = (int(z["generations_run"])
                            if "generations_run" in z else -1)
        except Exception:
            return  # unreadable/corrupt: keep the live state
        if disk_gen > search.generations_run:
            try:
                search.load(checkpoint)
                log.info(
                    "reloaded checkpoint %s: disk at gen %d, cached "
                    "search at %d (in-process fallback ran between "
                    "requests)", checkpoint, disk_gen,
                    search.generations_run)
            except Exception:
                log.exception("newer checkpoint %s not loadable; "
                              "keeping cached state", checkpoint)

    def _key_lock(self, key: str) -> threading.Lock:
        with self._lock:
            return self._key_locks.setdefault(key, threading.Lock())

    def _search(self, req: dict) -> dict:
        key = str(req.get("key") or req.get("storage") or "default")
        with self._key_lock(key):
            return self._search_locked(key, req)

    def _search_locked(self, key: str, req: dict) -> dict:
        from namazu_tpu.models.ingest import IngestParams, ingest_history

        params = req.get("search_params") or {}
        checkpoint = str(req.get("checkpoint") or "")
        search, fresh = self._get_search(key, params, checkpoint)
        storage_dir = req.get("storage")
        try:
            storage = load_storage(storage_dir) if storage_dir else None
        except Exception as e:
            return {"ok": False, "error": f"storage: {e}"}
        ip = req.get("ingest_params") or {}
        if ip.get("knowledge"):
            # a sidecar-hosted search serves knowledge-wired tenants
            # too: its ingest pushes/pulls the global pool (below, via
            # IngestParams) and its candidate re-rank may consult the
            # shared surrogate — possibly our own loopback, which is
            # fine (each connection gets its own handler thread)
            from namazu_tpu.knowledge import shared_client
            from namazu_tpu.knowledge.client import pairs_fingerprint

            kc = shared_client(
                str(ip["knowledge"]),
                tenant=str(ip.get("knowledge_tenant") or ""),
                scenario=str(ip.get("knowledge_scenario") or ""))
            search.remote_surrogate = (
                lambda feats, _c=kc, _s=search:
                    _c.predict(feats, pairs_fp=pairs_fingerprint(_s.pairs)))
        references = ingest_history(
            search, storage,
            IngestParams(**{k: v for k, v in ip.items()
                            if k in IngestParams._fields}))
        if not references:
            return {"ok": True, "no_history": True,
                    "generations_run": search.generations_run}
        best = search.run(references,
                          generations=int(req.get("generations", 64)))
        if checkpoint:
            try:
                search.save(checkpoint)
            except Exception:
                log.exception("could not save checkpoint %s", checkpoint)
        return {
            "ok": True,
            "fitness": float(best.fitness),
            "delays": [float(x) for x in best.delays],
            "faults": [float(x) for x in best.faults],
            "generations_run": search.generations_run,
        }


class SidecarServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 10990,
                 knowledge=None):
        self.service = SearchService()
        # optional multi-tenant knowledge service (knowledge/service.py):
        # the sidecar is its host process, sharing the framed wire
        self.knowledge = knowledge
        self._host, self._port = host, port
        # the shared keep-alive serve loop (endpoint/framed.py): one
        # frame-hygiene/error-answering/span-context implementation
        # across the framed wires. Keep-alive matters here: knowledge
        # clients push and pull on every run of a campaign, and
        # re-paying TCP setup per request would tax exactly the
        # cold-run path the warm-start exists to speed up; one-shot
        # clients still work — their close is just the first EOF.
        self._srv: Optional[FramedServer] = None

    @property
    def port(self) -> int:
        assert self._srv is not None
        return self._srv.port

    def start(self) -> None:
        srv = FramedServer(self._dispatch, name="sidecar")
        srv.bind_tcp(self._host, self._port)
        srv.start()
        self._srv = srv
        log.info("search sidecar on %s:%d", self._host, self.port)

    def shutdown(self) -> None:
        # shutdown severs live keep-alive connections too, or "kill
        # the service" would leave already-connected clients talking
        # to a half-dead server instead of degrading cleanly
        srv, self._srv = self._srv, None
        if srv is not None:
            srv.shutdown()
        if self.knowledge is not None:
            self.knowledge.close()

    def _dispatch(self, req: dict) -> dict:
        """Route one request: knowledge ops to the hosted knowledge
        service (an explicit refusal when none is configured, so clients
        can tell "no knowledge here" from a dead host and degrade),
        everything else to the search service."""
        op = req.get("op")
        from namazu_tpu.knowledge import KNOWLEDGE_OPS
        from namazu_tpu.obs import federation

        # observability ops (obs/federation.py): the sidecar's framed
        # wire doubles as a telemetry push target / fleet surface, so
        # knowledge-plane processes can aggregate without an HTTP stack
        obs_resp = federation.handle_obs_op(req)
        if obs_resp is not None:
            return obs_resp
        if op in KNOWLEDGE_OPS:
            if self.knowledge is None:
                resp = {"ok": False,
                        "error": "knowledge service not configured "
                                 "(start the sidecar with --pool-dir)"}
            else:
                resp = self.knowledge.handle(req)
            obs.sidecar_request(str(op), bool(resp.get("ok")))
            return resp
        resp = self.service.handle(req)
        if op == "ping" and self.knowledge is not None:
            # advertise the knowledge plane (and its version) so a
            # client can discover it from the same probe old clients
            # already send; a knowledge-less sidecar answers the
            # pre-knowledge shape unchanged
            resp["knowledge"] = True
            resp["knowledge_v"] = self.knowledge.VERSION
        return resp


def request(addr: str, req: dict, timeout: float = 300.0) -> dict:
    """One framed request/response against a sidecar at ``host:port``."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host or "127.0.0.1", int(port)),
                                  timeout=timeout) as s:
        write_frame(s, req)
        resp = read_frame(s)
    if resp is None:
        raise ConnectionError(f"sidecar {addr}: connection closed")
    return resp


def serve_sidecar(host: str, port: int, pool_dir: str = "",
                  state_dir: str = "", telemetry_url: str = "") -> int:
    """CLI entry: serve until interrupted. ``pool_dir`` enables the
    multi-tenant knowledge service (doc/knowledge.md) on the same
    wire; ``telemetry_url`` pushes this process's metrics to a fleet
    aggregator so the sidecar shows up in the campaign's ``/fleet``
    view (doc/observability.md "Fleet telemetry")."""
    knowledge = None
    if pool_dir:
        from namazu_tpu.knowledge import KnowledgeService

        knowledge = KnowledgeService(pool_dir, state_dir=state_dir)
        log.info("knowledge service enabled: pool %s",
                 knowledge.pool_dir)
    server = SidecarServer(host, port, knowledge=knowledge)
    server.start()
    from namazu_tpu.obs import federation

    federation.ensure_self_relay(
        "sidecar",
        push_url=(telemetry_url
                  or os.environ.get("NMZ_TELEMETRY_URL", "")))
    # continuous profiling: where does sidecar time go (framed wire vs
    # surrogate scoring) — served over the framed `profile` op
    from namazu_tpu.obs import profiling

    profiling.ensure_profiler("sidecar")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0
