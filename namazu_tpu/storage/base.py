"""HistoryStorage interface and factory.

Parity: /root/reference/nmz/historystorage/historystorage.go:22-61
(interface + New/LoadStorage).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional

from namazu_tpu.utils.trace import SingleTrace


class StorageError(Exception):
    pass


class HistoryStorage:
    """One experiment's history: N runs, each with a trace and a result."""

    NAME = "abstract"

    # -- lifecycle -------------------------------------------------------

    def create(self) -> None:
        """Create the on-disk layout (once, at `init` time)."""
        raise NotImplementedError

    def init(self) -> None:
        """Open an existing storage (every `run`)."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    # -- per-run ---------------------------------------------------------

    def create_new_working_dir(self) -> str:
        """Allocate the next run directory; returns its path."""
        raise NotImplementedError

    def record_new_trace(self, trace: SingleTrace) -> None:
        raise NotImplementedError

    def record_result(
        self,
        successful: bool,
        required_time: float,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        raise NotImplementedError

    def quarantine_current_run(self, reason: str = "") -> None:
        """Mark the in-flight run dir as deliberately abandoned (infra
        failure / deadline abort: nothing will be recorded). Keeps an
        aborted run distinguishable from a crashed one — fsck treats
        marked dirs as accounted for, unmarked ones as findings."""

    # -- queries ---------------------------------------------------------

    def run_dir(self, i: int) -> str:
        """Path of run ``i``'s working directory — the public accessor
        for per-run artifacts beyond the trace/result pair (e.g. the
        analyzer's ``coverage.json``, the run's ``nmz.log``)."""
        raise NotImplementedError

    def nr_stored_histories(self) -> int:
        raise NotImplementedError

    def is_quarantined(self, i: int) -> bool:
        """Whether run ``i`` was quarantined as incomplete (crash-safety;
        see storage/naive.py). Quarantined runs raise StorageError from
        every per-run query so partial data cannot pollute cross-run
        statistics; backends without crash detection report none."""
        return False

    def quarantined_runs(self) -> List[int]:
        return [i for i in range(self.nr_stored_histories())
                if self.is_quarantined(i)]

    def get_stored_history(self, i: int) -> SingleTrace:
        raise NotImplementedError

    def is_successful(self, i: int) -> bool:
        raise NotImplementedError

    def get_required_time(self, i: int) -> float:
        raise NotImplementedError

    def get_metadata(self, i: int) -> Dict[str, Any]:
        raise NotImplementedError

    def search(self, prefix: List[str]) -> Iterable[int]:
        """Indices of runs whose trace's action-class sequence starts with
        ``prefix`` (parity: naive.go:232-257 linear scan)."""
        raise NotImplementedError


_BACKENDS: Dict[str, type] = {}


def register_storage(cls: type) -> type:
    _BACKENDS[cls.NAME] = cls
    return cls


def new_storage(name: str, dir_path: str) -> HistoryStorage:
    """Parity: historystorage.New (historystorage.go:42-53)."""
    try:
        cls = _BACKENDS[name]
    except KeyError:
        raise StorageError(
            f"unknown storage type {name!r}; known: {sorted(_BACKENDS)}"
        ) from None
    return cls(dir_path)


def load_storage(dir_path: str) -> HistoryStorage:
    """Open an existing storage dir, reading its recorded backend type
    (parity: LoadStorage, historystorage.go:55-61)."""
    meta_path = os.path.join(dir_path, "storage.json")
    if not os.path.exists(meta_path):
        raise StorageError(f"not a storage dir (no storage.json): {dir_path}")
    with open(meta_path) as f:
        meta = json.load(f)
    st = new_storage(meta["type"], dir_path)
    st.init()
    return st
