"""Import recorded experiment runs from a reference-format storage dir.

The reference ships real recorded ZooKeeper experiment data — e.g.
``example/zk-found-2212.ryu/example-result.20150805`` holds four runs of
the actual ZOOKEEPER-2212 hunt (3-node ZK cluster, OVS/Ryu interception),
each as per-action JSON pairs plus a Go-gob ``result`` file (layout:
/root/reference/nmz/historystorage/naive/naive.go:143-176, per-action
files common.go:34-40). This module converts such a directory into a
native storage so every tool downstream — ``tools summary|visualize``,
the search plane's history ingest, golden-trace tests — consumes real
distributed-system data, not just the synthetic examples.

Wire mapping:

* ``<i>.action.json`` — reference signal JSON (class/entity/option); class
  names match ours 1:1 (register.go:31-36 vs namazu_tpu/signal/action.py).
* ``<i>.event.json`` — the cause event; its semantic payload (zktraffic's
  parsed FLE/ZAB messages) is re-keyed into the SAME hint format our
  ZkStreamParser emits — imported and live traces share buckets for
  every FLE/ZAB class; other protocols fall back to a deterministic
  intra-import identity (see ``semantic_hint``).
* ``result`` — gob ``testResult{Succeed bool; RequiredTime time.Duration}``
  (naive/common.go:34-40); decoded by a minimal gob reader below.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from namazu_tpu.signal.action import (
    Action,
    EventAcceptanceAction,
    FilesystemFaultAction,
    NopAction,
    PacketFaultAction,
    ShellAction,
)
from namazu_tpu.storage.naive import NaiveStorage
from namazu_tpu.utils.trace import SingleTrace

#: reference action class -> native class (names are identical by design;
#: the table just pins the mapping and rejects unknowns loudly)
ACTION_CLASSES = {
    "EventAcceptanceAction": EventAcceptanceAction,
    "AcceptEventAction": EventAcceptanceAction,  # pynmz-era alias
    "NopAction": NopAction,
    "PacketFaultAction": PacketFaultAction,
    "FilesystemFaultAction": FilesystemFaultAction,
    "ShellAction": ShellAction,
}


# -- minimal gob decoding (exactly what testResult needs) -------------------


def _gob_uint(b: bytes, i: int) -> Tuple[int, int]:
    """Decode one gob unsigned int at offset i -> (value, next offset)."""
    x = b[i]
    if x < 0x80:
        return x, i + 1
    n = 0x100 - x  # count of big-endian bytes that follow
    if n > 8 or i + 1 + n > len(b):
        raise ValueError(f"bad gob uint at {i}")
    v = 0
    for j in range(n):
        v = (v << 8) | b[i + 1 + j]
    return v, i + 1 + n


def _gob_int(b: bytes, i: int) -> Tuple[int, int]:
    u, i = _gob_uint(b, i)
    return (~(u >> 1) if (u & 1) else (u >> 1)), i


def parse_gob_result(path: str) -> Tuple[bool, float]:
    """(successful, required_time_seconds) from a gob testResult file.

    The stream is framed messages (uint byte-count + payload); type
    definitions carry a negative type id, the value message a positive
    one followed by delta-encoded struct fields — field 1 ``Succeed``
    (bool-as-uint, omitted when false) and field 2 ``RequiredTime``
    (signed int nanoseconds)."""
    with open(path, "rb") as f:
        b = f.read()
    i = 0
    while i < len(b):
        length, i = _gob_uint(b, i)
        end = i + length
        if end > len(b):
            raise ValueError(f"truncated gob message in {path}")
        type_id, j = _gob_int(b, i)
        if type_id < 0:  # type definition; skip
            i = end
            continue
        succeed = False
        required_ns = 0
        field = -1
        while j < end:
            delta, j = _gob_uint(b, j)
            if delta == 0:
                break
            field += delta
            if field == 0:
                v, j = _gob_uint(b, j)
                succeed = bool(v)
            elif field == 1:
                required_ns, j = _gob_int(b, j)
            else:
                raise ValueError(
                    f"unexpected gob field {field} in {path}")
        return succeed, required_ns / 1e9
    raise ValueError(f"no gob value message in {path}")


# -- semantic hint reconstruction -------------------------------------------


def _as_int(x: Any) -> int:
    """Recorded numerics are JSON floats (zktraffic ran under Python 2 and
    json.dump floated the int64s); collapse them back deterministically."""
    try:
        return int(x)
    except (TypeError, ValueError):
        return 0


def semantic_hint(event: Dict[str, Any]) -> str:
    """Reconstruct the replay hint our live stack would record for this
    event: the flow prefix PacketEvent.replay_hint adds ("src->dst:", so
    per-destination delays stay searchable) plus the content hint the
    ZkStreamParser emits (inspector/zookeeper.py _fle_step) — imported
    and freshly captured traces share one hint space."""
    opt = event.get("option") or {}
    msg = opt.get("message") or {}
    src, dst = opt.get("src_entity"), opt.get("dst_entity")
    flow = f"{src}->{dst}:" if src and dst else ""
    group, cls = msg.get("class_group"), msg.get("class")
    zxid = (_as_int(msg.get("zxid_hi", 0)) << 32) | (
        _as_int(msg.get("zxid_low", 0)) & 0xFFFFFFFF)
    if group == "FLE" and cls == "Notification":
        parts = [
            "fle:notif",
            f"state={msg.get('state', '?')}",
            f"leader={_as_int(msg.get('leader', 0))}",
            f"zxid={zxid:#x}",
            f"epoch={_as_int(msg.get('election_epoch', 0))}",
            f"peerEpoch={_as_int(msg.get('peer_epoch', 0))}",
        ]
        return flow + ":".join(parts)
    if group == "FLE" and cls == "Initial":
        return flow + f"fle:init:sid={_as_int(msg.get('server_id', 0))}"
    if group == "ZAB" and cls:
        # live format is zab:{type}:zxid=...:dlen={n} (zookeeper.py
        # _zab_step; pings collapse to the bare "ping" hint there).
        # zktraffic's JSON records neither the wire type id nor the data
        # length; the lowercased class name matches the live type names
        # for every concrete ZAB class (ack, ackepoch, leaderinfo, ...),
        # and dlen=0 matches the common null-buffer case —
        # data-carrying proposals may land one bucket off, the
        # election-critical FLE classes above match exactly.
        if cls.lower() == "ping":
            return flow + "ping"
        return flow + f"zab:{cls.lower()}:zxid={zxid:#x}:dlen=0"
    # Generic fallback: deterministic intra-import identity only. Live
    # formats for other protocols (e.g. the client parser's "zkc:..."
    # hints) cannot be reconstructed from zktraffic's parsed JSON, so
    # cross-to-live bucket matching is guaranteed for the FLE/ZAB
    # classes above and NOT for this branch — searches over purely
    # imported history are still self-consistent.
    scalars = {k: v for k, v in msg.items()
               if isinstance(v, (str, int, float, bool))}
    body = json.dumps(scalars, sort_keys=True) if scalars else ""
    return ":".join(x for x in (
        event.get("class", "?"),
        str(opt.get("src_entity", "")),
        str(opt.get("dst_entity", "")),
        body,
    ) if x)


# -- per-run / whole-experiment import --------------------------------------

_RUN_DIR_RE = re.compile(r"^[0-9a-f]{8}$")


def import_run(run_dir: str) -> Tuple[SingleTrace, bool, float]:
    """One reference run dir -> (trace, successful, required_time_s)."""
    actions_dir = os.path.join(run_dir, "actions")
    indices = sorted(
        int(m.group(1))
        for name in os.listdir(actions_dir)
        if (m := re.match(r"^(\d+)\.action\.json$", name))
    )
    trace = SingleTrace()
    for i in indices:
        with open(os.path.join(actions_dir, f"{i}.action.json")) as f:
            act = json.load(f)
        event: Dict[str, Any] = {}
        ev_path = os.path.join(actions_dir, f"{i}.event.json")
        if os.path.exists(ev_path):
            with open(ev_path) as f:
                event = json.load(f)
        cls_name = act.get("class", "")
        cls = ACTION_CLASSES.get(cls_name)
        if cls is None:
            raise ValueError(
                f"{run_dir}: unknown reference action class {cls_name!r}")
        ev_opt = event.get("option") or {}
        action: Action = cls(
            entity_id=str(act.get("entity", "")),
            option={k: v for k, v in (act.get("option") or {}).items()
                    if k != "event_uuid"},
            uuid=act.get("uuid"),
            event_uuid=str((act.get("option") or {}).get("event_uuid", "")
                           or event.get("uuid", "")),
            event_class=str(event.get("class", "")),
            event_hint=semantic_hint(event) if event else "",
        )
        # keep the flow identity queryable downstream (dump-trace, PO
        # reduction group by entity); recorded PacketEvents carry it in
        # the event option
        if "src_entity" in ev_opt or "dst_entity" in ev_opt:
            action.option.setdefault("src_entity", ev_opt.get("src_entity"))
            action.option.setdefault("dst_entity", ev_opt.get("dst_entity"))
        trace.append(action)
    successful, required_s = parse_gob_result(os.path.join(run_dir, "result"))
    return trace, successful, required_s


def import_experiment(src_dir: str, dest_dir: str) -> Dict[str, Any]:
    """Import every run of a reference experiment dir into a new native
    storage at ``dest_dir``; returns a summary dict."""
    run_dirs = sorted(
        d for d in os.listdir(src_dir)
        if _RUN_DIR_RE.match(d)
        and os.path.isdir(os.path.join(src_dir, d, "actions"))
    )
    if not run_dirs:
        raise ValueError(f"{src_dir}: no reference run dirs (%08x/actions)")
    storage = NaiveStorage(dest_dir)
    storage.create()
    imported, failures, total_actions = 0, 0, 0
    for name in run_dirs:
        trace, ok, required_s = import_run(os.path.join(src_dir, name))
        storage.create_new_working_dir()
        storage.record_new_trace(trace)
        from namazu_tpu.ops.trace_encoding import HINT_SPACE

        storage.record_result(ok, required_s,
                              metadata={"imported_from":
                                        os.path.join(src_dir, name),
                                        "hint_space": HINT_SPACE})
        imported += 1
        failures += not ok
        total_actions += len(trace)
    return {
        "source": os.path.abspath(src_dir),
        "storage": os.path.abspath(dest_dir),
        "runs": imported,
        "failures": failures,
        "actions": total_actions,
    }
