"""MongoDB-backed history storage (gated).

Parity: /root/reference/nmz/historystorage/mongodb/mongodb.go:25-105 — a
decorator over the naive backend that additionally inserts every trace and
result into MongoDB collections for cross-experiment querying. This image
ships no ``pymongo``; the class registers itself only when the import
succeeds, otherwise ``new_storage("mongodb", ...)`` reports the gap.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from namazu_tpu.storage.base import register_storage
from namazu_tpu.storage.naive import NaiveStorage
from namazu_tpu.utils.trace import SingleTrace

try:
    import pymongo  # noqa: F401

    _HAVE_PYMONGO = True
except ImportError:
    _HAVE_PYMONGO = False


class MongoDBStorage(NaiveStorage):
    NAME = "mongodb"

    DEFAULT_URL = "mongodb://localhost:27017"
    DB_NAME = "namazu_tpu"

    def __init__(self, dir_path: str, url: Optional[str] = None):
        super().__init__(dir_path)
        import pymongo

        self._client = pymongo.MongoClient(url or self.DEFAULT_URL)
        self._db = self._client[self.DB_NAME]

    def record_new_trace(self, trace: SingleTrace) -> None:
        super().record_new_trace(trace)
        self._db.traces.insert_one({
            "run_dir": self._current_run_dir,
            "actions": trace.to_jsonable(),
        })

    def record_result(
        self,
        successful: bool,
        required_time: float,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        super().record_result(successful, required_time, metadata)
        self._db.results.insert_one({
            "run_dir": self._current_run_dir,
            "successful": successful,
            "required_time": required_time,
            "metadata": metadata or {},
        })

    def close(self) -> None:
        self._client.close()


if _HAVE_PYMONGO:
    register_storage(MongoDBStorage)
