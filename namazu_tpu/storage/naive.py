"""Naive (filesystem JSON) history storage.

Parity: /root/reference/nmz/historystorage/naive — layout per storage dir:

::

    storage.json          {"type": "naive", "next_run": N}
    config.json           copy of the experiment config
    materials/            copy of the user's experiment scripts
    00000000/             one dir per run (%08x, parity naive.go:143-158)
        trace.json        the action sequence (JSON, not gob)
        result.json       {"successful": bool, "required_time": s, "metadata": {}}
        INCOMPLETE        quarantine marker (crash-safety, doc/robustness.md):
                          written by init()/fsck when a run crashed after
                          recording its trace but before its result. A
                          quarantined run is invisible to every query —
                          analytics, repro-rate stats, the search plane's
                          history ingest — so a partial run cannot pollute
                          cross-run statistics. ``nmz-tpu tools fsck``
                          lists and repairs quarantined runs.

The reference also writes per-action ``actions/<i>.{action,event}.json``
files; here the whole trace is one JSON array — same information, one file.
All JSON writes are atomic (utils/atomic.py: tmp + fsync + rename), so a
SIGKILL mid-write leaves the previous complete content, never a torn file.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional

from namazu_tpu.storage.base import HistoryStorage, StorageError, register_storage
from namazu_tpu.utils.atomic import atomic_write_json, atomic_write_text, is_tmp_artifact
from namazu_tpu.utils.log import get_logger
from namazu_tpu.utils.trace import SingleTrace

log = get_logger("storage.naive")

#: quarantine marker file inside a run dir (see module docstring)
INCOMPLETE_MARKER = "INCOMPLETE"


@register_storage
class NaiveStorage(HistoryStorage):
    NAME = "naive"

    def __init__(self, dir_path: str):
        self.dir = os.path.abspath(dir_path)
        self._next_run = 0
        self._current_run_dir: Optional[str] = None

    # -- layout helpers --------------------------------------------------

    def _meta_path(self) -> str:
        return os.path.join(self.dir, "storage.json")

    def run_dir(self, i: int) -> str:
        """Run ``i``'s working dir (%08x layout, parity naive.go:143-158)
        — the public accessor for per-run artifacts (coverage.json,
        nmz.log) beyond the trace/result pair."""
        return os.path.join(self.dir, f"{i:08x}")

    def _load_meta(self) -> Dict[str, Any]:
        with open(self._meta_path()) as f:
            return json.load(f)

    def _save_meta(self) -> None:
        atomic_write_json(self._meta_path(),
                          {"type": self.NAME, "next_run": self._next_run})

    def _marker_path(self, i: int) -> str:
        return os.path.join(self.run_dir(i), INCOMPLETE_MARKER)

    # -- lifecycle -------------------------------------------------------

    def create(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        if os.path.exists(self._meta_path()):
            raise StorageError(f"storage already exists: {self.dir}")
        self._next_run = 0
        self._save_meta()

    def init(self) -> None:
        if not os.path.exists(self._meta_path()):
            raise StorageError(f"not a storage dir: {self.dir}")
        self._next_run = int(self._load_meta()["next_run"])
        self._quarantine_crashed_runs()

    def _quarantine_crashed_runs(self) -> None:
        """Mark run dirs holding a trace but no result: the signature of
        a run killed between ``record_new_trace`` and ``record_result``.
        Dirs with NEITHER file are left unmarked here — an in-flight run
        looks exactly like that, and init() runs concurrently with live
        runs (the /analytics route loads the storage mid-experiment);
        ``tools fsck --repair``, which only an operator invokes on a
        quiescent storage, marks those too."""
        for i in range(self._next_run):
            run_dir = self.run_dir(i)
            if (os.path.exists(os.path.join(run_dir, "trace.json"))
                    and not os.path.exists(
                        os.path.join(run_dir, "result.json"))
                    and not os.path.exists(self._marker_path(i))):
                atomic_write_text(
                    self._marker_path(i),
                    "crashed between trace and result; quarantined by "
                    "init()\n")
                log.warning("run %08x has a trace but no result (crash "
                            "mid-run); quarantined", i)

    # -- per-run ---------------------------------------------------------

    def create_new_working_dir(self) -> str:
        run_dir = self.run_dir(self._next_run)
        os.makedirs(run_dir, exist_ok=False)
        self._next_run += 1
        self._save_meta()
        self._current_run_dir = run_dir
        return run_dir

    def record_new_trace(self, trace: SingleTrace) -> None:
        if self._current_run_dir is None:
            raise StorageError("no working dir; call create_new_working_dir first")
        atomic_write_text(
            os.path.join(self._current_run_dir, "trace.json"),
            trace.to_json())

    def record_result(
        self,
        successful: bool,
        required_time: float,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        if self._current_run_dir is None:
            raise StorageError("no working dir; call create_new_working_dir first")
        atomic_write_json(
            os.path.join(self._current_run_dir, "result.json"),
            {
                "successful": successful,
                "required_time": required_time,
                "metadata": metadata or {},
            },
        )
        # a concurrent init() (live /analytics scrape) may have seen the
        # trace-no-result window just above and quarantined this run;
        # the result landing proves it completed
        marker = os.path.join(self._current_run_dir, INCOMPLETE_MARKER)
        if os.path.exists(marker):
            os.unlink(marker)

    # -- quarantine ------------------------------------------------------

    def quarantine_current_run(self, reason: str = "") -> None:
        if self._current_run_dir is None:
            return
        atomic_write_text(
            os.path.join(self._current_run_dir, INCOMPLETE_MARKER),
            (reason or "run aborted; nothing recorded") + "\n")

    def is_quarantined(self, i: int) -> bool:
        return os.path.exists(self._marker_path(i))

    def quarantined_runs(self) -> List[int]:
        return [i for i in range(self._next_run) if self.is_quarantined(i)]

    def fsck(self, repair: bool = False) -> Dict[str, Any]:
        """Integrity report over every allocated run dir; with
        ``repair``, quarantine incomplete runs (including trace-less
        ones — fsck is operator-invoked on a quiescent storage, so the
        in-flight ambiguity init() must respect does not apply) and
        sweep orphan ``*.tmp`` files a hard kill left mid-atomic-write.
        """
        report: Dict[str, Any] = {
            "dir": self.dir,
            "next_run": self._next_run,
            "complete": 0,
            "quarantined": [],
            "incomplete_unmarked": [],
            "missing_dirs": [],
            "tmp_artifacts": [],
            "repaired": repair,
        }
        for i in range(self._next_run):
            run_dir = self.run_dir(i)
            if not os.path.isdir(run_dir):
                report["missing_dirs"].append(i)
                continue
            for name in sorted(os.listdir(run_dir)):
                if is_tmp_artifact(name):
                    path = os.path.join(run_dir, name)
                    report["tmp_artifacts"].append(path)
                    if repair:
                        os.unlink(path)
            if self.is_quarantined(i):
                report["quarantined"].append(i)
            elif os.path.exists(os.path.join(run_dir, "result.json")):
                report["complete"] += 1
            else:
                report["incomplete_unmarked"].append(i)
                if repair:
                    atomic_write_text(
                        self._marker_path(i),
                        "no result recorded; quarantined by fsck\n")
        for name in sorted(os.listdir(self.dir)):
            if is_tmp_artifact(name):
                path = os.path.join(self.dir, name)
                report["tmp_artifacts"].append(path)
                if repair:
                    os.unlink(path)
        if repair:
            # keep what was actually repaired visible: callers decide
            # exit codes on it even though the dirs are now quarantined
            report["repaired_runs"] = report["incomplete_unmarked"]
            report["quarantined"] = sorted(
                report["quarantined"] + report["incomplete_unmarked"])
            report["incomplete_unmarked"] = []
        else:
            report["repaired_runs"] = []
        return report

    # -- queries ---------------------------------------------------------

    def nr_stored_histories(self) -> int:
        # count only runs that completed (have a result)
        n = 0
        for i in range(self._next_run):
            if os.path.exists(os.path.join(self.run_dir(i), "result.json")):
                n = i + 1
        return n

    def _result(self, i: int) -> Dict[str, Any]:
        if self.is_quarantined(i):
            raise StorageError(f"run {i:08x} is quarantined (INCOMPLETE)")
        path = os.path.join(self.run_dir(i), "result.json")
        if not os.path.exists(path):
            raise StorageError(f"run {i:08x} has no result")
        with open(path) as f:
            return json.load(f)

    def get_stored_history(self, i: int) -> SingleTrace:
        # quarantined runs ARE likely to have a trace — refusing to
        # serve it is the point: a crash-truncated run must not feed
        # coverage stats or the search plane's archives
        if self.is_quarantined(i):
            raise StorageError(f"run {i:08x} is quarantined (INCOMPLETE)")
        path = os.path.join(self.run_dir(i), "trace.json")
        if not os.path.exists(path):
            raise StorageError(f"run {i:08x} has no trace")
        with open(path) as f:
            return SingleTrace.from_json(f.read())

    def is_successful(self, i: int) -> bool:
        return bool(self._result(i)["successful"])

    def get_required_time(self, i: int) -> float:
        return float(self._result(i)["required_time"])

    def get_metadata(self, i: int) -> Dict[str, Any]:
        return dict(self._result(i).get("metadata") or {})

    def search(self, prefix: List[str]) -> Iterable[int]:
        out = []
        for i in range(self.nr_stored_histories()):
            try:
                trace = self.get_stored_history(i)
            except StorageError:
                continue
            classes = [a.class_name() for a in trace]
            if classes[: len(prefix)] == list(prefix):
                out.append(i)
        return out
