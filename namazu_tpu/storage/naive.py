"""Naive (filesystem JSON) history storage.

Parity: /root/reference/nmz/historystorage/naive — layout per storage dir:

::

    storage.json          {"type": "naive", "next_run": N}
    config.json           copy of the experiment config
    materials/            copy of the user's experiment scripts
    00000000/             one dir per run (%08x, parity naive.go:143-158)
        trace.json        the action sequence (JSON, not gob)
        result.json       {"successful": bool, "required_time": s, "metadata": {}}

The reference also writes per-action ``actions/<i>.{action,event}.json``
files; here the whole trace is one JSON array — same information, one file.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional

from namazu_tpu.storage.base import HistoryStorage, StorageError, register_storage
from namazu_tpu.utils.trace import SingleTrace


@register_storage
class NaiveStorage(HistoryStorage):
    NAME = "naive"

    def __init__(self, dir_path: str):
        self.dir = os.path.abspath(dir_path)
        self._next_run = 0
        self._current_run_dir: Optional[str] = None

    # -- layout helpers --------------------------------------------------

    def _meta_path(self) -> str:
        return os.path.join(self.dir, "storage.json")

    def run_dir(self, i: int) -> str:
        """Run ``i``'s working dir (%08x layout, parity naive.go:143-158)
        — the public accessor for per-run artifacts (coverage.json,
        nmz.log) beyond the trace/result pair."""
        return os.path.join(self.dir, f"{i:08x}")

    def _load_meta(self) -> Dict[str, Any]:
        with open(self._meta_path()) as f:
            return json.load(f)

    def _save_meta(self) -> None:
        with open(self._meta_path(), "w") as f:
            json.dump({"type": self.NAME, "next_run": self._next_run}, f)

    # -- lifecycle -------------------------------------------------------

    def create(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        if os.path.exists(self._meta_path()):
            raise StorageError(f"storage already exists: {self.dir}")
        self._next_run = 0
        self._save_meta()

    def init(self) -> None:
        if not os.path.exists(self._meta_path()):
            raise StorageError(f"not a storage dir: {self.dir}")
        self._next_run = int(self._load_meta()["next_run"])

    # -- per-run ---------------------------------------------------------

    def create_new_working_dir(self) -> str:
        run_dir = self.run_dir(self._next_run)
        os.makedirs(run_dir, exist_ok=False)
        self._next_run += 1
        self._save_meta()
        self._current_run_dir = run_dir
        return run_dir

    def record_new_trace(self, trace: SingleTrace) -> None:
        if self._current_run_dir is None:
            raise StorageError("no working dir; call create_new_working_dir first")
        with open(os.path.join(self._current_run_dir, "trace.json"), "w") as f:
            f.write(trace.to_json())

    def record_result(
        self,
        successful: bool,
        required_time: float,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> None:
        if self._current_run_dir is None:
            raise StorageError("no working dir; call create_new_working_dir first")
        with open(os.path.join(self._current_run_dir, "result.json"), "w") as f:
            json.dump(
                {
                    "successful": successful,
                    "required_time": required_time,
                    "metadata": metadata or {},
                },
                f,
            )

    # -- queries ---------------------------------------------------------

    def nr_stored_histories(self) -> int:
        # count only runs that completed (have a result)
        n = 0
        for i in range(self._next_run):
            if os.path.exists(os.path.join(self.run_dir(i), "result.json")):
                n = i + 1
        return n

    def _result(self, i: int) -> Dict[str, Any]:
        path = os.path.join(self.run_dir(i), "result.json")
        if not os.path.exists(path):
            raise StorageError(f"run {i:08x} has no result")
        with open(path) as f:
            return json.load(f)

    def get_stored_history(self, i: int) -> SingleTrace:
        path = os.path.join(self.run_dir(i), "trace.json")
        if not os.path.exists(path):
            raise StorageError(f"run {i:08x} has no trace")
        with open(path) as f:
            return SingleTrace.from_json(f.read())

    def is_successful(self, i: int) -> bool:
        return bool(self._result(i)["successful"])

    def get_required_time(self, i: int) -> float:
        return float(self._result(i)["required_time"])

    def get_metadata(self, i: int) -> Dict[str, Any]:
        return dict(self._result(i).get("metadata") or {})

    def search(self, prefix: List[str]) -> Iterable[int]:
        out = []
        for i in range(self.nr_stored_histories()):
            try:
                trace = self.get_stored_history(i)
            except StorageError:
                continue
            classes = [a.class_name() for a in trace]
            if classes[: len(prefix)] == list(prefix):
                out.append(i)
        return out
