"""History storage: per-experiment persistence of traces and results.

Capability parity with /root/reference/nmz/historystorage
(historystorage.go:22-61). The ``naive`` backend stores everything as JSON
under a storage directory; a ``mongodb``-style backend can decorate it when
a MongoDB client is available (reference: mongodb/mongodb.go) — gated, as
pymongo is not part of this image.
"""

from namazu_tpu.storage.base import HistoryStorage, StorageError, new_storage, load_storage
from namazu_tpu.storage.naive import NaiveStorage
from namazu_tpu.storage import mongodb as _mongodb  # registers when pymongo exists  # noqa: F401

__all__ = [
    "HistoryStorage",
    "StorageError",
    "new_storage",
    "load_storage",
    "NaiveStorage",
]
